"""Blockwise (flash) causal attention as Pallas TPU kernels.

The dense attention path materializes the [S, S] score matrix in HBM —
at long context that matrix, not the matmuls, is the bandwidth bill.
These kernels stream K/V blocks through VMEM with an online softmax,
so scores never leave the chip and HBM traffic is O(S * D) per head:
the single-chip counterpart of the cross-chip ring attention in
shockwave_tpu/parallel/ring_attention.py (which holds the same
online-softmax state while blocks rotate over ICI). Pattern follows the
public flash/blockwise-attention literature re-derived for Pallas.

Forward: one pallas_call, grid (batch*heads, q_blocks, k_blocks) with
the k dimension innermost ("arbitrary" semantics) accumulating into
VMEM scratch; causally-dead k blocks are skipped via pl.when, and only
diagonal-straddling blocks pay the iota/compare mask arithmetic (fully
live blocks take an unmasked branch). A measured ablation (see
results/flagship_profile_breakdown.md) shows the kernels are
MXU-bound — the matmul-only variant costs 43 of 49 ms at S=32k, D=64 —
so the elementwise trims here (mask split, scale fold, bf16 p for the
PV matmul) shave only the ~12% softmax share; the lever that actually
moves wall-clock is head dim 128, which fills the 128-wide systolic
array on both attention matmuls (QK^T contracts over D; PV emits D
output lanes) and measures 1.5x fwd / 2x bwd over D=64. The 1/sqrt(D)
score scale is folded into q once outside the kernels, and the PV
matmul takes p cast to the input dtype so it runs at the MXU's bf16
rate with f32 accumulation. The kernel emits the per-row log-sum-exp
(lse = m + log l) — a single stats array from which the backward
recomputes probabilities exactly (p = exp(s - lse)).

Backward: two Pallas kernels, mirroring the forward's blocking.
  * dk/dv: grid (batch*heads, k_blocks, q_blocks), q innermost;
    each k block accumulates its dk/dv across the live q blocks
    (q blocks strictly above the diagonal are skipped).
  * dq: grid (batch*heads, q_blocks, k_blocks), k innermost; each q
    block accumulates dq across its live k blocks.
Both recompute the score block from q/k and the saved lse — O(S * D)
HBM traffic, no [S, S] materialization — wired through jax.custom_vjp.
delta = rowsum(dout * out) is computed outside the kernels (XLA fuses
it) and passed in lane-replicated like lse.

Block sizes default to min(1024, S) for head dims up to 128, scaled
down proportionally for wider heads (the dkv backward's score-sized
VMEM temporaries plus the operand blocks overflow the 16 MiB scoped
budget at D=256 x 1024-wide blocks; the cap in flash_attention also
overrides explicitly passed block sizes). On a v5e at
[128 x 2048 x 64] bfloat16 the 1024-wide forward runs 3.7x faster
than 256-wide blocks (fewer grid steps; the per-block softmax state
updates and mask VPU work amortize over more MXU FLOPs). At S <= 1024
the whole row of scores lives in one VMEM block and the kernel
degenerates to a dense-in-VMEM attention that never spills scores to
HBM — strictly less HBM traffic than the XLA dense path.

Off-TPU (CPU tests) the kernels run in interpret mode; numerics match
the dense reference to float tolerance either way
(tests/test_flash_attention.py).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# jax renamed TPUCompilerParams -> CompilerParams across 0.4.x/0.5;
# bind whichever this install ships so both work.
_CompilerParams = getattr(pltpu, "CompilerParams", None) or getattr(
    pltpu, "TPUCompilerParams"
)

_NEG_INF = -1e30
_LANES = 128

# Default ceiling for the kernel block sizes; _resolve_block steps down
# to fit shorter or odd-length sequences. Measured on a real v5e at
# [128 x 2048 x 64] bfloat16: fwd 2.2 ms at 1024x1024 vs 8.1 ms at the
# old 256x256 default.
DEFAULT_BLOCK_Q = 1024
DEFAULT_BLOCK_K = 1024


def _use_interpret() -> bool:
    return jax.default_backend() != "tpu"


def _causal_mask_val(qi, ki, block_q, block_k, s, window=None):
    """Mask the causally-dead upper-triangle entries of a score block;
    with ``window`` also the entries more than window-1 positions in
    the past (row r attends cols (r-window, r])."""
    rows = qi * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0
    )
    cols = ki * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1
    )
    dead = cols > rows
    if window is not None:
        dead = dead | (cols < rows - (window - 1))
    return jnp.where(dead, _NEG_INF, s)


def _causal_block_split(
    qi, ki, block_q, block_k, causal, accumulate,
    window=None, in_bounds=None,
):
    """Emit the shared three-way classification of a score block as
    pl.when branches: fully live (call ``accumulate(masked=False)``, no
    mask arithmetic), straddling a boundary (``accumulate(masked=True)``),
    dead (no branch taken). Boundaries: the causal diagonal, and — when
    ``window`` is set — the trailing window edge (row r attends cols
    (r-window, r]). ``in_bounds`` ANDs in a validity predicate for
    windowed grids whose shrunk index range can step outside the array
    (the caller's index map clamps the DMA; the block must still be
    skipped). With ``causal=False`` (ring-attention hops where the
    whole K block is in the past) every block is fully live. All three
    kernels classify blocks identically; keeping the predicates in one
    place is what guarantees the gradients see the same live set as
    the forward."""
    if not causal:
        accumulate(masked=False)
        return
    first_row, last_row = qi * block_q, qi * block_q + block_q - 1
    first_col = ki * block_k
    last_col = ki * block_k + block_k - 1

    full = last_col <= first_row
    live = first_col <= last_row
    if window is not None:
        full = full & (first_col >= last_row - (window - 1))
        live = live & (last_col >= first_row - (window - 1))
    if in_bounds is not None:
        full = full & in_bounds
        live = live & in_bounds

    @pl.when(full)
    def _full():
        accumulate(masked=False)

    @pl.when(live & jnp.logical_not(full))
    def _straddle():
        accumulate(masked=True)


def _fwd_kernel(
    q_ref, k_ref, v_ref, o_ref, lse_ref,
    acc_ref, m_ref, l_ref, *, block_q, block_k, causal, window,
):
    qi = pl.program_id(1)
    j = pl.program_id(2)
    nk = pl.num_programs(2)
    in_bounds = None
    if window is None:
        ki = j
    else:
        ki = _window_k_start(qi, block_q, block_k, nk, j)
        in_bounds = ki >= 0

    @pl.when(j == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    def _accumulate(masked):
        q = q_ref[0]  # [block_q, D], pre-scaled by the caller
        k = k_ref[0]  # [block_k, D]
        v = v_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [block_q, block_k]
        if masked:
            s = _causal_mask_val(qi, ki, block_q, block_k, s, window)

        m_prev = m_ref[:, :1]  # [block_q, 1]
        l_prev = l_ref[:, :1]
        m_blk = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_blk)
        p = jnp.exp(s - m_new)  # [block_q, block_k]
        correction = jnp.exp(m_prev - m_new)
        l_new = l_prev * correction + jnp.sum(p, axis=1, keepdims=True)
        # p in the input dtype so the PV matmul runs at the MXU's bf16
        # rate (f32 accumulation via preferred_element_type); an f32 p
        # here ran the whole matmul at the much slower f32 rate.
        acc_ref[...] = acc_ref[...] * correction + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_ref[...] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[...] = jnp.broadcast_to(l_new, l_ref.shape)

    _causal_block_split(qi, ki, block_q, block_k, causal, _accumulate,
                        window=window, in_bounds=in_bounds)

    @pl.when(j == nk - 1)
    def _finish():
        l = jnp.maximum(l_ref[:, :1], 1e-30)
        o_ref[0] = (acc_ref[...] / l).astype(o_ref.dtype)
        # lse replicated across the 128-lane trailing dim (TPU tiling
        # requires the last two block dims be (8k, 128m)).
        lse_ref[0] = m_ref[...] + jnp.log(l_ref[...] + 1e-30)


def _window_k_start(qi, block_q, block_k, n_j, j):
    """Absolute k block visited at step j of the shrunk k walk for q
    block qi: the walk's last step (j = n_j - 1) lands on the causal
    diagonal block, earlier steps walk back through the window. May be
    negative — kernels classify those dead; index maps clamp the DMA.
    Used by BOTH the kernels and their BlockSpec index maps: the block
    a kernel classifies must be the block its map fetched."""
    return (qi * block_q + block_q - 1) // block_k - (n_j - 1) + j


def _window_q_start(ki, block_q, block_k, j):
    """Absolute q block visited at step j of the shrunk q walk for k
    block ki: starts at the block containing the diagonal and walks
    forward through the window's reach. May run past the sequence —
    kernels classify those dead; index maps clamp the DMA."""
    return (ki * block_k) // block_q + j


def _kv_row(b, num_q_heads, group):
    """Flat KV row for flat q row ``b`` (batch-major, head-minor
    [B * H] layout): query head h reads KV head h // group — grouped-
    query attention resolved entirely in the BlockSpec index maps, so
    shared KV heads are never materialized per query head in HBM."""
    if group == 1:
        return b
    kv_heads = num_q_heads // group
    return (b // num_q_heads) * kv_heads + (b % num_q_heads) // group


def _window_blocks(window, block_a, block_b, n_b):
    """Number of block_b-sized blocks a shrunk windowed grid must walk
    per block_a-sized outer block: the span block_a + window - 1 plus
    one block of alignment slop, clamped to the full range."""
    return min(n_b, (block_a + window - 2) // block_b + 2)


def _flash_fwd_flat(q, k, v, block_q, block_k, causal, window,
                    num_q_heads, interpret):
    """q: [B*H, Sq, D], k/v: [B*Hkv, Sk, D] ->
    (out [B*H, Sq, D], lse [B*H, Sq, LANES]). causal requires Sq == Sk
    (positions are global block offsets); non-causal attends q to the
    whole K/V sequence (a ring hop whose K block is entirely in the
    past). ``window`` (causal only) shrinks the k grid to the blocks
    the sliding window can reach — O(S * window) compute AND block DMA
    (a pl.when skip alone would still fetch every K/V block). Hkv may
    divide H (grouped-query attention); the KV row is resolved by the
    index maps via _kv_row."""
    BH, Sq, D = q.shape
    Sk = k.shape[1]
    group = BH // k.shape[0]
    # Fold the 1/sqrt(D) score scale into q once (O(S*D)) instead of
    # multiplying the S^2 score matrix inside the kernel. The multiply
    # runs in f32; casting back to a bf16 q costs at most one extra
    # half-ulp rounding (exact when the scale is a power of two, i.e.
    # power-of-4 head dims; for D=128 it is not) — bounded by bf16's
    # own representation error and covered by the D=128 bf16-vs-dense
    # test in tests/test_flash_attention.py.
    scale = 1.0 / float(np.sqrt(D))
    q = (q.astype(jnp.float32) * scale).astype(q.dtype)
    nk = Sk // block_k
    if window is None:
        nj = nk

        def kmap(b, i, j):
            return (_kv_row(b, num_q_heads, group), j, 0)
    else:
        nj = _window_blocks(window, block_q, block_k, nk)

        def kmap(b, i, j):
            ki = _window_k_start(i, block_q, block_k, nj, j)
            return (_kv_row(b, num_q_heads, group),
                    jnp.clip(ki, 0, nk - 1), 0)

    grid = (BH, Sq // block_q, nj)
    kernel = functools.partial(
        _fwd_kernel, block_q=block_q, block_k=block_k, causal=causal,
        window=window,
    )
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, D), kmap),
            pl.BlockSpec((1, block_k, D), kmap),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, _LANES), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BH, Sq, D), q.dtype),
            jax.ShapeDtypeStruct((BH, Sq, _LANES), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, D), jnp.float32),
            pltpu.VMEM((block_q, _LANES), jnp.float32),
            pltpu.VMEM((block_q, _LANES), jnp.float32),
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(q, k, v)
    return out, lse


def _dkv_kernel(
    q_ref, k_ref, v_ref, g_ref, lse_ref, delta_ref,
    dk_ref, dv_ref, dk_acc, dv_acc, *, block_q, block_k, causal,
    window, num_q_blocks,
):
    ki = pl.program_id(1)
    j = pl.program_id(2)
    nq = pl.num_programs(2)
    in_bounds = None
    if window is None:
        qi = j
    else:
        qi = _window_q_start(ki, block_q, block_k, j)
        in_bounds = qi <= num_q_blocks - 1

    @pl.when(j == 0)
    def _init():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    def _accumulate(masked):
        q = q_ref[0]  # [block_q, D], pre-scaled by the caller; so
        # dk = ds^T @ q here IS the true scale * ds^T @ q_orig.
        k = k_ref[0]  # [block_k, D]
        v = v_ref[0]
        g = g_ref[0]  # dout block, [block_q, D]
        lse = lse_ref[0][:, :1]  # [block_q, 1]
        delta = delta_ref[0][:, :1]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        if masked:
            s = _causal_mask_val(qi, ki, block_q, block_k, s, window)
        p = jnp.exp(s - lse)  # [block_q, block_k]; dead entries -> 0
        pt = p.astype(g.dtype)
        dv_acc[...] += jax.lax.dot_general(
            pt, g, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # p^T @ g -> [block_k, D]
        dp = jax.lax.dot_general(
            g, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [block_q, block_k]
        ds = (p * (dp - delta)).astype(q.dtype)
        dk_acc[...] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # ds^T @ q -> [block_k, D]

    _causal_block_split(qi, ki, block_q, block_k, causal, _accumulate,
                        window=window, in_bounds=in_bounds)

    @pl.when(j == nq - 1)
    def _finish():
        dk_ref[0] = dk_acc[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[...].astype(dv_ref.dtype)


def _dq_kernel(
    q_ref, k_ref, v_ref, g_ref, lse_ref, delta_ref,
    dq_ref, dq_acc, *, block_q, block_k, scale, causal, window,
):
    qi = pl.program_id(1)
    j = pl.program_id(2)
    nk = pl.num_programs(2)
    in_bounds = None
    if window is None:
        ki = j
    else:
        ki = _window_k_start(qi, block_q, block_k, nk, j)
        in_bounds = ki >= 0

    @pl.when(j == 0)
    def _init():
        dq_acc[...] = jnp.zeros_like(dq_acc)

    def _accumulate(masked):
        q = q_ref[0]  # pre-scaled by the caller (for the s recompute)
        k = k_ref[0]
        v = v_ref[0]
        g = g_ref[0]
        lse = lse_ref[0][:, :1]
        delta = delta_ref[0][:, :1]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        if masked:
            s = _causal_mask_val(qi, ki, block_q, block_k, s, window)
        p = jnp.exp(s - lse)
        dp = jax.lax.dot_general(
            g, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = (p * (dp - delta)).astype(k.dtype)
        dq_acc[...] += jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # ds @ k -> [block_q, D]

    _causal_block_split(qi, ki, block_q, block_k, causal, _accumulate,
                        window=window, in_bounds=in_bounds)

    @pl.when(j == nk - 1)
    def _finish():
        # The kernel accumulates ds @ k with the unscaled ds; the
        # 1/sqrt(D) lands here once per q block instead of on every
        # S^2 score element.
        dq_ref[0] = (dq_acc[...] * scale).astype(dq_ref.dtype)


def _flash_bwd_flat(
    q, k, v, out, lse, g, block_q, block_k, causal, window,
    num_q_heads, interpret, g_lse=None,
):
    """Pallas flash backward; O(S * D) HBM traffic per head. g_lse is
    the optional cotangent of the returned lse (ring-attention merges
    differentiate through it): d s = p * (dp - delta + g_lse) row-wise,
    so it folds into the existing delta input as delta - g_lse — the
    kernels need no extra operand."""
    BH, Sq, D = q.shape
    Sk = k.shape[1]
    group = BH // k.shape[0]
    scale = 1.0 / float(np.sqrt(D))
    # Same fold as the forward: q carries the score scale, so the
    # kernels' s recompute needs no S^2 multiply, dk = ds^T @ q_scaled
    # is already the true gradient, and dq picks the scale up once at
    # its accumulator finish.
    q = (q.astype(jnp.float32) * scale).astype(q.dtype)
    # delta = rowsum(dout * out), lane-replicated like lse; XLA fuses
    # the product-reduce-broadcast into one cheap pass.
    delta = jnp.sum(
        g.astype(jnp.float32) * out.astype(jnp.float32), axis=-1
    )
    if g_lse is not None:
        # Sum over the replicated lane dim: however the caller consumed
        # the lane-replicated lse, the total row cotangent is the lane
        # sum (a [:, :, 0] slice scatters it all into lane 0).
        delta = delta - jnp.sum(g_lse.astype(jnp.float32), axis=-1)
    delta = jnp.broadcast_to(delta[..., None], (BH, Sq, _LANES))
    # Cotangent in the input dtype: for bf16 models the p/ds matmul
    # operands are bf16 with f32 accumulation — standard flash practice,
    # a deliberate precision/bandwidth tradeoff vs keeping g in f32
    # (guarded by test_bf16_gradients_match_dense).
    g = g.astype(q.dtype)

    nq = Sq // block_q
    nk = Sk // block_k
    qspec = pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0))
    sspec = pl.BlockSpec((1, block_q, _LANES), lambda b, i, j: (b, i, 0))
    # dkv grid: k outer, q inner -> q-indexed blocks vary with the
    # *inner* index j, k-indexed with the outer i. Windowed grids walk
    # only the q blocks whose window reaches the k block (same shrink
    # as the forward's k walk; index maps clamp, in_bounds skips).
    if window is None:
        njq = nq
        qmap_kv = lambda b, i, j: (b, j, 0)  # noqa: E731
    else:
        njq = _window_blocks(window, block_k, block_q, nq)

        def qmap_kv(b, i, j):
            qi = _window_q_start(i, block_q, block_k, j)
            return (b, jnp.clip(qi, 0, nq - 1), 0)

    qspec_kv = pl.BlockSpec((1, block_q, D), qmap_kv)
    sspec_kv = pl.BlockSpec((1, block_q, _LANES), qmap_kv)
    kspec_kv = pl.BlockSpec(
        (1, block_k, D),
        lambda b, i, j: (_kv_row(b, num_q_heads, group), i, 0),
    )
    # dk/dv are emitted per QUERY head (grid dim 0 runs over B*H, and
    # the sequential-revisit ordering Pallas relies on would break if
    # several q heads wrote the same KV row); the group reduction to
    # [B*Hkv] happens below in plain XLA.
    dspec_kv = pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, i, 0))

    dk, dv = pl.pallas_call(
        functools.partial(
            _dkv_kernel, block_q=block_q, block_k=block_k, causal=causal,
            window=window, num_q_blocks=nq,
        ),
        grid=(BH, nk, njq),
        in_specs=[
            qspec_kv, kspec_kv, kspec_kv, qspec_kv, sspec_kv, sspec_kv
        ],
        out_specs=[dspec_kv, dspec_kv],
        out_shape=[
            jax.ShapeDtypeStruct((BH, Sk, D), k.dtype),
            jax.ShapeDtypeStruct((BH, Sk, D), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, D), jnp.float32),
            pltpu.VMEM((block_k, D), jnp.float32),
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(q, k, v, g, lse, delta)
    if group > 1:
        # Sum the per-q-head KV gradients over each group (f32 to keep
        # the reduction exact, then back to the KV dtype). Heads are
        # minor in the flat layout and groups are contiguous in h.
        B = BH // num_q_heads
        kv_heads = num_q_heads // group

        def group_sum(d, dtype):
            d = d.astype(jnp.float32)
            d = d.reshape(B, kv_heads, group, Sk, D).sum(axis=2)
            return d.reshape(B * kv_heads, Sk, D).astype(dtype)

        dk = group_sum(dk, k.dtype)
        dv = group_sum(dv, v.dtype)

    if window is None:
        njk = nk

        def kmap(b, i, j):
            return (_kv_row(b, num_q_heads, group), j, 0)
    else:
        njk = _window_blocks(window, block_q, block_k, nk)

        def kmap(b, i, j):
            ki = _window_k_start(i, block_q, block_k, njk, j)
            return (_kv_row(b, num_q_heads, group),
                    jnp.clip(ki, 0, nk - 1), 0)

    kspec = pl.BlockSpec((1, block_k, D), kmap)
    dq = pl.pallas_call(
        functools.partial(
            _dq_kernel, block_q=block_q, block_k=block_k, scale=scale,
            causal=causal, window=window,
        ),
        grid=(BH, Sq // block_q, njk),
        in_specs=[qspec, kspec, kspec, qspec, sspec, sspec],
        out_specs=qspec,
        out_shape=jax.ShapeDtypeStruct((BH, Sq, D), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, D), jnp.float32)],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(q, k, v, g, lse, delta)
    return dq, dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8))
def _flash_flat_lse(q, k, v, block_q, block_k, causal, window,
                    num_q_heads, interpret):
    return _flash_fwd_flat(
        q, k, v, block_q, block_k, causal, window, num_q_heads, interpret
    )


def _flash_flat_lse_fwd(q, k, v, block_q, block_k, causal, window,
                        num_q_heads, interpret):
    out, lse = _flash_fwd_flat(
        q, k, v, block_q, block_k, causal, window, num_q_heads, interpret
    )
    return (out, lse), (q, k, v, out, lse)


def _flash_flat_lse_bwd(block_q, block_k, causal, window, num_q_heads,
                        interpret, res, gs):
    q, k, v, out, lse = res
    g_out, g_lse = gs
    dq, dk, dv = _flash_bwd_flat(
        q, k, v, out, lse, g_out, block_q, block_k, causal, window,
        num_q_heads, interpret, g_lse=g_lse,
    )
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


_flash_flat_lse.defvjp(_flash_flat_lse_fwd, _flash_flat_lse_bwd)


def _block_cap(head_dim: int) -> int:
    """VMEM-aware block ceiling: the dkv backward holds ~4
    [block_q, block_k] f32 score-sized temporaries plus the operand
    blocks, which at D=256 and 1024-wide blocks overflows the 16 MiB
    scoped-VMEM budget (by 36 KiB, measured on v5e). Scale the ceiling
    down with the head dim; D <= 128 keeps the measured-fastest 1024.
    Rounded down to a lane multiple so non-128-multiple head dims
    (e.g. D=192) yield the largest lane-aligned block under the
    budget rather than leaning on _resolve_block's step-down."""
    cap = 1024 * 128 // max(head_dim, 128)
    return max(_LANES, cap // _LANES * _LANES)


def _resolve_block(requested: int, seq_len: int) -> int:
    """Clamp the requested block to the sequence; when the clamped
    block doesn't divide a lane-aligned sequence, step down in lane
    multiples (so e.g. S=384 runs 128-wide blocks under the 1024
    default instead of falling back to dense)."""
    b = min(requested, seq_len)
    if seq_len % b and seq_len % _LANES == 0:
        b = max(_LANES, (b // _LANES) * _LANES)
        while seq_len % b:
            b -= _LANES
    if seq_len % b or b % 8:
        raise ValueError(
            f"seq len {seq_len} does not tile into valid blocks "
            f"(requested {requested}; see flash_tiles for the "
            "dense-fallback gate)"
        )
    return b


def flash_tiles(seq_len: int) -> bool:
    """Whether a sequence tiles into lane-aligned kernel blocks
    (flash_attention steps its block size down to 128 as needed, so
    any multiple of 128 qualifies). Callers that want a dense fallback
    instead of the ValueError below gate on this
    (models/transformer.py, parallel/ulysses.py)."""
    return seq_len >= _LANES and seq_len % _LANES == 0


def flash_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    block_q: int = DEFAULT_BLOCK_Q,
    block_k: int = DEFAULT_BLOCK_K,
    window: int | None = None,
) -> jnp.ndarray:
    """Causal flash attention; [B, S, H, D] in and out, differentiable.

    Same contract as
    :func:`shockwave_tpu.parallel.ring_attention.dense_causal_attention`.
    Sequence length must divide by the block sizes (callers fall back to
    the dense path otherwise — see models/transformer.py).

    ``window`` restricts each token to its ``window`` most recent
    positions (itself included — Mistral-style sliding-window
    attention). The kernels walk a shrunk k grid, so compute and K/V
    block DMA are O(S * window) instead of O(S^2): long-context cost
    becomes linear in S at fixed window.

    k/v may carry FEWER heads than q (grouped-query attention): with
    Hkv dividing H, query head h attends KV head h // (H // Hkv). The
    sharing is resolved in the kernels' index maps — the KV tensors
    are never repeated per query head in HBM.
    """
    B, S, H, D = q.shape
    Hkv = _check_kv_heads(H, k.shape[2], v.shape[2])
    # The cap also overrides explicitly passed block sizes (VMEM
    # correctness beats caller preference).
    cap = _block_cap(D)
    block_q = _resolve_block(min(block_q, cap), S)
    block_k = _resolve_block(min(block_k, cap), S)
    window = _resolve_window(window, S)

    def flat(x, h):
        return x.transpose(0, 2, 1, 3).reshape(B * h, S, D)

    # Single custom_vjp shared with flash_attention_lse (the discarded
    # lse's zero cotangent folds into the backward's delta for free) —
    # one backward implementation to keep correct, not two.
    out, _ = _flash_flat_lse(
        flat(q, H), flat(k, Hkv), flat(v, Hkv), block_q, block_k, True,
        window, H, _use_interpret(),
    )
    return out.reshape(B, H, S, D).transpose(0, 2, 1, 3)


def _check_kv_heads(num_q_heads, k_heads, v_heads):
    if k_heads != v_heads:
        raise ValueError(
            f"k and v head counts differ: {k_heads} vs {v_heads}"
        )
    if num_q_heads % k_heads:
        raise ValueError(
            f"q heads ({num_q_heads}) must be a multiple of kv heads "
            f"({k_heads})"
        )
    return k_heads


def _resolve_window(window, seq_len):
    """Validate the sliding window; a window covering the whole
    sequence is plain causal attention (and cheaper without the
    shrunk-grid indexing)."""
    if window is None:
        return None
    if window < 1:
        raise ValueError(f"window must be >= 1, got {window}")
    return None if window >= seq_len else int(window)


def flash_attention_lse(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    block_q: int = DEFAULT_BLOCK_Q,
    block_k: int = DEFAULT_BLOCK_K,
    causal: bool = True,
    window: int | None = None,
) -> tuple:
    """Flash attention returning (out [B, Sq, H, D], lse [B, H, Sq]).

    The per-row log-sum-exp lets callers merge partial attention
    results over disjoint key sets exactly (the ring-attention hop
    merge: out_total = sum_i out_i * exp(lse_i - logaddexp_i lse_i)) —
    gradients flow through both outputs. causal=False attends every
    query to the whole K/V sequence (a ring hop whose keys are all in
    the past); it is also the only mode where Sk may differ from Sq.
    ``window`` as in :func:`flash_attention` (causal only).
    """
    B, Sq, H, D = q.shape
    Sk = k.shape[1]
    Hkv = _check_kv_heads(H, k.shape[2], v.shape[2])
    if causal and Sq != Sk:
        raise ValueError(
            f"causal flash needs matching q/k lengths, got {Sq} vs {Sk}"
        )
    if window is not None and not causal:
        raise ValueError("window requires causal attention")
    cap = _block_cap(D)
    block_q = _resolve_block(min(block_q, cap), Sq)
    block_k = _resolve_block(min(block_k, cap), Sk)
    window = _resolve_window(window, Sq)

    def flat(x, s, h):
        return x.transpose(0, 2, 1, 3).reshape(B * h, s, D)

    out, lse = _flash_flat_lse(
        flat(q, Sq, H), flat(k, Sk, Hkv), flat(v, Sk, Hkv), block_q,
        block_k, causal, window, H, _use_interpret(),
    )
    out = out.reshape(B, H, Sq, D).transpose(0, 2, 1, 3)
    lse = lse[:, :, 0].reshape(B, H, Sq)
    return out, lse
