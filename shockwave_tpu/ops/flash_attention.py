"""Blockwise (flash) causal attention as a Pallas TPU kernel.

The dense attention path materializes the [S, S] score matrix in HBM —
at long context that matrix, not the matmuls, is the bandwidth bill.
This kernel streams K/V blocks through VMEM with an online softmax
(running max + normalizer), so scores never leave the chip and HBM
traffic is O(S * D) per head: the single-chip counterpart of the
cross-chip ring attention in shockwave_tpu/parallel/ring_attention.py
(which holds the same online-softmax state while blocks rotate over
ICI). Pattern follows the public flash/blockwise-attention literature
re-derived for Pallas.

Forward: one pallas_call, grid (batch*heads, q_blocks, k_blocks) with
the k dimension innermost ("arbitrary" semantics) accumulating into
VMEM scratch; causally-dead k blocks are skipped via pl.when. The
kernel also emits the per-row softmax stats (running max m, normalizer
l).

Backward: the standard flash backward recurrence in plain JAX, one
lax.scan over K/V blocks re-computing probabilities from the saved
stats — O(S * block) memory, no [S, S] materialization — wired through
jax.custom_vjp so the kernel trains.

Off-TPU (CPU tests) the kernel runs in interpret mode; numerics match
the dense reference to float tolerance either way
(tests/test_flash_attention.py).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30
_LANES = 128

# Default kernel block sizes. Measured on a real v5e at
# [64 heads x 4096 x 64] bfloat16: 256x256 runs the forward+backward
# 1.8x faster than 128x128 (fewer grid steps amortize the per-block
# softmax state updates; 512-wide blocks gained nothing further).
DEFAULT_BLOCK_Q = 256
DEFAULT_BLOCK_K = 256


def _use_interpret() -> bool:
    return jax.default_backend() != "tpu"


def _fwd_kernel(
    q_ref, k_ref, v_ref, o_ref, m_out_ref, l_out_ref,
    acc_ref, m_ref, l_ref, *, block_q, block_k, scale,
):
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    # Causal: k block strictly above the diagonal contributes nothing.
    @pl.when(ki * block_k <= qi * block_q + block_q - 1)
    def _body():
        q = q_ref[0]  # [block_q, D]
        k = k_ref[0]  # [block_k, D]
        v = v_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale  # [block_q, block_k]
        rows = qi * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0
        )
        cols = ki * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1
        )
        s = jnp.where(cols > rows, _NEG_INF, s)

        m_prev = m_ref[:, :1]  # [block_q, 1]
        l_prev = l_ref[:, :1]
        m_blk = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_blk)
        p = jnp.exp(s - m_new)  # [block_q, block_k]
        correction = jnp.exp(m_prev - m_new)
        l_new = l_prev * correction + jnp.sum(p, axis=1, keepdims=True)
        acc_ref[...] = acc_ref[...] * correction + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_ref[...] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[...] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(ki == nk - 1)
    def _finish():
        l = l_ref[:, :1]
        o_ref[0] = (acc_ref[...] / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)
        # Stats replicated across the 128-lane trailing dim (TPU tiling
        # requires the last two block dims be (8k, 128m)); the host
        # wrapper slices lane 0.
        m_out_ref[0] = m_ref[...]
        l_out_ref[0] = l_ref[...]


def _flash_fwd_flat(q, k, v, block_q, block_k, interpret):
    """q/k/v: [BH, S, D] -> (out [BH, S, D], m [BH, S], l [BH, S])."""
    BH, S, D = q.shape
    scale = 1.0 / float(np.sqrt(D))
    grid = (BH, S // block_q, S // block_k)
    kernel = functools.partial(
        _fwd_kernel, block_q=block_q, block_k=block_k, scale=scale
    )
    out, m3, l3 = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, _LANES), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, _LANES), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BH, S, D), q.dtype),
            jax.ShapeDtypeStruct((BH, S, _LANES), jnp.float32),
            jax.ShapeDtypeStruct((BH, S, _LANES), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, D), jnp.float32),
            pltpu.VMEM((block_q, _LANES), jnp.float32),
            pltpu.VMEM((block_q, _LANES), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(q, k, v)
    return out, m3[..., 0], l3[..., 0]


def _flash_bwd_flat(q, k, v, out, m, l, g, block_k, scale):
    """Flash backward: scan over K/V blocks, probabilities recomputed
    from the saved stats; O(S * block_k) memory."""
    BH, S, D = q.shape
    nk = S // block_k
    delta = jnp.sum(g * out, axis=-1)  # [BH, S]
    rows = jnp.arange(S)
    k_blocks = k.reshape(BH, nk, block_k, D).transpose(1, 0, 2, 3)
    v_blocks = v.reshape(BH, nk, block_k, D).transpose(1, 0, 2, 3)

    def one_block(dq, inputs):
        j, k_j, v_j = inputs
        # Scores recomputed in float32 (bfloat16 inputs would otherwise
        # quantize the exp argument); matmul inputs stay in their dtype.
        s = jnp.einsum(
            "bsd,btd->bst", q, k_j, preferred_element_type=jnp.float32
        ) * scale  # [BH, S, block_k]
        cols = j * block_k + jnp.arange(block_k)
        dead = cols[None, :] > rows[:, None]  # [S, block_k]
        p = jnp.where(
            dead[None], 0.0, jnp.exp(s - m[..., None])
        ) / jnp.maximum(l[..., None], 1e-30)
        dv_j = jnp.einsum("bst,bsd->btd", p, g)
        dp = jnp.einsum("bsd,btd->bst", g, v_j)
        ds = p * (dp - delta[..., None]) * scale
        dk_j = jnp.einsum("bst,bsd->btd", ds, q)
        dq = dq + jnp.einsum("bst,btd->bsd", ds, k_j)
        return dq, (dk_j, dv_j)

    dq, (dk_b, dv_b) = jax.lax.scan(
        one_block,
        jnp.zeros(q.shape, jnp.float32),
        (jnp.arange(nk), k_blocks, v_blocks),
    )
    dk = dk_b.transpose(1, 0, 2, 3).reshape(BH, S, D)
    dv = dv_b.transpose(1, 0, 2, 3).reshape(BH, S, D)
    return dq, dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _flash_flat(q, k, v, block_q, block_k, interpret):
    out, _, _ = _flash_fwd_flat(q, k, v, block_q, block_k, interpret)
    return out


def _flash_flat_fwd(q, k, v, block_q, block_k, interpret):
    out, m, l = _flash_fwd_flat(q, k, v, block_q, block_k, interpret)
    return out, (q, k, v, out, m, l)


def _flash_flat_bwd(block_q, block_k, interpret, res, g):
    q, k, v, out, m, l = res
    scale = 1.0 / float(np.sqrt(q.shape[-1]))
    dq, dk, dv = _flash_bwd_flat(
        q, k, v, out, m, l, g.astype(jnp.float32), block_k, scale
    )
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


_flash_flat.defvjp(_flash_flat_fwd, _flash_flat_bwd)


def _resolve_block(requested: int, seq_len: int) -> int:
    """Clamp the requested block to the sequence; when the clamped
    block doesn't divide a lane-aligned sequence, step down in lane
    multiples (so e.g. S=384 runs 128-wide blocks under the 256
    default instead of falling back to dense)."""
    b = min(requested, seq_len)
    if seq_len % b and seq_len % _LANES == 0:
        b = max(_LANES, (b // _LANES) * _LANES)
        while seq_len % b:
            b -= _LANES
    if seq_len % b or b % 8:
        raise ValueError(
            f"seq len {seq_len} does not tile into valid blocks "
            f"(requested {requested}; see flash_tiles for the "
            "dense-fallback gate)"
        )
    return b


def flash_tiles(seq_len: int) -> bool:
    """Whether a sequence tiles into lane-aligned kernel blocks
    (flash_attention steps its block size down to 128 as needed, so
    any multiple of 128 qualifies). Callers that want a dense fallback
    instead of the ValueError below gate on this
    (models/transformer.py, parallel/ulysses.py)."""
    return seq_len >= _LANES and seq_len % _LANES == 0


def flash_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    block_q: int = DEFAULT_BLOCK_Q,
    block_k: int = DEFAULT_BLOCK_K,
) -> jnp.ndarray:
    """Causal flash attention; [B, S, H, D] in and out, differentiable.

    Same contract as
    :func:`shockwave_tpu.parallel.ring_attention.dense_causal_attention`.
    Sequence length must divide by the block sizes (callers fall back to
    the dense path otherwise — see models/transformer.py).
    """
    B, S, H, D = q.shape
    block_q = _resolve_block(block_q, S)
    block_k = _resolve_block(block_k, S)

    def flat(x):
        return x.transpose(0, 2, 1, 3).reshape(B * H, S, D)

    out = _flash_flat(
        flat(q), flat(k), flat(v), block_q, block_k, _use_interpret()
    )
    return out.reshape(B, H, S, D).transpose(0, 2, 1, 3)
