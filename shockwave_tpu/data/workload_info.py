"""Static facts about the workload model families.

The scheduler reasons about epochs (dataset passes) and batch-size scaling
limits per model family. These constants mirror the reference's tables
(reference: scheduler/scheduler.py:64-90) so that traces written for the
reference produce identical epoch math here.
"""

# Samples per epoch for each model family (dataset sizes).
DATASET_SIZES = {
    "ResNet-18": 50000,  # cifar10
    "ResNet-50": 100000,  # imagenet subset
    "Transformer": 10000,  # multi30k
    "LM": 59675,  # wikitext2
    "Recommendation": 117907,  # ml-20m
    "CycleGAN": 6287,  # monet2photo
    "A3C": 4,  # no dataset
}

# Largest batch size with profiled throughputs (scaling ceiling).
MAX_BATCH_SIZES = {
    "ResNet-18": 256,
    "ResNet-50": 128,
    "Transformer": 128,
    "LM": 80,
    "Recommendation": 8192,
}

# Smallest profiled batch size (scale-down floor for Accordion).
MIN_BATCH_SIZES = {
    "ResNet-18": 16,
    "ResNet-50": 16,
    "Transformer": 16,
    "LM": 5,
    "Recommendation": 512,
}


def parse_job_type(job_type: str):
    """Split ``"Model (batch size N)"`` into ``(model, batch_size)`` — the
    one place the job_type string encoding is interpreted."""
    return job_type[: job_type.find(" ")], int(
        job_type[job_type.rfind(" ") + 1 : -1]
    )


def steps_per_epoch(model: str, batch_size: int) -> int:
    """Number of optimizer steps in one epoch: ceil(dataset / batch)."""
    size = DATASET_SIZES[model]
    return -(-size // int(batch_size))


def num_epochs(model: str, batch_size: int, num_steps: int) -> int:
    """Epochs covered by ``num_steps`` steps at ``batch_size``
    (reference: scheduler/scheduler.py:3490-3496)."""
    spe = steps_per_epoch(model, batch_size)
    return -(-int(num_steps) // spe)


def total_steps_for_epochs(model: str, batch_size: int, epochs: int) -> int:
    """Steps needed for ``epochs`` full epochs
    (reference: scheduler/scheduler.py:3498-3503)."""
    return int(epochs) * steps_per_epoch(model, batch_size)
