"""Time-varying accelerator price schedules.

Capability parity with the reference's spot-price machinery
(reference: scheduler/utils.py:300-420 reads AWS/Azure price logs and
resolves the latest price at the current simulation time; the log data
itself is stripped from the reference snapshot). Here the same
capability takes a plain JSON schedule:

    {"v100": [[0, 0.74], [3600, 0.69], ...],   # [time_s, $/hr] pairs
     "p100": 0.43}                              # or a constant

``latest_price`` resolves the most recent price at or before ``t``
(the first listed price applies before the first timestamp).
"""

from __future__ import annotations

import bisect
import json
from typing import Dict, Union

PriceSchedule = Union[float, list]


def read_price_schedules(path: str) -> Dict[str, PriceSchedule]:
    with open(path) as f:
        schedules = json.load(f)
    for worker_type, schedule in schedules.items():
        if isinstance(schedule, list):
            if not schedule:
                raise ValueError(f"empty price schedule for {worker_type!r}")
            schedules[worker_type] = sorted(
                [[float(t), float(p)] for t, p in schedule]
            )
    return schedules


def latest_price(
    schedules: Dict[str, PriceSchedule], worker_type: str, t: float
) -> float:
    schedule = schedules.get(worker_type, 0.0)
    if not isinstance(schedule, list):
        return float(schedule)
    times = [entry[0] for entry in schedule]
    idx = bisect.bisect_right(times, t) - 1
    return float(schedule[max(idx, 0)][1])
