"""Throughput-oracle readers.

Oracle JSON format (reference: scheduler/utils.py:456-476):

  {worker_type: {"('<job_type>', <scale_factor>)":
      {"null": isolated_tput,
       "('<other_job_type>', <sf>)": [tput_self, tput_other]}}}

Keys are stringified ``(job_type, scale_factor)`` tuples; ``"null"`` holds
the isolated throughput (steps/s), other keys hold co-located throughputs
for Gavel-style packing.
"""

from __future__ import annotations

import json
from typing import Dict, Optional, Tuple

JobTypeKey = Tuple[str, int]


def _parse_job_type_key(s: str) -> Optional[JobTypeKey]:
    """Parse "('LM (batch size 10)', 2)" -> ("LM (batch size 10)", 2)."""
    s = s.strip()
    if not (s.startswith("(") and s.endswith(")")):
        return None
    body = s[1:-1]
    comma = body.rfind(",")
    if comma < 0:
        return None
    job_type = body[:comma].strip()
    if job_type[0] in "'\"" and job_type[-1] == job_type[0]:
        job_type = job_type[1:-1]
    return (job_type, int(body[comma + 1 :].strip()))


def read_throughputs(file_name: str) -> Dict[str, Dict[JobTypeKey, dict]]:
    """Read an oracle throughputs JSON into nested dicts keyed by
    (job_type, scale_factor) tuples; colocated entries keep the "null" key."""
    with open(file_name, "r") as f:
        raw = json.load(f)
    parsed: Dict[str, Dict[JobTypeKey, dict]] = {}
    for worker_type, per_type in raw.items():
        parsed[worker_type] = {}
        for job_type_str, entries in per_type.items():
            key = _parse_job_type_key(job_type_str)
            if key is None:
                raise ValueError(f"Bad job-type key: {job_type_str!r}")
            converted = {}
            for other_str, value in entries.items():
                if other_str == "null":
                    converted["null"] = value
                else:
                    other_key = _parse_job_type_key(other_str)
                    if other_key is None:
                        raise ValueError(f"Bad job-type key: {other_str!r}")
                    converted[other_key] = value
            parsed[worker_type][key] = converted
    return parsed


def stringify_throughputs(throughputs: Dict[str, Dict[JobTypeKey, dict]]) -> dict:
    """Inverse of :func:`read_throughputs` for writing oracle files."""
    out: dict = {}
    for worker_type, per_type in throughputs.items():
        out[worker_type] = {}
        for key, entries in per_type.items():
            out[worker_type][str(key)] = {
                ("null" if other == "null" else str(other)): v
                for other, v in entries.items()
            }
    return out
