"""Per-job epoch-profile synthesis.

The Shockwave planner consumes a per-job *epoch profile*: the batch size and
wall-clock duration of every epoch, plus totals (schema from reference:
scheduler/job_metadata.py:14-23). The reference ships these as per-trace
pickles which are stripped from its public snapshot, so this module
regenerates them from first principles:

  * the epoch count comes from the trace's total step count and initial
    batch size (epochs = ceil(steps / ceil(dataset / bs)));
  * the batch-size schedule comes from the job's dynamic-adaptation mode
    (static / accordion / gns, see :mod:`shockwave_tpu.data.bs_patterns`);
  * each epoch's duration is steps-in-epoch / oracle throughput at that
    epoch's batch size on the reference worker type.

``mem_every_epoch`` / ``util_every_epoch`` are carried for schema parity but
never read by the planner (reference: job_metadata.py:34-40 stores them and
no consumer exists).
"""

from __future__ import annotations

import os
import pickle
from typing import Dict, List, Sequence

from shockwave_tpu.core.job import Job
from shockwave_tpu.data import bs_patterns
from shockwave_tpu.data.workload_info import num_epochs as epochs_for_steps
from shockwave_tpu.data.workload_info import steps_per_epoch

Profile = Dict[str, object]


def _isolated_throughput(
    throughputs: dict, worker_type: str, model: str, bs: int, scale_factor: int
):
    key = ("%s (batch size %d)" % (model, bs), scale_factor)
    entry = throughputs[worker_type].get(key)
    if entry is not None:
        return entry["null"]
    return None


def synthesize_profile(
    job: Job,
    throughputs: dict,
    worker_type: str = "v100",
) -> Profile:
    """Build one job's epoch profile from the throughput oracle."""
    model = job.model
    initial_bs = job.batch_size
    total_epochs = epochs_for_steps(model, initial_bs, job.total_steps)
    bs_every_epoch = bs_patterns.pattern_for_mode(
        job.mode, job.job_type, initial_bs, total_epochs, job.scale_factor
    )

    base_tput = _isolated_throughput(
        throughputs, worker_type, model, initial_bs, job.scale_factor
    )
    if base_tput is None:
        raise KeyError(
            f"No oracle throughput for {job.job_type!r} x{job.scale_factor} "
            f"on {worker_type}"
        )

    duration_every_epoch: List[float] = []
    for bs in bs_every_epoch:
        tput = _isolated_throughput(throughputs, worker_type, model, bs, job.scale_factor)
        if tput is None or tput <= 0:
            # Unprofiled batch size: assume constant samples/s, i.e. the
            # steps/s throughput shrinks proportionally with batch growth.
            tput = base_tput * (initial_bs / bs)
        duration_every_epoch.append(steps_per_epoch(model, bs) / tput)

    return {
        "num_epochs": total_epochs,
        "num_samples_per_epoch": steps_per_epoch(model, initial_bs) * initial_bs,
        "scale_factor": job.scale_factor,
        "duration": float(sum(duration_every_epoch)),
        "bs_every_epoch": bs_every_epoch,
        "mem_every_epoch": [0.0] * total_epochs,
        "util_every_epoch": [0.0] * total_epochs,
        "duration_every_epoch": duration_every_epoch,
    }


def synthesize_profiles(
    jobs: Sequence[Job], throughputs: dict, worker_type: str = "v100"
) -> Dict[int, Profile]:
    """Profiles for all jobs of a trace, keyed by integer job index."""
    return {
        i: synthesize_profile(job, throughputs, worker_type)
        for i, job in enumerate(jobs)
    }


def _oracle_fingerprint(throughputs: dict, worker_type: str) -> str:
    import hashlib

    entries = sorted(
        (str(k), float(v["null"])) for k, v in throughputs[worker_type].items()
    )
    return hashlib.sha256(repr(entries).encode()).hexdigest()[:16]


def load_or_synthesize_profiles(
    trace_file: str,
    jobs: Sequence[Job],
    throughputs: dict,
    worker_type: str = "v100",
    cache: bool = True,
) -> Dict[int, Profile]:
    """Load ``<trace>.profile.pickle`` if present, else synthesize (and
    cache) profiles for the trace's jobs. The cache is keyed on the job
    count, worker type, and an oracle fingerprint so a pickle built against
    a different oracle is never silently reused. ``cache=False`` bypasses
    the pickle entirely — no read and no write — so hermetic callers
    (golden tests, the replication harness) always exercise the current
    synthesis code rather than machine state."""
    base, _ = os.path.splitext(trace_file)
    pickle_path = base + ".profile.pickle"
    fingerprint = _oracle_fingerprint(throughputs, worker_type)
    if cache and os.path.exists(pickle_path):
        with open(pickle_path, "rb") as f:
            cached = pickle.load(f)
        if (
            isinstance(cached, dict)
            and cached.get("worker_type") == worker_type
            and cached.get("oracle_fingerprint") == fingerprint
            and len(cached.get("profiles", ())) == len(jobs)
        ):
            return cached["profiles"]
    profiles = synthesize_profiles(jobs, throughputs, worker_type)
    if cache:
        try:
            with open(pickle_path, "wb") as f:
                pickle.dump(
                    {
                        "worker_type": worker_type,
                        "oracle_fingerprint": fingerprint,
                        "profiles": profiles,
                    },
                    f,
                )
        except OSError:
            pass
    return profiles
