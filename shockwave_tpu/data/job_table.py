"""Workload templates: the seven model families the scheduler knows.

Capability parity with reference: scheduler/job_template.py:1-40 and
scheduler/job_table.py:4-124. The (job_type, command, num_steps_arg) strings
are the scheduler<->workload *interface* — traces written against the
reference must parse into the same job types here — so they match verbatim.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional


@dataclasses.dataclass(frozen=True)
class JobTemplate:
    model: str
    command: str
    working_directory: str
    num_steps_arg: str
    needs_data_dir: bool = True
    distributed: bool = False


def _resnet18(bs: int) -> JobTemplate:
    return JobTemplate(
        model=f"ResNet-18 (batch size {bs})",
        command=f"python3 main.py --data_dir=%s/cifar10 --batch_size {bs}",
        working_directory="image_classification/cifar10",
        num_steps_arg="--num_steps",
        distributed=True,
    )


def _resnet50(bs: int) -> JobTemplate:
    return JobTemplate(
        model=f"ResNet-50 (batch size {bs})",
        command=f"python3 main.py -j 4 -a resnet50 -b {bs} %s/imagenet/",
        working_directory="image_classification/imagenet",
        num_steps_arg="--num_minibatches",
        distributed=True,
    )


def _transformer(bs: int) -> JobTemplate:
    return JobTemplate(
        model=f"Transformer (batch size {bs})",
        command=(
            "python3 train.py -data %s/translation/multi30k.atok.low.pt"
            f" -batch_size {bs} -proj_share_weight"
        ),
        working_directory="translation",
        num_steps_arg="-step",
        distributed=True,
    )


def _lm(bs: int) -> JobTemplate:
    return JobTemplate(
        model=f"LM (batch size {bs})",
        command=f"python3 main.py --cuda --data %s/wikitext2 --batch_size {bs}",
        working_directory="language_modeling",
        num_steps_arg="--steps",
        distributed=True,
    )


def _recommendation(bs: int) -> JobTemplate:
    return JobTemplate(
        model=f"Recommendation (batch size {bs})",
        command=f"python3 train.py --data_dir %s/ml-20m/pro_sg/ --batch_size {bs}",
        working_directory="recommendation",
        num_steps_arg="-n",
    )


def _a3c() -> JobTemplate:
    return JobTemplate(
        model="A3C (batch size 4)",
        command="python3 main.py --env PongDeterministic-v4 --workers 4 --amsgrad True",
        working_directory="rl",
        num_steps_arg="--max-steps",
        needs_data_dir=False,
    )


def _cyclegan() -> JobTemplate:
    return JobTemplate(
        model="CycleGAN (batch size 1)",
        command="python3 cyclegan.py --dataset_path %s/monet2photo --decay_epoch 0",
        working_directory="cyclegan",
        num_steps_arg="--n_steps",
    )


def build_job_table(include_gan_rl: bool = False) -> List[JobTemplate]:
    """The generation job table (reference: job_table.py:105-124 enables the
    five profiled families; CycleGAN/A3C templates exist but are not
    generated)."""
    table: List[JobTemplate] = []
    for bs in (32, 64, 128, 256):
        table.append(_resnet18(bs))
    for bs in (16, 32, 64):
        table.append(_resnet50(bs))
    for bs in (16, 32, 64, 128):
        table.append(_transformer(bs))
    for bs in (5, 10, 20, 40, 80):
        table.append(_lm(bs))
    for bs in (512, 1024, 2048, 4096, 8192):
        table.append(_recommendation(bs))
    if include_gan_rl:
        table.append(_a3c())
        table.append(_cyclegan())
    return table


JOB_TABLE: List[JobTemplate] = build_job_table()


def template_for_job_type(job_type: str) -> Optional[JobTemplate]:
    for template in build_job_table(include_gan_rl=True):
        if template.model == job_type:
            return template
    return None
