"""Synthetic trace generation.

Port of the reference's generation *semantics* (reference:
scheduler/scripts/utils/generate_trace.py:17-32 and
scheduler/utils.py:50-178), so the repo can build its own traces instead of
depending on the reference's committed ones:

  * independent seeded RNG streams for template choice, interarrival time,
    duration, scale factor, and dynamic-adaptation mode (seed, seed+1, ...),
    so changing one knob doesn't reshuffle the others;
  * exponential interarrival times with mean ``lam`` seconds;
  * durations sampled as whole hours from ``linspace(min, max, num)`` hours
    (Gavel style) or log-uniform seconds (Shockwave dynamic-trace style);
  * scale factors from a categorical distribution — Gavel's 70/10/20 over
    {1,2,4} (generate_trace.py:25-32) or Shockwave's 60/30/9/1 over
    {1,2,4,8} (the distribution encoded in its trace file names);
  * total steps = duration x oracle isolated throughput on the reference
    worker type (utils.py:141-144);
  * dynamic-adaptation mode drawn per job (static/accordion/gns), matching
    the Shockwave "dynamic" traces' 0/0.5/0.5 split;
  * optional multi-priority (20% weight 5.0, utils.py:146-150) and SLO
    (1.2/2.0/10.0 thirds, utils.py:152-160) assignment.
"""

from __future__ import annotations

import math
import random
from typing import Dict, List, Optional, Sequence, Tuple

from shockwave_tpu.core.job import Job
from shockwave_tpu.data.job_table import JOB_TABLE, JobTemplate
from shockwave_tpu.data.trace import write_trace
from shockwave_tpu.data.workload_info import parse_job_type

# (scale_factor -> probability); remaining mass goes to scale factor 1.
GAVEL_SCALE_FACTOR_DIST: Dict[int, float] = {1: 0.7, 2: 0.1, 4: 0.2}
SHOCKWAVE_SCALE_FACTOR_DIST: Dict[int, float] = {1: 0.6, 2: 0.3, 4: 0.09, 8: 0.01}

# (mode -> probability): the Shockwave "multigpu_dynamic" traces are half
# accordion / half gns, no static jobs.
DYNAMIC_MODE_DIST: Dict[str, float] = {"static": 0.0, "accordion": 0.5, "gns": 0.5}
STATIC_MODE_DIST: Dict[str, float] = {"static": 1.0, "accordion": 0.0, "gns": 0.0}


def exponential_interarrival(rng: random.Random, lam: float) -> float:
    """Mean-``lam``-seconds exponential draw (inverse CDF, like the
    reference so identical seeds give comparable arrival processes)."""
    return -math.log(1.0 - rng.random()) * lam


def _categorical(rng: random.Random, dist: Dict) -> object:
    r = rng.uniform(0, 1)
    acc = 0.0
    supported = None
    for value, p in dist.items():
        if p > 0:
            supported = value
        acc += p
        if p > 0 and r <= acc:
            return value
    return supported  # numerical slack: last value with nonzero mass


def _oracle_steps_per_sec(
    throughputs: dict, worker_type: str, job_type: str, scale_factor: int
) -> Optional[float]:
    entry = throughputs[worker_type].get((job_type, scale_factor))
    if entry is None:
        return None
    return float(entry["null"])


def generate_job(
    throughputs: dict,
    rng: random.Random,
    duration_rng: random.Random,
    scale_factor_rng: random.Random,
    mode_rng: random.Random,
    reference_worker_type: str = "v100",
    scale_factor_dist: Dict[int, float] = GAVEL_SCALE_FACTOR_DIST,
    mode_dist: Dict[str, float] = STATIC_MODE_DIST,
    duration_hours: Sequence[float] = (),
    min_duration_s: float = 1200.0,
    max_duration_s: float = 14400.0,
    priority_rng: Optional[random.Random] = None,
    slo_rng: Optional[random.Random] = None,
    job_table: Sequence[JobTemplate] = JOB_TABLE,
) -> Job:
    """Draw one job. Template first, then a scale factor only if the
    template trains distributed (reference utils.py:104-112 with
    always_generate_scale_factor=False)."""
    template = rng.choice(list(job_table))
    if template.distributed:
        scale_factor = int(_categorical(scale_factor_rng, scale_factor_dist))
    else:
        scale_factor = 1

    if duration_hours:
        duration = 3600.0 * duration_rng.choice(list(duration_hours))
    else:
        # Log-uniform seconds: matches the wide spread of the Shockwave
        # dynamic traces (minutes to several hours).
        duration = math.exp(
            duration_rng.uniform(
                math.log(min_duration_s), math.log(max_duration_s)
            )
        )

    mode = str(_categorical(mode_rng, mode_dist))

    job_type = template.model
    steps_per_sec = _oracle_steps_per_sec(
        throughputs, reference_worker_type, job_type, scale_factor
    )
    if steps_per_sec is None:
        raise KeyError(
            f"oracle has no throughput for {job_type!r} x{scale_factor}"
        )
    total_steps = max(1, int(duration * steps_per_sec))

    priority_weight = 1.0
    if priority_rng is not None and priority_rng.uniform(0, 1) <= 0.2:
        priority_weight = 5.0

    slo = None
    if slo_rng is not None:
        r = slo_rng.uniform(0, 1)
        slo = 1.2 if r < 1 / 3 else (2.0 if r < 2 / 3 else 10.0)

    return Job(
        job_type=job_type,
        command=template.command,
        working_directory=template.working_directory,
        num_steps_arg=template.num_steps_arg,
        needs_data_dir=template.needs_data_dir,
        total_steps=total_steps,
        duration=duration,
        scale_factor=scale_factor,
        mode=mode,
        priority_weight=priority_weight,
        SLO=slo,
    )


def generate_trace_jobs(
    num_jobs: int,
    throughputs: dict,
    seed: int = 0,
    lam: float = 0.0,
    **job_kwargs,
) -> Tuple[List[Job], List[float]]:
    """Generate ``num_jobs`` jobs with Poisson arrivals (all at t=0 when
    ``lam`` == 0). RNG stream fan-out mirrors the reference
    (generate_trace.py:35-46): seed+0 templates, +1 interarrivals,
    +2 durations, +3 scale factors, +4 modes."""
    rng = random.Random(seed)
    interarrival_rng = random.Random(seed + 1)
    duration_rng = random.Random(seed + 2)
    scale_factor_rng = random.Random(seed + 3)
    mode_rng = random.Random(seed + 4)

    jobs: List[Job] = []
    arrivals: List[float] = []
    t = 0.0
    for i in range(num_jobs):
        jobs.append(
            generate_job(
                throughputs,
                rng,
                duration_rng,
                scale_factor_rng,
                mode_rng,
                **job_kwargs,
            )
        )
        if i > 0 and lam > 0:
            t += exponential_interarrival(interarrival_rng, lam)
        arrivals.append(round(t))
    return jobs, arrivals


def smoke_trace_jobs(
    num_jobs: int,
    epochs: int = 2,
    arrival_gap_s: float = 0.0,
) -> Tuple[List[Job], List[float]]:
    """The deterministic alternating ResNet-18/50 smoke trace
    (scale-factor pattern 1,1,2,1; ``epochs`` epochs each; arrivals
    every ``arrival_gap_s`` seconds) shared by bench.py's pipelining
    phase, scripts/ci/pipelining_smoke.py, and tests/test_pipelining.py
    — one definition, so the bench-gated pipelining series always
    measures the same workload the smoke gate asserts invariants on."""
    from shockwave_tpu.data.workload_info import steps_per_epoch

    jobs: List[Job] = []
    arrivals: List[float] = []
    for i in range(num_jobs):
        model = ["ResNet-18", "ResNet-50"][i % 2]
        bs = 32 if model == "ResNet-18" else 64
        jobs.append(
            Job(
                job_type=f"{model} (batch size {bs})",
                command="python3 main.py",
                total_steps=steps_per_epoch(model, bs) * epochs,
                scale_factor=[1, 1, 2, 1][i % 4],
                mode="static",
            )
        )
        arrivals.append(i * arrival_gap_s)
    return jobs, arrivals


def generate_trace_file(
    path: str,
    num_jobs: int,
    throughputs: dict,
    seed: int = 0,
    lam: float = 0.0,
    **job_kwargs,
) -> Tuple[List[Job], List[float]]:
    jobs, arrivals = generate_trace_jobs(
        num_jobs, throughputs, seed=seed, lam=lam, **job_kwargs
    )
    write_trace(path, jobs, arrivals)
    return jobs, arrivals


def style_job_kwargs(style: str, multi_gpu: bool = True) -> dict:
    """Generation kwargs for the two canonical workload styles, shared
    by every driver/sweep CLI: "shockwave" = dynamic-adaptation jobs
    (accordion/gns, 60/30/9/1 scale factors, log-uniform durations);
    "gavel" = static jobs with whole-hour durations."""
    if style == "shockwave":
        return dict(
            scale_factor_dist=SHOCKWAVE_SCALE_FACTOR_DIST,
            mode_dist=DYNAMIC_MODE_DIST,
        )
    if style == "gavel":
        return dict(
            scale_factor_dist=(
                GAVEL_SCALE_FACTOR_DIST if multi_gpu else {1: 1.0}
            ),
            mode_dist=STATIC_MODE_DIST,
            duration_hours=[1, 2, 3, 4, 5, 6, 7, 8, 9, 10],
        )
    raise ValueError(f"unknown workload style {style!r}")
