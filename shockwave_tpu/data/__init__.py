from shockwave_tpu.data.trace import parse_trace, write_trace
from shockwave_tpu.data.throughputs import read_throughputs
from shockwave_tpu.data.profiles import synthesize_profiles, load_or_synthesize_profiles
from shockwave_tpu.data import bs_patterns

__all__ = [
    "parse_trace",
    "write_trace",
    "read_throughputs",
    "synthesize_profiles",
    "load_or_synthesize_profiles",
    "bs_patterns",
]
