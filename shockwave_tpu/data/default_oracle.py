"""Synthetic throughput oracle.

The simulator needs an oracle mapping (job type, scale factor) -> steps/s on
each worker type (isolated and co-located). The reference ships measured
JSONs (e.g. ``simulation_throughputs.json``); this module *generates* a
deterministic, realistic oracle from a small analytic performance model so
the framework is self-contained. An externally measured oracle JSON (the
reference's format, see :mod:`shockwave_tpu.data.throughputs`) can always be
supplied instead.

Performance model per family: samples/s on a v100 saturates with batch size
(``samples/s = peak * bs / (bs + half_sat)``); slower worker types apply a
constant relative speed; gang scaling applies a per-doubling efficiency;
space-shared pairs divide throughput according to each family's
utilization pressure.
"""

from __future__ import annotations

import itertools
import json
from typing import Dict, List, Tuple

from shockwave_tpu.data.workload_info import parse_job_type

# family -> (peak samples/s on v100, half-saturation batch size, utilization)
_FAMILY_MODEL = {
    "ResNet-18": (6500.0, 48.0, 0.55),
    "ResNet-50": (950.0, 24.0, 0.85),
    "Transformer": (2600.0, 40.0, 0.65),
    "LM": (1700.0, 12.0, 0.60),
    "Recommendation": (250000.0, 1500.0, 0.40),
    "CycleGAN": (8.5, 1.0, 0.90),
    "A3C": (20.0, 2.0, 0.25),
}

_WORKER_SPEED = {"v100": 1.0, "p100": 0.58, "k80": 0.22}

# Profiled batch sizes per family (matches the scaling range the batch-size
# adaptation modes can reach).
_FAMILY_BATCH_SIZES = {
    "ResNet-18": [16, 32, 64, 128, 256],
    "ResNet-50": [16, 32, 64, 128],
    "Transformer": [16, 32, 64, 128],
    "LM": [5, 10, 20, 40, 80],
    "Recommendation": [512, 1024, 2048, 4096, 8192],
    "CycleGAN": [1],
    "A3C": [4],
}

_SCALE_FACTORS = [1, 2, 4, 8]
_GANG_EFFICIENCY = 0.92  # per doubling of the gang size


def isolated_steps_per_sec(
    family: str, bs: int, scale_factor: int, worker_type: str
) -> float:
    peak, half_sat, _ = _FAMILY_MODEL[family]
    samples_per_sec = peak * bs / (bs + half_sat)
    gang = scale_factor * (_GANG_EFFICIENCY ** max(0, (scale_factor - 1).bit_length()))
    return _WORKER_SPEED[worker_type] * samples_per_sec * gang / bs


def _pressure(family: str, bs: int) -> float:
    """How hard a (family, batch size) leans on the accelerator."""
    peak, half_sat, util = _FAMILY_MODEL[family]
    return util * (0.7 + 0.6 * bs / (bs + half_sat))


def _sensitivity(family: str, bs: int) -> float:
    """How much a (family, batch size) suffers from a co-located peer."""
    peak, half_sat, util = _FAMILY_MODEL[family]
    return 0.3 + util * (0.6 + 0.8 * bs / (bs + half_sat))


def _pair_factors(
    family_a: str, bs_a: int, family_b: str, bs_b: int
) -> Tuple[float, float]:
    """Fraction of isolated throughput each job keeps when space-shared.
    Depends on BOTH sides (my sensitivity x the peer's pressure) so every
    (family, batch size) has a distinguishable colocation signature — what
    the throughput estimator's cosine matching relies on."""
    fa = 1.0 / (1.0 + _sensitivity(family_a, bs_a) * _pressure(family_b, bs_b))
    fb = 1.0 / (1.0 + _sensitivity(family_b, bs_b) * _pressure(family_a, bs_a))
    return fa, fb


def generate_oracle(
    pair_scale_factors: Tuple[int, ...] = (1, 2),
) -> Dict[str, dict]:
    """Build the full oracle with tuple keys (see data.throughputs)."""
    job_type_keys: List[Tuple[str, int]] = []
    for family, batch_sizes in _FAMILY_BATCH_SIZES.items():
        for bs in batch_sizes:
            for sf in _SCALE_FACTORS:
                job_type_keys.append((f"{family} (batch size {bs})", sf))

    oracle: Dict[str, dict] = {}
    for worker_type in _WORKER_SPEED:
        per_type: dict = {}
        for job_type, sf in job_type_keys:
            family, bs = parse_job_type(job_type)
            per_type[(job_type, sf)] = {
                "null": isolated_steps_per_sec(family, bs, sf, worker_type)
            }
        # Space-sharing entries for same-scale-factor pairs.
        for (jt_a, sf_a), (jt_b, sf_b) in itertools.product(
            job_type_keys, job_type_keys
        ):
            if sf_a != sf_b or sf_a not in pair_scale_factors:
                continue
            fam_a, bs_a = parse_job_type(jt_a)
            fam_b, bs_b = parse_job_type(jt_b)
            fa, fb = _pair_factors(fam_a, bs_a, fam_b, bs_b)
            per_type[(jt_a, sf_a)][(jt_b, sf_b)] = [
                per_type[(jt_a, sf_a)]["null"] * fa,
                per_type[(jt_b, sf_b)]["null"] * fb,
            ]
        oracle[worker_type] = per_type
    return oracle


def write_oracle_json(path: str, **kwargs) -> None:
    from shockwave_tpu.data.throughputs import stringify_throughputs
    from shockwave_tpu.utils.fileio import atomic_write_json

    atomic_write_json(
        path, stringify_throughputs(generate_oracle(**kwargs)), indent=None
    )
