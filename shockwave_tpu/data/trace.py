"""Trace file I/O.

Trace format: one job per line, 12 tab-separated fields
(reference: scheduler/utils.py:554-609):

  job_type  command  working_directory  num_steps_arg  needs_data_dir
  total_steps  scale_factor  mode  priority_weight  SLO  duration
  arrival_time

Arrival times must be nondecreasing.
"""

from __future__ import annotations

from typing import Iterable, List, Tuple

from shockwave_tpu.core.job import Job


def parse_trace(trace_file: str) -> Tuple[List[Job], List[float]]:
    jobs: List[Job] = []
    arrival_times: List[float] = []
    with open(trace_file, "r") as f:
        for line in f:
            line = line.rstrip("\n")
            if not line:
                continue
            (
                job_type,
                command,
                working_directory,
                num_steps_arg,
                needs_data_dir,
                total_steps,
                scale_factor,
                mode,
                priority_weight,
                slo,
                duration,
                arrival_time,
            ) = line.split("\t")
            if int(scale_factor) < 1:
                raise ValueError(f"scale_factor must be >= 1: {line!r}")
            jobs.append(
                Job(
                    job_type=job_type,
                    command=command,
                    working_directory=working_directory,
                    num_steps_arg=num_steps_arg,
                    needs_data_dir=bool(int(needs_data_dir)),
                    total_steps=int(total_steps),
                    duration=float(duration),
                    scale_factor=int(scale_factor),
                    mode=mode,
                    priority_weight=float(priority_weight),
                    SLO=float(slo),
                )
            )
            arrival_times.append(float(arrival_time))
    for earlier, later in zip(arrival_times, arrival_times[1:]):
        if later < earlier:
            raise ValueError("arrival times in trace are not sorted")
    return jobs, arrival_times


def write_trace(
    trace_file: str, jobs: Iterable[Job], arrival_times: Iterable[float]
) -> None:
    from shockwave_tpu.utils.fileio import atomic_write_text

    atomic_write_text(
        trace_file,
        "".join(
            "%s\t%g\n" % (job.to_trace_line(), float(arrival))
            for job, arrival in zip(jobs, arrival_times)
        ),
    )
