"""Dynamic-adaptation batch-size schedules (Accordion and GNS).

The reference encodes these schedules as a large if/else tree per
(model, batch size, scale factor) (reference: scheduler/utils.py:635-1180).
Here the same schedules are data tables:

* Accordion: per-model "critical regime" epoch sets during which the job
  trains at its original batch size; outside the critical regime (and past
  the first 30% of training) the batch size jumps to the model's maximum.
* GNS (gradient-noise-scale): batch size doubles in steps at fixed epoch
  boundaries, clamped to the model's profiled maximum. Encoded as
  ``(first_epoch, multiplier)`` breakpoints; each multiplier applies from
  its epoch until the next breakpoint.

A quirk of the reference generator is preserved because committed traces
depend on it: within a GNS schedule, the *final* epoch keeps the base batch
size unless it falls in the first breakpoint's range (the reference's later
loops break before assigning the last epoch, utils.py:743-747 vs 749-752).
"""

from __future__ import annotations

import functools
from typing import List, Sequence, Tuple

from shockwave_tpu.data.workload_info import MAX_BATCH_SIZES, parse_job_type

# -- Accordion ---------------------------------------------------------------

# Head critical-regime length keyed by original batch size.
_ACCORDION_HEAD = {
    "ResNet-18": {16: 10, 32: 10, 64: 10, 128: 10, 256: 20},
    "LM": {None: 10},
    "Recommendation": {512: 30, 1024: 30, 2048: 40, 4096: 10, 8192: 10},
}

# Extra mid-training critical windows used by the trace *generator* only
# (the run-time adaptation check below intentionally differs; see
# reference utils.py:656-667 vs :691-712).
_ACCORDION_GENERATOR_WINDOWS = {
    "ResNet-18": [(150, 160), (250, 260)],
    "Recommendation": [(60, 70), (80, 90)],
}

_ACCORDION_EXEMPT = ("Transformer", "CycleGAN", "A3C")


def _head_length(model: str, original_bs: int) -> int:
    heads = _ACCORDION_HEAD[model]
    head = heads.get(original_bs, heads.get(None))
    if head is None:
        raise KeyError((model, original_bs))
    return head


def accordion_in_critical_regime(model: str, original_bs: int, epoch: int) -> bool:
    """Run-time critical-regime check used by the simulator's Accordion
    adaptation (reference: scheduler/utils.py:691-712). Note ResNet-18 keeps
    its mid-training windows here but Recommendation does not."""
    if model == "ResNet-50":
        return (epoch % 30) < 10
    if epoch < _head_length(model, original_bs):
        return True
    if model == "ResNet-18":
        return any(lo <= epoch < hi for lo, hi in ((150, 160), (250, 260)))
    return False


def _generator_in_critical_regime(model: str, original_bs: int, epoch: int) -> bool:
    if model == "ResNet-50":
        return epoch < 600 and (epoch % 30) < 10
    if epoch < _head_length(model, original_bs):
        return True
    windows = _ACCORDION_GENERATOR_WINDOWS.get(model, [])
    return any(lo <= epoch < hi for lo, hi in windows)


def accordion_pattern(
    job_type: str, initial_batch_size: int, num_epochs: int
) -> List[int]:
    """Per-epoch batch sizes under Accordion
    (reference: scheduler/utils.py:635-688)."""
    return list(_accordion_pattern(job_type, initial_batch_size, num_epochs))


@functools.lru_cache(maxsize=4096)
def _accordion_pattern(
    job_type: str, initial_batch_size: int, num_epochs: int
) -> Tuple[int, ...]:
    model, _ = parse_job_type(job_type)
    schedule = [initial_batch_size] * num_epochs
    if model in _ACCORDION_EXEMPT:
        return tuple(schedule)
    max_bs = MAX_BATCH_SIZES.get(model, initial_batch_size)
    for epoch in range(num_epochs):
        in_critical = _generator_in_critical_regime(model, initial_batch_size, epoch)
        # The first 30% of training always counts as critical to preserve
        # final accuracy (reference: utils.py:683-686).
        if not in_critical and epoch > num_epochs * 0.3:
            schedule[epoch] = max_bs
    return tuple(schedule)


# -- GNS ---------------------------------------------------------------------

# (model, batch_size, scale_factor) -> list of (first_epoch, multiplier)
# breakpoints. The schedule only activates when num_epochs exceeds the first
# breakpoint's epoch.
_GNS_BREAKPOINTS = {
    ("ResNet-18", 16, 1): [(31, 2), (41, 4), (51, 8), (71, 16)],
    ("ResNet-18", 32, 1): [(21, 2), (31, 4), (51, 8)],
    ("ResNet-18", 64, 1): [(11, 2), (31, 4)],
    ("ResNet-18", 128, 1): [(11, 2)],
    ("ResNet-18", 16, 2): [(21, 2), (31, 4), (91, 8), (111, 16)],
    ("ResNet-18", 32, 2): [(11, 2), (21, 4), (41, 8)],
    ("ResNet-18", 64, 2): [(21, 2), (41, 4)],
    ("ResNet-18", 128, 2): [(41, 2)],
    ("ResNet-18", 16, 4): [(11, 2), (21, 4), (81, 8), (91, 16)],
    ("ResNet-18", 32, 4): [(21, 2), (31, 4), (61, 8)],
    ("ResNet-18", 64, 4): [(11, 2), (61, 4)],
    ("ResNet-18", 128, 4): [(11, 2)],
    ("ResNet-50", 64, 1): [(101, 2)],
    ("ResNet-50", 32, 2): [(101, 2), (111, 4)],
    ("ResNet-50", 64, 2): [(81, 2)],
    ("ResNet-50", 32, 4): [(131, 2), (221, 4)],
    ("ResNet-50", 64, 4): [(191, 2)],
    ("LM", 5, 1): [(31, 2), (41, 4), (61, 8), (71, 16)],
    ("LM", 10, 1): [(11, 2), (21, 4), (41, 8)],
    ("LM", 20, 1): [(11, 2), (41, 4)],
    ("LM", 40, 1): [(11, 2)],
    ("LM", 5, 2): [(31, 2), (51, 4), (61, 8), (71, 16)],
    ("LM", 10, 2): [(11, 2), (31, 4), (41, 8)],
    ("LM", 20, 2): [(31, 2), (41, 4)],
    ("LM", 40, 2): [(11, 2)],
    ("LM", 5, 4): [(11, 2), (31, 4), (71, 8), (91, 16)],
    ("LM", 10, 4): [(11, 2), (31, 4), (61, 8)],
    ("LM", 20, 4): [(11, 2), (61, 4)],
    ("LM", 40, 4): [(61, 2)],
    ("Recommendation", 512, 1): [(21, 2), (41, 4), (71, 8), (91, 16)],
    ("Recommendation", 1024, 1): [(21, 2), (51, 4), (91, 8)],
    ("Recommendation", 2048, 1): [(21, 2), (41, 4)],
    ("Recommendation", 4096, 1): [(41, 2)],
}

_GNS_EXEMPT = ("Transformer", "CycleGAN", "A3C")


def gns_pattern(
    job_type: str, batch_size: int, num_epochs: int, scale_factor: int
) -> List[int]:
    """Per-epoch batch sizes under GNS doubling
    (reference: scheduler/utils.py:714-1180)."""
    return list(_gns_pattern(job_type, batch_size, num_epochs, scale_factor))


# The simulator re-derives the schedule for every adaptive job every
# round (scheduler._simulate_gns); the patterns are pure functions of
# their arguments, so memoize (15k+ recomputes per 900-job trace
# otherwise dominate the sim profile).
@functools.lru_cache(maxsize=4096)
def _gns_pattern(
    job_type: str, batch_size: int, num_epochs: int, scale_factor: int
) -> Tuple[int, ...]:
    model, _ = parse_job_type(job_type)
    schedule = [batch_size] * num_epochs
    if model in _GNS_EXEMPT:
        return tuple(schedule)
    breakpoints = _GNS_BREAKPOINTS.get((model, batch_size, scale_factor))
    if breakpoints is not None and num_epochs > breakpoints[0][0]:
        starts = [bp for bp, _ in breakpoints] + [num_epochs]
        for i, (start, mult) in enumerate(breakpoints):
            end = min(starts[i + 1], num_epochs)
            for epoch in range(start, end):
                # Reference quirk: only the first breakpoint's loop scales
                # the final epoch; later loops break before assigning it.
                if i > 0 and epoch + 1 >= num_epochs:
                    break
                schedule[epoch] = batch_size * mult
    limit = MAX_BATCH_SIZES[model]
    return tuple(min(bs, limit) for bs in schedule)


def pattern_for_mode(
    mode: str, job_type: str, batch_size: int, num_epochs: int, scale_factor: int
) -> List[int]:
    if mode == "accordion":
        return accordion_pattern(job_type, batch_size, num_epochs)
    if mode == "gns":
        return gns_pattern(job_type, batch_size, num_epochs, scale_factor)
    return [batch_size] * num_epochs
