"""Cross-cell reconciliation: prices, capacity flow, migration.

Cells are independent markets; what couples them is the fleet's total
capacity and the accident of which jobs landed where. The coordinator
recovers that coupling with the market's own currency — each cell's
*congestion price*, the marginal welfare density an extra chip-round
would buy there (the budget row's shadow price, read off the solved
allocation). Chips flow from cheap cells to congested ones; when the
price spread persists after capacity has rebalanced, jobs migrate —
and a migration is never free: an incumbent's move is charged its
PR-1 switching cost (the measured relaunch overhead the objective
already prices), so the coordinator only moves a job when the
cross-cell welfare gain beats the real cost of relaunching it.

Everything here is pure, deterministic host math over solved
allocations — the replay exactness of a cell-decomposed decision log
depends on it.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from shockwave_tpu.solver.eg_problem import EGProblem

_EPS = 1e-9
# A cell is "slack" (price 0) while its plan leaves more than this
# fraction of the budget unused — the market did not clear, so an
# extra chip there buys nothing the cell wanted.
_SLACK_FRACTION = 1e-3


def demand_rounds(problem: EGProblem) -> np.ndarray:
    """Rounds of service each job still needs: remaining epochs
    converted through epoch duration into round units (the same
    per-job cap the PDHG projection enforces)."""
    dur = max(float(problem.round_duration), _EPS)
    need_epochs = np.maximum(
        problem.total_epochs - problem.completed_epochs, 0.0
    )
    epoch_dur = np.maximum(problem.epoch_duration, _EPS)
    return need_epochs * epoch_dur / dur


def congestion_price(problem: EGProblem, s: np.ndarray) -> float:
    """Marginal welfare density of one more chip-round in this cell:
    max over jobs still short of their demand cap of
    q_j beta_j / ((A_j + beta_j s_j + eps) w_j) — the same marginal
    the PDHG welfare water-fill thresholds on. 0 when the budget did
    not clear (spare capacity => an extra chip is worthless here)."""
    s = np.asarray(s, dtype=np.float64)
    J = problem.num_jobs
    if J == 0:
        return 0.0
    R = float(problem.future_rounds)
    dur = max(float(problem.round_duration), _EPS)
    w = np.maximum(np.asarray(problem.nworkers, dtype=np.float64), _EPS)
    budget = float(problem.num_gpus) * R
    used = float(np.sum(w * s))
    if used < budget * (1.0 - _SLACK_FRACTION):
        return 0.0
    total = np.maximum(problem.total_epochs, _EPS)
    epoch_dur = np.maximum(problem.epoch_duration, _EPS)
    A = problem.completed_epochs / total
    beta = dur / (epoch_dur * total)
    q = problem.priorities / (J * R)
    xcap = demand_rounds(problem)
    unmet = (s + 1e-6) < np.minimum(xcap, R)
    fits = problem.nworkers <= problem.num_gpus
    unmet &= fits
    if not np.any(unmet):
        return 0.0
    density = q * beta / ((A + _EPS + beta * s) * w)
    return float(np.max(density[unmet]))


def spare_chips(problem: EGProblem, s: np.ndarray) -> int:
    """Whole chips the cell's solved plan leaves idle across the
    window (the donatable surplus)."""
    s = np.asarray(s, dtype=np.float64)
    R = float(problem.future_rounds)
    used = float(np.sum(np.asarray(problem.nworkers) * s))
    return max(0, int((float(problem.num_gpus) * R - used) // max(R, 1.0)))


@dataclasses.dataclass
class CapacityMove:
    src: str
    dst: str
    chips: int
    price_src: float
    price_dst: float

    def as_dict(self) -> dict:
        return {
            "src": self.src,
            "dst": self.dst,
            "chips": self.chips,
            "price_src": self.price_src,
            "price_dst": self.price_dst,
        }


def propose_capacity_move(
    names: Sequence[str],
    prices: Dict[str, float],
    spares: Dict[str, int],
    capacities: Dict[str, int],
    floors: Dict[str, int],
    price_ratio_tol: float = 0.25,
) -> Optional[CapacityMove]:
    """One step of the price-adjustment loop: chips from the cheapest
    cell with donatable surplus to the most congested cell. None when
    prices are already within ``price_ratio_tol`` of each other (or no
    cell can donate) — the loop's fixed point."""
    if len(names) < 2:
        return None
    dst = max(names, key=lambda n: (prices.get(n, 0.0), n))
    p_dst = prices.get(dst, 0.0)
    if p_dst <= 0.0:
        return None
    donors = [
        n
        for n in names
        if n != dst
        and min(spares.get(n, 0), capacities[n] - floors.get(n, 1)) >= 1
        and prices.get(n, 0.0) <= (1.0 - price_ratio_tol) * p_dst
    ]
    if not donors:
        return None
    src = min(donors, key=lambda n: (prices.get(n, 0.0), n))
    give = min(
        spares.get(src, 0),
        capacities[src] - floors.get(src, 1),
        max(1, capacities[dst] // 8),
    )
    if give < 1:
        return None
    return CapacityMove(
        src=src, dst=dst, chips=int(give),
        price_src=prices.get(src, 0.0), price_dst=p_dst,
    )


@dataclasses.dataclass
class Migration:
    job: object
    src: str
    dst: str
    gain: float
    cost: float
    incumbent: bool

    def as_dict(self) -> dict:
        return {
            "job": str(self.job),
            "src": self.src,
            "dst": self.dst,
            "gain": self.gain,
            "cost": self.cost,
            "incumbent": self.incumbent,
        }


def plan_migrations(
    names: Sequence[str],
    problems: Dict[str, EGProblem],
    solutions: Dict[str, np.ndarray],
    job_ids: Dict[str, List[object]],
    prices: Dict[str, float],
    capacities: Dict[str, int],
    max_moves: int = 8,
    price_ratio_tol: float = 0.5,
) -> List[Migration]:
    """Migrations from the most congested cell to the cheapest one,
    priced through the switching-cost term: candidate j moves only
    when its cross-cell gain — the price spread times the chip-rounds
    of demand the congested cell left unserved for j — exceeds its
    switch bonus (regularizer x measured relaunch overhead for
    incumbents; free for jobs not currently holding workers). Largest
    net gain first, bounded by ``max_moves``."""
    if len(names) < 2:
        return []
    src = max(names, key=lambda n: (prices.get(n, 0.0), n))
    p_src = prices.get(src, 0.0)
    if p_src <= 0.0:
        return []
    others = [n for n in names if n != src]
    dst = min(others, key=lambda n: (prices.get(n, 0.0), n))
    p_dst = prices.get(dst, 0.0)
    if p_src - p_dst < price_ratio_tol * p_src:
        return []
    problem = problems[src]
    s = np.asarray(solutions[src], dtype=np.float64)
    ids = job_ids[src]
    xcap = np.minimum(demand_rounds(problem), float(problem.future_rounds))
    unmet = np.maximum(xcap - s, 0.0)
    bonus = problem.switch_bonus()
    incumbent = (
        np.asarray(problem.incumbent, dtype=np.float64)
        if problem.incumbent is not None
        else np.zeros(problem.num_jobs)
    )
    candidates: List[Migration] = []
    for i, job in enumerate(ids):
        if problem.nworkers[i] > capacities[dst]:
            continue  # a gang the destination can never place
        gain = (p_src - p_dst) * float(problem.nworkers[i]) * float(unmet[i])
        cost = float(bonus[i])
        if gain <= cost or gain <= 0.0:
            continue  # moves are never free: the relaunch must pay for itself
        candidates.append(
            Migration(
                job=job, src=src, dst=dst, gain=gain, cost=cost,
                incumbent=bool(incumbent[i] > 0.0),
            )
        )
    candidates.sort(key=lambda m: (-(m.gain - m.cost), str(m.job)))
    return candidates[: max(0, int(max_moves))]
