"""Cell-decomposed market: partitioned EG solves with a reconciling
coordinator (ROADMAP item 2, CvxCluster direction).

One global Eisenberg-Gale solve is a single latency and failure domain:
every job rides one solve, one compile, one timeout. This package
splits the fleet into *cells* — independent EG markets over disjoint
job sets and capacity slices — and recovers the coupling (total
capacity, cross-cell load balance) with a cheap top-level coordinator:

  * :mod:`shockwave_tpu.cells.partition` — capacity partitioning and
    least-loaded cell assignment at admission.
  * :mod:`shockwave_tpu.cells.batched` — the whole fleet of cells
    solved as ONE batched ``vmap`` dispatch of the restarted-PDHG
    kernel (one compile per (lane-band, slot-band); optionally
    ``shard_map``-ed over the cell axis so each device owns its cells
    with zero collectives).
  * :mod:`shockwave_tpu.cells.coordinator` — congestion prices from
    each cell's solved allocation, the capacity-reconciliation step
    (chips flow from cheap cells to congested ones), and migration
    planning priced through the PR-1 switching-cost term.
  * :mod:`shockwave_tpu.cells.planner` — :class:`CellPlanner`, the
    scheduler-facing federation conforming to the single-planner
    contract, with selective replanning (only stale cells re-solve),
    per-cell degradation (a cell-solver timeout degrades that cell
    only), and coordinator-level flight-recorder exactness.
"""

from shockwave_tpu.cells.planner import CellPlanner  # noqa: F401
