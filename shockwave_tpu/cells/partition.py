"""Fleet partitioning: capacity split and cell assignment.

Pure, deterministic helpers — the planner's replay exactness rides on
every decision here being a function of explicit inputs only.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence


def cell_names(num_cells: int) -> List[str]:
    """Stable cell identifiers ("c00", "c01", ...)."""
    return [f"c{i:02d}" for i in range(int(num_cells))]


def partition_capacity(num_gpus: int, num_cells: int) -> List[int]:
    """Split ``num_gpus`` chips over ``num_cells`` cells as evenly as
    possible (remainder to the first cells), every cell >= 1 chip.
    More cells than chips clamps the cell count to the chip count —
    a zero-chip cell has no market to clear."""
    num_gpus = max(1, int(num_gpus))
    num_cells = max(1, min(int(num_cells), num_gpus))
    base, rem = divmod(num_gpus, num_cells)
    return [base + (1 if i < rem else 0) for i in range(num_cells)]


def spread_capacity_delta(
    capacities: List[int], delta: int, floors: Optional[List[int]] = None
) -> List[int]:
    """Apply a fleet-level capacity change (churn re-add / worker
    death) across cells deterministically: grow largest-deficit-first
    toward the even split, shrink largest-first but never below each
    cell's floor (its widest incumbent gang — shrinking past it would
    wedge that job forever). When every cell is at its floor the
    remaining shrink is dropped (the applier never reclaims the last
    chip of a cell for the same reason the single planner clamps to
    >= 1)."""
    out = list(int(c) for c in capacities)
    floors = [max(1, int(f)) for f in (floors or [1] * len(out))]
    step = 1 if delta > 0 else -1
    for _ in range(abs(int(delta))):
        if step > 0:
            # Grow the currently-smallest cell (lowest index on ties).
            i = min(range(len(out)), key=lambda k: (out[k], k))
            out[i] += 1
        else:
            candidates = [
                k for k in range(len(out)) if out[k] - 1 >= floors[k]
            ]
            if not candidates:
                break
            i = min(candidates, key=lambda k: (-out[k], k))
            out[i] -= 1
    return out


# Admission-routing hysteresis, as a fraction of the sticky cell's
# FAIR-SHARE load (fleet-minimum load-per-chip x its capacity): a
# burst of arrivals STICKS to the previously-picked cell until its
# load exceeds its fair share by this fraction (floored at one
# gang-weight unit), instead of round-robining across the fleet on
# per-job load deltas (a pure argmin flips cells on every 1-job tie).
# Stickiness is what bounds the stale-cell set — and therefore the
# per-round replanning cost — under streaming churn, and it is a
# SCALE property: at planet scale 2% of a cell's population absorbs
# whole submission bursts, while tiny fleets (band -> 1 job) keep the
# plain balanced behavior.
LOAD_HYSTERESIS_FRAC = 0.02


def pick_cell(
    scale_factor: int,
    loads: Sequence[float],
    capacities: Sequence[int],
    sticky: Optional[int] = None,
    hysteresis_frac: float = LOAD_HYSTERESIS_FRAC,
) -> int:
    """Sticky least-loaded admission: among cells wide enough for the
    job's gang, keep the previously-picked ``sticky`` cell while its
    load stays within ``hysteresis_frac`` of its fair share at the
    fleet-minimum load-per-chip (floor: one gang-weight unit);
    otherwise the cell with the lowest load-per-chip (ties to the
    lowest index). Falls back to the widest cell when no cell fits —
    the same unschedulable-gang semantics the hetero pool picker
    uses."""
    best, best_ratio = None, None
    for i, cap in enumerate(capacities):
        if cap < scale_factor:
            continue
        ratio = float(loads[i]) / max(float(cap), 1.0)
        if best_ratio is None or (ratio, i) < (best_ratio, best):
            best, best_ratio = i, ratio
    if best is None:
        return max(range(len(capacities)), key=lambda i: (capacities[i], -i))
    if (
        sticky is not None
        and 0 <= sticky < len(capacities)
        and capacities[sticky] >= scale_factor
    ):
        cap_sticky = max(float(capacities[sticky]), 1.0)
        fair_share = best_ratio * cap_sticky
        band = max(1.0, hysteresis_frac * fair_share)
        if float(loads[sticky]) - fair_share <= band:
            return sticky
    return best


def cell_floor(job_gangs: Dict[object, float]) -> int:
    """The capacity floor of a cell: its widest gang (>= 1)."""
    widest = max([1.0] + [float(g) for g in job_gangs.values()])
    return max(1, int(widest))
