"""Batched per-cell PDHG: the whole fleet of cells in ONE dispatch.

Every cell's market is the same J-slot restarted-PDHG saddle-point
solve (:func:`shockwave_tpu.solver.eg_pdhg._pdhg_core`); ``vmap`` over
a leading cell axis turns C independent cell solves into one device
program — one compile covers the fleet, and each lane early-stops on
its own residual/stall criterion (vmap's while_loop batching masks
finished lanes, so the batch runs for the SLOWEST cell's cycles, not
the sum).

Lane-count banding: the number of lanes is padded to a power of two
with inert lanes (all-inactive job masks, 1-chip capacity), so
selective replanning — this round 2 stale cells, next round 5 — reuses
at most log2(C)+1 compiled programs instead of one per stale-count.

Mesh path: with ``mesh`` set the SAME kernel runs under ``shard_map``
with the cell axis split over devices. There are no cross-cell
collectives — cells are independent by construction, the coordinator
handles coupling on host — so each device computes its own cells'
markets concurrently. This is the planet-scale shape: per-device work
is one cell's rows regardless of fleet size.
"""

from __future__ import annotations

import functools
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from shockwave_tpu.analysis import sanitize
from shockwave_tpu.solver.eg_pdhg import (
    DEFAULT_INNER_ITERS,
    DEFAULT_MAX_CYCLES,
    DEFAULT_TOL,
    _STALL_REL,
    _default_s0,
    _packed_args,
    _pdhg_core,
)
from shockwave_tpu.solver.eg_jax import num_slots_for
from shockwave_tpu.solver.eg_problem import EGProblem


def lane_band(num_lanes: int) -> int:
    """Next power-of-two lane count >= num_lanes (bounds recompiles
    across varying stale-cell sets)."""
    n = 1
    while n < int(num_lanes):
        n *= 2
    return n


@functools.partial(jax.jit, static_argnames=("max_cycles", "inner_iters"))
def _solve_cells_kernel(
    active,  # [C, J]
    priorities,
    completed,
    total,
    epoch_dur,
    remaining,
    nworkers,
    switch_bonus,
    s0,
    num_gpus,  # [C]: per-cell capacity — the only per-cell scalar
    round_duration,
    future_rounds,
    regularizer,
    tol,
    stall_rel,
    max_cycles: int = DEFAULT_MAX_CYCLES,
    inner_iters: int = DEFAULT_INNER_ITERS,
):
    core = functools.partial(
        _pdhg_core,
        max_cycles=max_cycles,
        inner_iters=inner_iters,
        axis_name=None,
    )
    return jax.vmap(
        lambda *a: core(*a), in_axes=(0,) * 10 + (None,) * 5
    )(
        active, priorities, completed, total, epoch_dur, remaining,
        nworkers, switch_bonus, s0, num_gpus,
        round_duration, future_rounds, regularizer, tol, stall_rel,
    )


@functools.lru_cache(maxsize=8)
def _build_cells_sharded(mesh: Mesh, axis: str, max_cycles, inner_iters):
    """shard_map the batched kernel over the cell axis: no collectives
    (cells are independent), so this is a pure split of lanes across
    devices."""
    from shockwave_tpu.utils.compat import shard_map

    def kernel(*args):
        return _solve_cells_kernel(
            *args, max_cycles=max_cycles, inner_iters=inner_iters
        )

    spec_c = P(axis)
    spec_rep = P()
    diag_spec = {
        k: spec_c
        for k in (
            "cycles", "iterations", "restarts", "residual", "residual0",
            "converged", "welfare_filled",
        )
    }
    fn = shard_map(
        kernel,
        mesh=mesh,
        check_vma=False,
        in_specs=(spec_c,) * 10 + (spec_rep,) * 5,
        out_specs=(spec_c, spec_c, diag_spec),
    )
    return jax.jit(fn)


def _stack_cells(
    problems: Sequence[EGProblem],
    s0s: Sequence[Optional[np.ndarray]],
    slots: int,
    lanes: int,
):
    """Pack C cell problems into [lanes, slots] arrays; lanes past C
    are inert (no active jobs, 1-chip capacity)."""
    per_cell = [
        _packed_args(p, slots, s0s[i]) for i, p in enumerate(problems)
    ]
    stacked = []
    for field in range(9):
        rows = [np.asarray(args[field]) for args in per_cell]
        rows += [np.zeros(slots, np.float32)] * (lanes - len(per_cell))
        stacked.append(jnp.asarray(np.stack(rows)))
    gpus = [float(p.num_gpus) for p in problems]
    gpus += [1.0] * (lanes - len(problems))
    stacked.append(jnp.asarray(np.asarray(gpus, np.float32)))
    return stacked


def solve_cells_pdhg(
    problems: Sequence[EGProblem],
    s0s: Optional[Sequence[Optional[np.ndarray]]] = None,
    tol: float = DEFAULT_TOL,
    stall_rel: float = _STALL_REL,
    max_cycles: int = DEFAULT_MAX_CYCLES,
    inner_iters: int = DEFAULT_INNER_ITERS,
    slots: Optional[int] = None,
    mesh: Optional[Mesh] = None,
    axis_name: str = "cells",
) -> Tuple[List[np.ndarray], List[float], List[dict]]:
    """Solve every cell's relaxed EG market in one batched dispatch.

    All problems must share ``round_duration`` / ``future_rounds`` /
    ``regularizer`` (one fleet, one planning config — asserted).
    Returns per-cell ``(s [num_jobs] float64, objective, diagnostics)``
    lists; lane results are bit-identical to the single-cell
    :func:`shockwave_tpu.solver.eg_pdhg.solve_pdhg_relaxed` on the
    same inputs (pinned by tests), so a cell's market does not change
    meaning by being solved next to its neighbors.
    """
    if not problems:
        return [], [], []
    ref = problems[0]
    for p in problems[1:]:
        assert (
            p.round_duration == ref.round_duration
            and p.future_rounds == ref.future_rounds
            and p.regularizer == ref.regularizer
        ), "cells must share the fleet planning config"
    if s0s is None:
        s0s = [None] * len(problems)
    s0s = [
        s0 if s0 is not None else _default_s0(p)
        for p, s0 in zip(problems, s0s)
    ]
    if slots is None:
        slots = num_slots_for(max(p.num_jobs for p in problems))
    lanes = lane_band(len(problems))
    args = _stack_cells(problems, s0s, slots, lanes)
    scalars = (
        jnp.float32(ref.round_duration),
        jnp.float32(ref.future_rounds),
        jnp.float32(ref.regularizer),
        jnp.float32(tol),
        jnp.float32(stall_rel),
    )
    if mesh is not None and lanes % int(np.prod(mesh.devices.shape)) == 0:
        fn = _build_cells_sharded(
            mesh, axis_name, int(max_cycles), int(inner_iters)
        )
        shard_c = NamedSharding(mesh, P(axis_name))
        rep = NamedSharding(mesh, P())
        placed = [jax.device_put(a, shard_c) for a in args]
        placed += [jax.device_put(v, rep) for v in scalars]
        with sanitize.jax_entry("cells.solve_cells_pdhg_sharded"):
            s, obj, diag = fn(*placed)
    else:
        with sanitize.jax_entry("cells.solve_cells_pdhg"):
            s, obj, diag = _solve_cells_kernel(
                *args, *scalars,
                max_cycles=int(max_cycles), inner_iters=int(inner_iters),
            )
        sanitize.check_recompiles(
            "cells.solve_cells_pdhg",
            _solve_cells_kernel,
            (lanes, slots, int(max_cycles), int(inner_iters)),
        )
    s = np.asarray(s)
    obj = np.asarray(obj)
    diags = []
    for i, p in enumerate(problems):
        diags.append(
            {
                "cycles": int(np.asarray(diag["cycles"])[i]),
                "iterations": int(np.asarray(diag["iterations"])[i]),
                "restarts": int(np.asarray(diag["restarts"])[i]),
                "residual": float(np.asarray(diag["residual"])[i]),
                "converged": bool(np.asarray(diag["converged"])[i]),
                "welfare_filled": bool(
                    np.asarray(diag["welfare_filled"])[i]
                ),
            }
        )
    return (
        [
            s[i, : p.num_jobs].astype(np.float64)
            for i, p in enumerate(problems)
        ],
        [float(o) for o in obj[: len(problems)]],
        diags,
    )


def schedule_cell(
    problem: EGProblem, s: np.ndarray, polish: bool = True
) -> np.ndarray:
    """Host tail of one cell's solve: the same integer rounding +
    placement + reorder every counts-producing backend shares, so a
    cell's boolean plan is exactly what the standalone pdhg backend
    would emit for the same relaxed iterate."""
    from shockwave_tpu.solver.eg_jax import counts_to_schedule
    from shockwave_tpu.solver.rounding import reorder_rounds, round_counts

    counts = round_counts(
        s, problem.nworkers, problem.num_gpus, problem.future_rounds
    )
    Y = counts_to_schedule(counts, problem, polish=polish)
    return reorder_rounds(
        Y, problem.priorities, problem.nworkers, problem.num_gpus
    )
