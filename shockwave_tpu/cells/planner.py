"""CellPlanner: a federation of per-cell EG markets behind the
single-planner contract.

The scheduler drives this exactly like a :class:`ShockwavePlanner`
(add/remove jobs, throughput updates, ``current_round_schedule``,
capacity changes, checkpoint state) — inside, the fleet is partitioned
into cells, each owning a capacity slice and a disjoint job set, each
planning its own market with its own child planner. What makes the
federation more than C independent planners:

* **Selective replanning.** Only *stale* cells (recompute flagged, or
  plan cache exhausted) re-solve each round; the rest keep their
  cached windows. A churn event touches one cell's market, so the
  per-round planning cost is bounded by the churned cells, not the
  fleet — the 10x-jobs-at-flat-latency property the global solve can
  never have.
* **One compile for the fleet.** Stale cells solve as one batched
  ``vmap`` dispatch of the restarted-PDHG kernel
  (:func:`shockwave_tpu.cells.batched.solve_cells_pdhg`), lane-banded
  so varying stale-set sizes reuse compiled programs.
* **Reconciliation.** The coordinator reads each solved cell's
  congestion price and moves chips from cheap cells to congested
  ones (a small price-adjustment loop); when imbalance persists past
  ``cell_migration_patience`` rounds, jobs migrate — priced through
  the PR-1 switching-cost term, and a migrated incumbent CARRIES its
  incumbency and measured relaunch overhead into the destination
  cell, so the move is charged (and protected) exactly once.
* **Per-cell degradation.** With fault injection armed or a plan
  deadline set, cells solve individually through each child's
  degradation ladder: an injected ``solver_timeout`` degrades that
  cell's solve (pdhg -> relaxed -> native) while every other cell
  plans normally; a cell whose ladder is exhausted keeps its cached
  plan and the rest of the fleet proceeds — failure isolation the
  single market cannot express.
* **Flight-recorder exactness.** Each coordinated replan records ONE
  plan record whose planner state is the full pre-replan federation
  snapshot (kind ``cell_set``), stamped with the stale set, per-cell
  backends/warm-starts, and the reconciliation trail; replay restores
  the federation and re-runs the identical coordinated replan.
"""

from __future__ import annotations

import dataclasses
import time
from collections import OrderedDict
from typing import Dict, List, Optional

import numpy as np

from shockwave_tpu import obs
from shockwave_tpu.cells import batched, coordinator, partition
from shockwave_tpu.policies.shockwave import ShockwavePlanner
from shockwave_tpu.policies.speculation import SpeculativePlannerMixin

# Solve knobs default to the single-pdhg backend's; config keys
# ("cell_*") override per deployment.
DEFAULT_RECONCILE_ITERS = 2
DEFAULT_PRICE_RATIO_TOL = 0.25
DEFAULT_MIGRATION_PATIENCE = 2
DEFAULT_MAX_MIGRATIONS = 8


class CellPlanner(SpeculativePlannerMixin):
    """Cell-decomposed planner (see module docstring). Config keys:

    ``cells`` (required, int >= 2)
        number of cells the fleet partitions into (clamped to
        ``num_gpus``).
    ``cell_backend`` (default ``"pdhg"``)
        the per-cell backend for the individual/ladder path; the
        batched fast path is always the PDHG kernel.
    ``cell_reconcile_iters`` / ``cell_price_ratio_tol``
        capacity-reconciliation loop bound and the relative price
        spread it stops at.
    ``cell_migration_patience`` / ``cell_max_migrations``
        consecutive imbalanced replans before jobs migrate, and the
        per-replan migration cap.
    ``cell_max_cycles`` / ``cell_inner_iters``
        per-cell PDHG effort (defaults: the pdhg backend's).
    ``cell_mesh`` (default false)
        shard the batched solve's cell axis over every visible device
        (each device computes its own cells; no collectives).
    """

    def __init__(self, config: dict, backend: str = "cells"):
        self.config = dict(config)
        self.backend = backend
        self.num_gpus = int(config["num_gpus"])
        self.round_duration = float(config["time_per_iteration"])
        self.future_rounds = int(config.get("future_rounds", 20))
        num_cells = int(config.get("cells", 2))
        caps = partition.partition_capacity(self.num_gpus, num_cells)
        names = partition.cell_names(len(caps))
        self.cells: "OrderedDict[str, int]" = OrderedDict(zip(names, caps))
        child_backend = str(config.get("cell_backend", "pdhg"))
        self.child_backend = child_backend
        self.children: "OrderedDict[str, ShockwavePlanner]" = OrderedDict(
            (
                name,
                ShockwavePlanner(
                    {**config, "num_gpus": cap}, backend=child_backend
                ),
            )
            for name, cap in self.cells.items()
        )
        for name, child in self.children.items():
            child.pool_label = name
        self.job_cell: Dict[object, str] = {}
        self.assignments: Dict[str, int] = {n: 0 for n in self.cells}
        # O(1) live-load accounting (admission at 100k jobs cannot
        # afford a per-add scan of the cell's job table): per-cell gang
        # sizes of INCOMPLETE jobs plus their running sum, maintained
        # by add/remove/complete/migrate and rebuilt on restore.
        self._cell_jobs: Dict[str, Dict[object, float]] = {
            n: {} for n in self.cells
        }
        self._load: Dict[str, float] = {n: 0.0 for n in self.cells}
        # Admission stickiness: the last-picked cell, kept while its
        # load stays within hysteresis of the fleet minimum (bounds the
        # stale set under bursty arrivals; see partition.pick_cell).
        self.sticky_cell: Optional[str] = None
        # Last-known congestion price / donatable surplus per cell —
        # persisted so reconciliation can weigh cells that did not
        # solve this round (and so replay recomputes identical moves).
        self.prices: Dict[str, float] = {n: 0.0 for n in self.cells}
        self.spares: Dict[str, int] = {n: 0 for n in self.cells}
        self.imbalance_rounds = 0
        self.migrations_total = 0
        # Last committed replan's per-job spend snapshot across the
        # re-solved cells (scheduler tenant-spend gauges; NOT replayed).
        self.last_market: Optional[dict] = None
        self.pdhg_tol = float(config.get("pdhg_tol", 1e-4))
        raw_deadline = config.get("plan_deadline_s")
        self.plan_deadline_s = (
            float(raw_deadline) if raw_deadline is not None else None
        )
        self.reconcile_iters = int(
            config.get("cell_reconcile_iters", DEFAULT_RECONCILE_ITERS)
        )
        self.price_ratio_tol = float(
            config.get("cell_price_ratio_tol", DEFAULT_PRICE_RATIO_TOL)
        )
        self.migration_patience = int(
            config.get("cell_migration_patience", DEFAULT_MIGRATION_PATIENCE)
        )
        self.max_migrations = int(
            config.get("cell_max_migrations", DEFAULT_MAX_MIGRATIONS)
        )
        self.cell_max_cycles = int(config.get("cell_max_cycles", 96))
        self.cell_inner_iters = int(config.get("cell_inner_iters", 40))
        self.use_mesh = bool(config.get("cell_mesh", False))
        # Coordinator-level solve history (the per-cell child records
        # ride each child's own solve_records).
        self.coord_solve_records: List[dict] = []
        self.coord_solve_times: List[float] = []
        # Merged window of the cells solved by the most recent
        # coordinated replan (what the flight-recorder replay diffs).
        self.schedules: "OrderedDict[int, list]" = OrderedDict()
        self._replay_stamp: Optional[dict] = None
        self._failed_cells: set = set()
        # Plan-ahead pipelining (shockwave_tpu/policies/speculation.py):
        # the federation speculates as a whole and reconciles per cell —
        # only churned cells re-solve at the boundary. Shared
        # scaffolding from SpeculativePlannerMixin.
        self._init_speculation(config)
        obs.gauge(
            "cells_count", "number of cells the fleet partitions into"
        ).set(float(len(self.cells)))

    # -- scheduler-facing interface -------------------------------------
    @property
    def round_index(self) -> int:
        return next(iter(self.children.values())).round_index

    @property
    def num_cells(self) -> int:
        return len(self.cells)

    @property
    def num_jobs(self) -> int:
        return sum(c.num_jobs for c in self.children.values())

    @property
    def solve_times(self) -> List[float]:
        return list(self.coord_solve_times)

    @property
    def solve_records(self) -> List[dict]:
        records = [dict(r) for r in self.coord_solve_records]
        records += [
            {**r, "cell": name}
            for name, c in self.children.items()
            for r in c.solve_records
        ]
        return records

    def _cell_load(self, name: str) -> float:
        """Live demand weight: sum of incomplete jobs' gang sizes."""
        return self._load.get(name, 0.0)

    def _drop_load(self, job_id) -> None:
        name = self.job_cell.get(job_id)
        if name is None:
            return
        gang = self._cell_jobs.get(name, {}).pop(job_id, None)
        if gang is not None:
            self._load[name] = max(0.0, self._load[name] - gang)

    def add_job(
        self, job_id, profile: dict, round_len: float, scale_factor: int,
        submit_time: Optional[float] = None, overhead_s: float = 0.0,
        **_ignored,
    ) -> None:
        names = list(self.cells)
        idx = partition.pick_cell(
            int(scale_factor),
            [self._cell_load(n) for n in names],
            [self.cells[n] for n in names],
            sticky=(
                names.index(self.sticky_cell)
                if self.sticky_cell in self.cells
                else None
            ),
        )
        name = names[idx]
        self.sticky_cell = name
        self.job_cell[job_id] = name
        self.assignments[name] = self.assignments.get(name, 0) + 1
        self._cell_jobs[name][job_id] = float(scale_factor)
        self._load[name] = self._load.get(name, 0.0) + float(scale_factor)
        self.children[name].add_job(
            job_id, profile, round_len, scale_factor, submit_time,
            overhead_s=overhead_s,
        )
        obs.counter(
            "cells_jobs_assigned_total", "jobs admitted into a cell"
        ).inc(cell=name)

    def cell_of(self, job_id) -> Optional[str]:
        return self.job_cell.get(job_id)

    def _child_of(self, job_id) -> Optional[ShockwavePlanner]:
        name = self.job_cell.get(job_id)
        return self.children.get(name) if name is not None else None

    def remove_job(self, job_id) -> None:
        self._drop_load(job_id)
        child = self._child_of(job_id)
        if child is not None:
            child.remove_job(job_id)
        self.job_cell.pop(job_id, None)

    def record_round_throughput(self, job_id, round_id, throughput, bs) -> None:
        child = self._child_of(job_id)
        if child is not None:
            child.record_round_throughput(job_id, round_id, throughput, bs)

    def mark_complete(self, job_id) -> None:
        self._drop_load(job_id)
        child = self._child_of(job_id)
        if child is not None:
            child.mark_complete(job_id)

    def set_progress(self, job_id, num_epochs: int) -> None:
        child = self._child_of(job_id)
        if child is not None:
            child.set_progress(job_id, num_epochs)
            md = child.job_metadata.get(job_id)
            if md is not None and md.completed_epochs >= md.total_epochs:
                self._drop_load(job_id)

    def get_metadata(self, job_id):
        child = self._child_of(job_id)
        return child.get_metadata(job_id) if child is not None else None

    def increment_round(self) -> None:
        for child in self.children.values():
            child.increment_round()

    def set_recompute_flag(self, jobs=None) -> None:
        """With ``jobs`` given, only the cells owning them go stale —
        one job's requeue or batch-size change re-solves its cell, not
        the fleet. A job not yet mapped to a cell (or a bare call)
        stales everything, the safe default."""
        if jobs is not None:
            cells = {self.job_cell.get(j) for j in jobs}
            if None not in cells:
                for name in cells:
                    self.children[name].set_recompute_flag()
                return
        for child in self.children.values():
            child.set_recompute_flag()

    def _cell_floor(self, name: str) -> int:
        """A cell can never shrink below its widest incomplete gang."""
        return partition.cell_floor(self._cell_jobs.get(name, {}))

    def set_capacity(self, num_gpus: int) -> None:
        """Fleet capacity changed (worker death, reclamation, churn
        re-add): spread the delta across cells deterministically,
        respecting each cell's widest-gang floor."""
        num_gpus = max(1, int(num_gpus))
        if num_gpus == self.num_gpus:
            return
        names = list(self.cells)
        new = partition.spread_capacity_delta(
            [self.cells[n] for n in names],
            num_gpus - sum(self.cells.values()),
            [self._cell_floor(n) for n in names],
        )
        for name, cap in zip(names, new):
            if cap != self.cells[name]:
                self.cells[name] = cap
                self.children[name].set_capacity(cap)
        self.num_gpus = sum(new)
        self.config["num_gpus"] = self.num_gpus

    # -- planning -------------------------------------------------------
    def _cell_stale(self, child: ShockwavePlanner) -> bool:
        """Mirror of ShockwavePlanner.current_round_schedule's replan
        trigger: recompute flagged, no cached round at the cursor, or
        a cached round whose jobs all completed while incomplete jobs
        remain."""
        if child.recompute_flag or child.round_index not in child.schedules:
            return True
        schedule = child.schedules[child.round_index]
        live = [
            j
            for j in schedule
            if j in child.job_metadata
            and child.job_metadata[j].completed_epochs
            < child.job_metadata[j].total_epochs
        ]
        return not live and child._has_incomplete_jobs()

    def _needs_replan(self) -> bool:
        return any(self._cell_stale(c) for c in self.children.values())

    def current_round_schedule(self) -> list:
        """This round's fleet-wide job list. With plan-ahead pipelining
        armed, a pending speculative solve for this boundary reconciles
        first (see the hooks below); the wall time spent here on
        reconcile + any coordinated replan is the run's EXPOSED
        planning time."""
        start = time.perf_counter()
        reconciled = self._reconcile_speculation()
        if self._needs_replan():
            self._replan()
            for name, child in self.children.items():
                if name not in self._failed_cells:
                    child.recompute_flag = False
            self._observe_boundary(time.perf_counter() - start)
        elif reconciled is not None:
            self._observe_boundary(time.perf_counter() - start)
        return [
            j
            for child in self.children.values()
            for j in child.schedules.get(child.round_index, [])
        ]

    # -- plan-ahead pipelining ------------------------------------------
    # The federation speculates as a whole (one clone, one coordinated
    # replan over the predicted stale set) and reconciles per cell:
    # cells whose predicted state matches reality adopt their
    # speculative windows, churned cells alone re-solve at the boundary
    # warm-started from the speculative windows.
    # speculate_next_round / _reconcile_speculation / _observe_boundary
    # come from SpeculativePlannerMixin; the hooks below are the
    # federation's reconcile semantics.
    def _spec_solve_base(self) -> dict:
        return {
            "coord": len(self.coord_solve_records),
            "cells": {
                n: len(c.solve_records) for n, c in self.children.items()
            },
        }

    def _augment_mismatch(self, mismatch: dict) -> dict:
        """A recompute-flagged cell is churned even when the fingerprint
        math cannot see why (batch-size switch, capacity event)."""
        flagged = [
            n for n, c in self.children.items() if c.recompute_flag
        ]
        if flagged:
            mismatch = dict(mismatch)
            for name in flagged:
                mismatch.setdefault(name, []).append("recompute_flagged")
        return mismatch

    def _install_speculation(self, spec) -> None:
        """No-churn boundary: adopt the clone's coordinated-replan
        outputs wholesale — including any cross-cell decisions the
        speculative coordinator made (capacity moves, job migrations),
        which are replicated on the live federation so its topology
        matches the installed windows. The live children's measured
        predictor state stays authoritative (in simulation it equals
        the clone's by exact prediction)."""
        clone = spec.clone
        if not spec.solved:
            return  # the boundary serves every cell's cache either way
        # Migrations first (a move may be the reason capacities differ).
        for job_id, dst in list(clone.job_cell.items()):
            src = self.job_cell.get(job_id)
            if src is not None and src != dst:
                self._install_migration(job_id, src, dst)
        for name, cap in clone.cells.items():
            if name in self.cells and self.cells[name] != int(cap):
                # Direct field writes, NOT set_capacity: the installed
                # windows were solved at this capacity, so the change
                # must not re-flag the cell for another replan.
                self.cells[name] = int(cap)
                child = self.children[name]
                child.num_gpus = int(cap)
                child.config["num_gpus"] = int(cap)
        self.num_gpus = sum(self.cells.values())
        self.config["num_gpus"] = self.num_gpus
        base = spec.base_solve_records
        for name, child in self.children.items():
            cchild = clone.children.get(name)
            if cchild is None:
                continue
            child.schedules = OrderedDict(
                (r, list(s)) for r, s in cchild.schedules.items()
            )
            child.finish_time_estimates = {
                j: list(h)
                for j, h in cchild.finish_time_estimates.items()
            }
            cell_base = base["cells"].get(name, 0)
            child.solve_times.extend(cchild.solve_times[cell_base:])
            child.solve_records.extend(
                dict(r) for r in cchild.solve_records[cell_base:]
            )
            child.recompute_flag = bool(cchild.recompute_flag)
        self.coord_solve_times.extend(
            clone.coord_solve_times[base["coord"]:]
        )
        self.coord_solve_records.extend(
            dict(r) for r in clone.coord_solve_records[base["coord"]:]
        )
        self.schedules = OrderedDict(
            (r, list(s)) for r, s in clone.schedules.items()
        )
        self.prices = dict(clone.prices)
        self.spares = dict(clone.spares)
        self.imbalance_rounds = int(clone.imbalance_rounds)
        self.migrations_total = int(self.migrations_total) + max(
            0, int(clone.migrations_total) - int(self.migrations_total)
        )
        self._failed_cells = set(clone._failed_cells)

    def _install_migration(self, job_id, src: str, dst: str) -> None:
        """Replicate one speculative migration on the live federation
        (same mechanics as :meth:`_move_job`, but the recompute flags
        are governed by the install — the migrated job's window is
        already part of the installed plan)."""
        src_child, dst_child = self.children[src], self.children[dst]
        md = src_child.job_metadata.pop(job_id, None)
        if md is None:
            return
        dst_child.job_metadata[job_id] = md
        history = src_child.finish_time_estimates.pop(job_id, None)
        if history is not None:
            dst_child.finish_time_estimates[job_id] = history
        dst_child.job_overheads[job_id] = src_child.job_overheads.pop(
            job_id, 0.0
        )
        if job_id in src_child.last_round_jobs:
            src_child.last_round_jobs = [
                j for j in src_child.last_round_jobs if j != job_id
            ]
            dst_child.last_round_jobs = list(
                dst_child.last_round_jobs
            ) + [job_id]
        gang = self._cell_jobs.get(src, {}).pop(job_id, None)
        if gang is not None:
            self._load[src] = max(0.0, self._load[src] - gang)
            self._cell_jobs[dst][job_id] = gang
            self._load[dst] = self._load.get(dst, 0.0) + gang
        self.job_cell[job_id] = dst
        self.migrations_total += 1
        obs.counter(
            "cells_migrations_total", "jobs migrated between cells"
        ).inc(src=src, dst=dst)

    def _prepare_repair(self, spec, mismatch: dict) -> bool:
        """Churned boundary. Only when the federation was going to
        replan anyway: each STALE cell adopts the speculative window as
        its plan-cache warm basis (the batched boundary re-solve
        warm-starts from it through the existing
        ``_solution_warm_start`` -> ``delta_patch_counts`` path) and is
        re-flagged so it definitely re-solves against reality. Cells
        that are not stale keep their live caches untouched — the
        repair never re-plans a cell the serial boundary would have
        served from cache. The clone's cross-cell moves are discarded:
        the boundary coordinator re-decides them from live prices."""
        if not self._needs_replan():
            return False
        if spec.solved:
            for name, child in self.children.items():
                if not self._cell_stale(child):
                    continue
                cchild = spec.clone.children.get(name)
                if cchild is None:
                    continue
                # Only the window rows of jobs still owned by this live
                # cell form a valid warm basis (the clone may have
                # migrated jobs; delta_patch_counts drops strays, but
                # keeping the filter here makes the basis exact).
                child.schedules = OrderedDict(
                    (
                        r,
                        [j for j in s if j in child.job_metadata],
                    )
                    for r, s in cchild.schedules.items()
                )
                child.recompute_flag = True
        self._last_repair = True
        return True

    def current_round_schedule_by_cell(self) -> "OrderedDict[str, list]":
        self.current_round_schedule()
        return OrderedDict(
            (name, list(child.schedules.get(child.round_index, [])))
            for name, child in self.children.items()
        )

    def _slot_band(self) -> int:
        from shockwave_tpu.solver.eg_jax import num_slots_for

        return num_slots_for(
            max([1] + [c.num_jobs for c in self.children.values()])
        )

    def _mesh(self):
        if not self.use_mesh:
            return None
        import jax
        from jax.sharding import Mesh

        devices = jax.devices()
        if len(devices) <= 1:
            return None
        n = len(devices)
        lanes = batched.lane_band(len(self.cells))
        while n > 1 and lanes % n:
            n -= 1
        if n <= 1:
            return None
        return Mesh(np.array(devices[:n]), ("cells",))

    def _replan(self) -> None:
        """One coordinated planning round over the stale cells (see
        module docstring). Records exactly one coordinator-level plan
        record; replay restores the federation and re-enters here."""
        from shockwave_tpu.runtime import faults

        recorder = obs.get_recorder()
        pre_state = self.state_dict() if recorder.enabled else None
        self._replan_epoch += 1
        # A speculative clone must not CONSUME injected solver faults
        # (they belong to the live ladder) but must take the same
        # individual-vs-batched path the live boundary would, so a
        # no-churn install is decision-identical to the serial solve.
        armed = faults.active() is not None
        injector = (
            None if getattr(self, "_speculative", False) else faults.active()
        )
        replay = self._replay_stamp
        self._replay_stamp = None
        if replay is not None:
            stale = [n for n in replay["stale"] if n in self.children]
            individual = bool(replay.get("individual"))
        else:
            stale = [
                n
                for n, c in self.children.items()
                if self._cell_stale(c)
            ] or list(self.children)
            individual = armed or self.plan_deadline_s is not None
        self._failed_cells = set()

        with obs.span(
            "cells_replan", cat="plan", pid="solver", tid="cells",
            args={"round": self.round_index, "stale": len(stale)},
        ):
            built: "OrderedDict[str, tuple]" = OrderedDict()
            for name in stale:
                built[name] = self._build_cell(name)
            t0 = time.time()
            solved: Dict[str, dict] = {}
            warm_used: Dict[str, Optional[np.ndarray]] = {}
            if individual:
                self._solve_cells_individual(
                    stale, built, solved, warm_used, replay, injector
                )
                reconcile = {
                    "iterations": 0,
                    "moves": [],
                    "skipped": "replay" if replay is not None
                    else "faults_armed",
                }
                migrations: list = []
            else:
                reconcile, migrations = self._solve_cells_batched(
                    built, solved, warm_used
                )
            solve_seconds = time.time() - t0
            self._write_schedules(built, solved)
            self._finish_replan(
                pre_state, recorder, stale, individual, built, solved,
                warm_used, reconcile, migrations, solve_seconds,
            )

    def _build_cell(self, name: str):
        child = self.children[name]
        for r in [r for r in child.schedules if r < child.round_index]:
            del child.schedules[r]
        return child._build_problem()

    def _solve_cells_individual(
        self, stale, built, solved, warm_used, replay, injector
    ) -> None:
        """Per-cell solves through each child's own solve path (ladder
        when armed): an injected solver fault charges the cell whose
        solve consumed it; a cell whose ladder is exhausted is
        isolated (cached plan kept, counter bumped) instead of taking
        the round down."""
        for name in stale:
            child = self.children[name]
            problem, _job_ids = built[name]
            if problem is None:
                continue
            t0 = time.time()
            try:
                if replay is not None:
                    # Offline replay: re-enter the exact backend (and
                    # fallback flag) the live solve used — no injector
                    # runs at replay, so the ladder must not re-roll.
                    child._solve_warm_start = child._solution_warm_start()
                    child._last_ladder = None
                    backend = replay["backends"].get(
                        name, self.child_backend
                    )
                    fallback = bool(replay["fallback"].get(name, False))
                    if name in replay.get("failed", ()):
                        self._failed_cells.add(name)
                        continue
                    Y, used = child._solve_backend(
                        backend, problem, as_fallback=fallback
                    )
                else:
                    Y, used = child._solve(problem)
                    ladder = child._last_ladder
                    fallback = bool(ladder and ladder.get("degraded"))
            except Exception as e:
                seconds = time.time() - t0
                child._record_solve(
                    seconds,
                    getattr(child, "_attempted_backend", child.backend),
                    problem.num_jobs,
                    ok=False,
                    error=type(e).__name__,
                )
                self._failed_cells.add(name)
                obs.counter(
                    "cells_cell_failures_total",
                    "cell solves that exhausted every recovery rung "
                    "(cell isolated; cached plan kept)",
                ).inc(cell=name)
                obs.gauge(
                    "cells_health",
                    "1 healthy / 0.5 degraded rung / 0 failed, per cell",
                ).set(0.0, cell=name)
                continue
            seconds = time.time() - t0
            child._record_solve(seconds, used, problem.num_jobs, ok=True)
            warm_used[name] = getattr(child, "_solve_warm_start", None)
            solved[name] = {
                "Y": Y,
                "backend": used,
                "fallback": fallback,
                "seconds": seconds,
            }
            self.prices[name] = 0.0  # refreshed on the next batched round
            obs.histogram(
                "cells_cell_solve_seconds",
                "per-cell plan solve wall time (individual path)",
            ).observe(seconds, cell=name)
            obs.gauge(
                "cells_health",
                "1 healthy / 0.5 degraded rung / 0 failed, per cell",
            ).set(0.5 if fallback else 1.0, cell=name)

    def _batched_subset(self, names, built, warm_used, s_by_cell):
        """One batched dispatch over ``names``; updates ``s_by_cell``
        and the persisted prices/spares."""
        solve_names = [n for n in names if built[n][0] is not None]
        if not solve_names:
            return {}
        problems = [built[n][0] for n in solve_names]
        s0s = []
        # Re-solves within one replan (capacity moves, migrations)
        # warm-start from the in-replan iterates — a migrated job
        # carries its solved row into the destination cell's lane.
        prev_map = {
            j: float(v)
            for entry in s_by_cell.values()
            for j, v in zip(entry["ids"], entry["s"])
        }
        for n in solve_names:
            if n in s_by_cell or any(
                j in prev_map for j in built[n][1]
            ):
                s0 = np.array(
                    [prev_map.get(j, 0.0) for j in built[n][1]],
                    dtype=np.float64,
                )
            else:
                child = self.children[n]
                s0 = child._solution_warm_start()
                warm_used[n] = s0
                child._solve_warm_start = s0
            s0s.append(s0)
        s_list, objs, diags = batched.solve_cells_pdhg(
            problems,
            s0s,
            tol=self.pdhg_tol,
            max_cycles=self.cell_max_cycles,
            inner_iters=self.cell_inner_iters,
            slots=self._slot_band(),
            mesh=self._mesh(),
        )
        out = {}
        for i, n in enumerate(solve_names):
            s_by_cell[n] = {"ids": list(built[n][1]), "s": s_list[i]}
            self.prices[n] = coordinator.congestion_price(
                problems[i], s_list[i]
            )
            self.spares[n] = coordinator.spare_chips(problems[i], s_list[i])
            out[n] = {"objective": objs[i], "diag": diags[i]}
            obs.gauge(
                "cells_price",
                "congestion price (marginal welfare density per "
                "chip-round), per cell",
            ).set(self.prices[n], cell=n)
            obs.gauge(
                "cells_health",
                "1 healthy / 0.5 degraded rung / 0 failed, per cell",
            ).set(1.0, cell=n)
        return out

    def _solve_cells_batched(self, built, solved, warm_used):
        """Batched fast path + the reconciliation loop + migrations."""
        s_by_cell: Dict[str, dict] = {}
        diags = self._batched_subset(list(built), built, warm_used, s_by_cell)
        names = list(self.cells)
        moves: List[dict] = []
        for _ in range(max(0, self.reconcile_iters)):
            move = coordinator.propose_capacity_move(
                names,
                self.prices,
                self.spares,
                dict(self.cells),
                {n: self._cell_floor(n) for n in names},
                price_ratio_tol=self.price_ratio_tol,
            )
            if move is None:
                break
            self.cells[move.src] -= move.chips
            self.cells[move.dst] += move.chips
            touched = []
            for n in (move.src, move.dst):
                self.children[n].set_capacity(self.cells[n])
                if n in built and built[n][0] is not None:
                    built[n] = (
                        dataclasses.replace(
                            built[n][0], num_gpus=self.cells[n]
                        ),
                        built[n][1],
                    )
                elif n not in built:
                    built[n] = self._build_cell(n)
                touched.append(n)
                obs.gauge(
                    "cells_capacity", "chips owned, per cell"
                ).set(float(self.cells[n]), cell=n)
            diags.update(
                self._batched_subset(touched, built, warm_used, s_by_cell)
            )
            moves.append(move.as_dict())
            obs.counter(
                "cells_capacity_moves_total",
                "chips reconciled between cells",
            ).inc(move.chips)
        # Migration: only when the price spread persists across
        # replans (patience), decided among cells with fresh solves.
        spread_now = self._imbalanced()
        self.imbalance_rounds = (
            self.imbalance_rounds + 1 if spread_now else 0
        )
        migrations: List[dict] = []
        if spread_now and self.imbalance_rounds >= self.migration_patience:
            fresh = [n for n in s_by_cell]
            plan = coordinator.plan_migrations(
                fresh,
                {n: built[n][0] for n in fresh},
                {n: s_by_cell[n]["s"] for n in fresh},
                {n: s_by_cell[n]["ids"] for n in fresh},
                self.prices,
                dict(self.cells),
                max_moves=self.max_migrations,
                price_ratio_tol=self.price_ratio_tol,
            )
            if plan:
                touched = sorted({m.src for m in plan} | {m.dst for m in plan})
                for m in plan:
                    self._move_job(m)
                    migrations.append(m.as_dict())
                for n in touched:
                    built[n] = self._build_cell(n)
                    if built[n][0] is None:
                        # Every job migrated out: nothing to solve,
                        # and the pre-migration lane is stale.
                        s_by_cell.pop(n, None)
                diags.update(
                    self._batched_subset(
                        touched, built, warm_used, s_by_cell
                    )
                )
                self.imbalance_rounds = 0
        for n, entry in s_by_cell.items():
            problem = built[n][0]
            if problem is None:
                continue
            solved[n] = {
                "Y": batched.schedule_cell(problem, entry["s"]),
                "backend": "cells",
                "fallback": False,
                "seconds": 0.0,
                "objective": diags.get(n, {}).get("objective"),
                "diag": diags.get(n, {}).get("diag"),
            }
        obs.gauge(
            "cells_reconcile_iterations",
            "capacity moves applied by the last coordinated replan",
        ).set(float(len(moves)))
        obs.gauge(
            "cells_price_spread",
            "max-min congestion price across cells (imbalance signal)",
        ).set(self._price_spread())
        reconcile = {
            "iterations": len(moves),
            "moves": moves,
            "prices": {n: self.prices[n] for n in names},
            "imbalance_rounds": self.imbalance_rounds,
        }
        return reconcile, migrations

    def _price_spread(self) -> float:
        prices = [self.prices.get(n, 0.0) for n in self.cells]
        return float(max(prices) - min(prices)) if prices else 0.0

    def _imbalanced(self) -> bool:
        prices = {n: self.prices.get(n, 0.0) for n in self.cells}
        hi = max(prices.values(), default=0.0)
        lo = min(prices.values(), default=0.0)
        return hi > 0.0 and (hi - lo) >= self.price_ratio_tol * hi

    def _move_job(self, m: "coordinator.Migration") -> None:
        """Migrate one job between cells, carrying its full predictor
        state, finish-time history, measured relaunch overhead, and
        incumbency — a migrated incumbent stays an incumbent, so the
        destination market still prices dropping it."""
        src, dst = self.children[m.src], self.children[m.dst]
        md = src.job_metadata.pop(m.job, None)
        if md is None:
            return
        dst.job_metadata[m.job] = md
        history = src.finish_time_estimates.pop(m.job, None)
        if history is not None:
            dst.finish_time_estimates[m.job] = history
        dst.job_overheads[m.job] = src.job_overheads.pop(m.job, 0.0)
        if m.job in src.last_round_jobs:
            src.last_round_jobs = [
                j for j in src.last_round_jobs if j != m.job
            ]
            dst.last_round_jobs = list(dst.last_round_jobs) + [m.job]
        gang = self._cell_jobs.get(m.src, {}).pop(m.job, None)
        if gang is not None:
            self._load[m.src] = max(0.0, self._load[m.src] - gang)
            self._cell_jobs[m.dst][m.job] = gang
            self._load[m.dst] = self._load.get(m.dst, 0.0) + gang
        self.job_cell[m.job] = m.dst
        self.migrations_total += 1
        src.recompute_flag = True
        dst.recompute_flag = True
        obs.counter(
            "cells_migrations_total", "jobs migrated between cells"
        ).inc(src=m.src, dst=m.dst)
        obs.instant(
            "cell_migration", cat="plan", pid="solver", tid="cells",
            args={
                "job": str(m.job), "src": m.src, "dst": m.dst,
                "gain": m.gain, "cost": m.cost,
                "incumbent": m.incumbent,
            },
        )

    def _write_schedules(self, built, solved) -> None:
        """Post-process every solved cell exactly like the single
        planner (stickiness, backfill), write the child plan caches,
        and rebuild the merged window of THIS replan's decisions."""
        self.schedules = OrderedDict()
        for name, (problem, job_ids) in built.items():
            child = self.children[name]
            if problem is None:
                for i in range(child.future_rounds):
                    child.schedules[child.round_index + i] = []
                continue
            if name not in solved:
                continue  # failed cell: cached plan kept
            info = solved[name]
            Y = child._apply_stickiness(info["Y"], problem)
            Y = child._backfill(Y, problem)
            info["Y"] = Y
            if info.get("objective") is None:
                info["objective"] = float(problem.objective_value(Y))
            for r in range(child.future_rounds):
                child.schedules[child.round_index + r] = [
                    job_ids[j] for j in range(len(job_ids)) if Y[j, r]
                ]
        for name in built:
            child = self.children[name]
            if name in solved or built[name][0] is None:
                for r in range(child.future_rounds):
                    abs_r = child.round_index + r
                    merged = self.schedules.setdefault(abs_r, [])
                    merged.extend(child.schedules.get(abs_r, []))

    def _finish_replan(
        self, pre_state, recorder, stale, individual, built, solved,
        warm_used, reconcile, migrations, solve_seconds,
    ) -> None:
        num_jobs = sum(
            built[n][0].num_jobs
            for n in solved
            if built[n][0] is not None
        )
        record = {
            "backend": "cells",
            "seconds": solve_seconds,
            "ok": True,
            "round": self.round_index,
            "num_jobs": num_jobs,
            "stale_cells": len(stale),
            "cells": {
                n: {
                    "backend": info["backend"],
                    "degraded": info["fallback"],
                    "num_jobs": built[n][0].num_jobs,
                    **(
                        {"cycles": info["diag"]["cycles"]}
                        if info.get("diag")
                        else {}
                    ),
                }
                for n, info in solved.items()
            },
            "failed_cells": sorted(self._failed_cells),
            "reconcile": reconcile,
            "migrations": migrations,
        }
        if self._last_repair:
            # Pipelining repair: this coordinated replan re-planned the
            # churned stale cells warm-started from speculative windows.
            record["repair"] = True
            self._last_repair = False
        self.coord_solve_records.append(record)
        self.coord_solve_times.append(solve_seconds)
        obs.histogram(
            "shockwave_solve_seconds",
            "plan-solve wall time per backend (ok=False: failed solves)",
        ).observe(solve_seconds, backend="cells", ok="True")
        obs.histogram(
            "cells_coordinated_replan_seconds",
            "wall time of one coordinated (batched) cell replan",
        ).observe(solve_seconds)
        self._market_attribution(built, solved, migrations)
        if pre_state is None:
            return
        pre_state["cells_replay"] = {
            "stale": list(stale),
            "individual": bool(individual),
            "backends": {n: info["backend"] for n, info in solved.items()},
            "fallback": {n: info["fallback"] for n, info in solved.items()},
            "failed": sorted(self._failed_cells),
            "warm_starts": {
                n: (None if w is None else [float(x) for x in w])
                for n, w in warm_used.items()
            },
        }
        start = self.round_index
        plan = {
            r: list(self.schedules.get(start + r, []))
            for r in range(self.future_rounds)
        }
        objective = float(
            sum(
                info["objective"]
                for info in solved.values()
                if info.get("objective") is not None
            )
        )
        recorder.record_plan(
            planner_state=pre_state,
            plan=plan,
            backend="cells",
            objective=objective,
            solve_record=record,
            problem_summary={
                "cells": {
                    n: {
                        "job_ids": list(built[n][1]),
                        "num_gpus": int(self.cells[n]),
                    }
                    for n in solved
                },
                "num_gpus": int(self.num_gpus),
                "future_rounds": int(self.future_rounds),
            },
            tags=self._plan_record_tags,
        )

    def _market_attribution(self, built, solved, migrations) -> None:
        """Market explainability tap for the cells market: per-cell
        dual reports at the final (post-stickiness/backfill) schedules,
        fleet price gauges, and one attribution record spanning every
        re-solved cell — each job row carries its cell id, and the
        record carries the coordinator's reconcile prices and this
        replan's migrations (with their gain/cost prices). Jobs in
        cells that kept their cached plan re-enter the trail when
        their cell next goes stale. Pure reads; one boolean check when
        both the recorder and metrics are off."""
        speculative = bool(
            self._plan_record_tags
            and self._plan_record_tags.get("speculative")
        )
        recorder = obs.get_recorder()
        if not (recorder.enabled or obs.metrics_enabled()):
            return
        from shockwave_tpu.solver.duals import dual_report

        reports = {}
        for name, info in solved.items():
            problem = built[name][0]
            if problem is None:
                continue
            reports[name] = dual_report(problem, Y=info["Y"])
        fleet_price = max(
            (r.budget_dual for r in reports.values()), default=0.0
        )
        chips = {n: float(self.cells[n]) for n in reports}
        total_chips = sum(chips.values()) or 1.0
        fleet_drift = sum(
            reports[n].fairness_drift * chips[n] for n in reports
        ) / total_chips
        if not speculative:
            obs.gauge(
                "market_price",
                "fleet congestion price (budget dual) of the last plan",
            ).set(fleet_price)
            obs.gauge(
                "market_fairness_drift",
                "budget-weighted fair-share deficit of the last plan "
                "[0,1]",
            ).set(fleet_drift)
            # Per-job spend snapshot for the scheduler's tenant-spend
            # gauges (see ShockwavePlanner._market_attribution).
            self.last_market = {
                "round": int(self.round_index),
                "keys": [
                    str(j)
                    for name in reports
                    for j in built[name][1]
                ],
                "spend": [
                    float(x)
                    for name in reports
                    for x in reports[name].spend
                ],
                "price": float(fleet_price),
            }
        if not recorder.enabled or not reports:
            return
        from shockwave_tpu.obs.recorder import _job_key

        jobs = {
            "keys": [], "cell": [], "share": [], "fair_share": [],
            "welfare": [], "marginal": [], "price": [], "spend": [],
            "bonus": [], "bonus_state": [], "switch_cost": [],
            "makespan_binding": [], "predicted_finish_s": [],
        }
        for name, report in reports.items():
            problem, job_ids = built[name]
            child = self.children[name]
            bonus = problem.switch_bonus()
            granted = report.s >= 0.5
            jobs["keys"].extend(_job_key(j) for j in job_ids)
            jobs["cell"].extend([name] * len(job_ids))
            jobs["share"].extend(float(x) for x in report.s)
            jobs["fair_share"].extend(float(x) for x in report.fair_share)
            jobs["welfare"].extend(
                float(x) for x in report.welfare_contribution
            )
            jobs["marginal"].extend(
                float(x) for x in report.marginal_welfare
            )
            jobs["price"].extend(float(x) for x in report.price)
            jobs["spend"].extend(float(x) for x in report.spend)
            jobs["bonus"].extend(float(x) for x in bonus)
            jobs["bonus_state"].extend(
                ("applied" if g else "forfeited") if b > 0.0 else "none"
                for b, g in zip(bonus, granted)
            )
            jobs["switch_cost"].extend(
                float(x) for x in problem.switch_cost
            )
            jobs["makespan_binding"].extend(
                int(x) for x in report.makespan_binding
            )
            jobs["predicted_finish_s"].extend(
                float(child.finish_time_estimates[j][-1][1])
                if child.finish_time_estimates.get(j)
                else None
                for j in job_ids
            )
        detail = {
            "round": int(self.round_index),
            "backend": "cells",
            "market": {
                "budget_dual": float(fleet_price),
                "fairness_drift": float(fleet_drift),
                "cell_prices": {
                    n: float(r.budget_dual) for n, r in reports.items()
                },
                "coordinator_prices": {
                    n: float(p) for n, p in self.prices.items()
                },
            },
            "degraded": any(info["fallback"] for info in solved.values()),
            "fallback_from": None,
            "migrations": [dict(m) for m in migrations],
            "jobs": jobs,
        }
        if speculative:
            detail["speculative"] = True
        recorder.record_attribution(detail)

    # -- serialization --------------------------------------------------
    def state_dict(self) -> dict:
        return {
            "kind": "cell_set",
            "config": dict(self.config),
            "backend": self.backend,
            "round_index": self.round_index,
            "cells": OrderedDict(self.cells),
            "children": OrderedDict(
                (n, c.state_dict()) for n, c in self.children.items()
            ),
            "job_cell": dict(self.job_cell),
            "assignments": dict(self.assignments),
            "prices": dict(self.prices),
            "spares": dict(self.spares),
            "imbalance_rounds": int(self.imbalance_rounds),
            "migrations_total": int(self.migrations_total),
            "sticky_cell": self.sticky_cell,
        }

    @classmethod
    def from_state(cls, state: dict) -> "CellPlanner":
        planner = cls(state["config"], backend=state["backend"])
        planner.cells = OrderedDict(
            (n, int(c)) for n, c in state["cells"].items()
        )
        planner.children = OrderedDict(
            (n, ShockwavePlanner.from_state(cs))
            for n, cs in state["children"].items()
        )
        for n, child in planner.children.items():
            child.pool_label = n
        planner.num_gpus = sum(planner.cells.values())
        planner.job_cell = dict(state["job_cell"])
        planner.assignments = dict(state.get("assignments", {}))
        # Rebuild the O(1) load accounting from the restored children.
        planner._cell_jobs = {n: {} for n in planner.cells}
        planner._load = {n: 0.0 for n in planner.cells}
        for name, child in planner.children.items():
            for j, md in child.job_metadata.items():
                if md.completed_epochs < md.total_epochs:
                    planner._cell_jobs[name][j] = float(md.nworkers)
                    planner._load[name] += float(md.nworkers)
        planner.prices = {
            n: float(p) for n, p in state.get("prices", {}).items()
        }
        planner.spares = {
            n: int(s) for n, s in state.get("spares", {}).items()
        }
        planner.imbalance_rounds = int(state.get("imbalance_rounds", 0))
        planner.migrations_total = int(state.get("migrations_total", 0))
        planner.sticky_cell = state.get("sticky_cell")
        stamp = state.get("cells_replay")
        if stamp is not None:
            planner._replay_stamp = {
                "stale": list(stamp.get("stale", [])),
                "individual": bool(stamp.get("individual")),
                "backends": dict(stamp.get("backends", {})),
                "fallback": dict(stamp.get("fallback", {})),
                "failed": list(stamp.get("failed", [])),
            }
            for n, warm in (stamp.get("warm_starts") or {}).items():
                child = planner.children.get(n)
                if child is not None and warm is not None:
                    child._replay_warm_start = list(warm)
        return planner
