"""Throughput-sum maximization, optionally cost-normalized and with SLO
rate constraints. SLO-infeasible programs are re-solved without SLOs.
Reference: scheduler/policies/max_sum_throughput.py:1-178.
"""

from __future__ import annotations

import numpy as np

from shockwave_tpu.policies.base import (
    Policy,
    PolicyWithPacking,
    constraint_matrices,
    packed_constraint_matrices,
)
from shockwave_tpu.policies.lp_backend import max_sum_lp_general


def _max_reachable_rate(tputs: np.ndarray, caps: np.ndarray) -> float:
    """A single job's best achievable effective rate when it may split
    its one unit of time share across worker types, each capped at
    ``caps[w]`` (= min(1, num_workers/scale_factor), or 0 for cells the
    LP forces to zero): fill types in descending-throughput order."""
    order = np.argsort(-tputs)
    share_left = 1.0
    rate = 0.0
    for w in order:
        take = min(caps[w], share_left)
        if take <= 0:
            continue
        rate += float(tputs[w]) * take
        share_left -= take
        if share_left <= 0:
            break
    return rate


class ThroughputNormalizedByCostSumWithPerfSLOs(Policy):
    name = "ThroughputNormalizedByCostSum_PerfSLOs"

    def get_allocation(
        self,
        throughputs,
        scale_factors,
        cluster_spec,
        instance_costs=None,
        SLOs=None,
        num_steps_remaining=None,
    ):
        SLOs = SLOs or {}
        num_steps_remaining = num_steps_remaining or {}
        matrix, index = self.flatten(throughputs, cluster_spec)
        if matrix is None:
            return None
        m, n = matrix.shape
        job_ids, worker_types = index
        sf = self.scale_factors_array(scale_factors, job_ids, m, n)

        costs = np.ones(n)
        if instance_costs is not None:
            costs = np.array([instance_costs[wt] for wt in worker_types])
        objective = (matrix / costs[None, :]).reshape(-1)

        A_base, b_base = constraint_matrices(sf, self._num_workers)
        rows, rhs = [], []
        for job_id in SLOs:
            i = job_ids.index(job_id)
            required = num_steps_remaining[job_id] / SLOs[job_id]
            # A job whose deadline is already unreachable even with the
            # largest share the capacity constraints allow it alone
            # (time split across types, each x <= num_workers /
            # scale_factor and <= 1) would make the whole LP
            # infeasible; pruning it keeps the still-meetable deadlines
            # enforceable. (The reference instead re-solves with ALL
            # SLOs dropped on any infeasibility, reference: :91-96 —
            # one doomed job disables SLO steering for everyone.)
            cap = np.minimum(
                1.0,
                np.asarray(self._num_workers, dtype=float)
                / np.maximum(sf[i], 1e-9),
            )
            if required > _max_reachable_rate(matrix[i], cap) + 1e-12:
                continue
            row = np.zeros(m * n)
            row[i * n : (i + 1) * n] = -matrix[i]
            rows.append(row)
            rhs.append(-required)
        if rows:
            A = np.vstack([A_base, np.array(rows)])
            b = np.concatenate([b_base, np.array(rhs)])
            x = max_sum_lp_general(objective, A, b)
            if x is None:
                # Aggregate contention still unsatisfiable: drop SLOs
                # (reference: :91-96).
                x = max_sum_lp_general(objective, A_base, b_base)
        else:
            x = max_sum_lp_general(objective, A_base, b_base)
        if x is None:
            return None
        return self.unflatten(x.reshape(m, n).clip(0.0, 1.0), index)


class ThroughputSumWithPerf(Policy):
    name = "ThroughputSumWithPerf"

    def __init__(self, solver=None):
        super().__init__(solver)
        self._policy = ThroughputNormalizedByCostSumWithPerfSLOs(solver)

    def get_allocation(self, throughputs, scale_factors, cluster_spec):
        return self._policy.get_allocation(throughputs, scale_factors, cluster_spec)


class ThroughputNormalizedByCostSumWithPerf(Policy):
    name = "ThroughputNormalizedByCostSum_Perf"

    def __init__(self, solver=None):
        super().__init__(solver)
        self._policy = ThroughputNormalizedByCostSumWithPerfSLOs(solver)

    def get_allocation(
        self, throughputs, scale_factors, cluster_spec, instance_costs=None
    ):
        return self._policy.get_allocation(
            throughputs, scale_factors, cluster_spec, instance_costs=instance_costs
        )


class ThroughputNormalizedByCostSumWithPackingSLOs(PolicyWithPacking):
    name = "ThroughputNormalizedByCostSum_PackingSLOs"

    def get_allocation(
        self,
        throughputs,
        scale_factors,
        cluster_spec,
        instance_costs=None,
        SLOs=None,
        num_steps_remaining=None,
    ):
        SLOs = SLOs or {}
        num_steps_remaining = num_steps_remaining or {}
        all_m, index = self.flatten(throughputs, cluster_spec)
        if all_m is None or len(all_m) == 0:
            return None
        job_ids, single_job_ids, worker_types, relevant = index
        C, W = len(job_ids), len(worker_types)
        S = len(single_job_ids)
        sf = self.scale_factors_array(scale_factors, job_ids, C, W)

        costs = np.ones(W)
        if instance_costs is not None:
            costs = np.array([instance_costs[wt] for wt in worker_types])
        # Per-single effective throughput summed across the singles gives a
        # per-cell objective (reference: :131-148).
        objective = (all_m / costs[None, None, :]).sum(axis=0).reshape(-1)

        A_base, b_base = packed_constraint_matrices(
            sf, self._num_workers, single_job_ids, relevant
        )
        zero_mask = (sf.reshape(-1) == 0).astype(bool)
        rows, rhs = [], []
        coeff = all_m.reshape(S, C * W)
        cap = np.minimum(
            1.0,
            np.asarray(self._num_workers, dtype=float)[None, :]
            / np.maximum(sf, 1e-9),
        ).reshape(-1)
        # Cells the LP pins to zero (mixed-scale pairs) can't contribute.
        cap[zero_mask] = 0.0
        for job_id in SLOs:
            i = single_job_ids.index(job_id)
            required = num_steps_remaining[job_id] / SLOs[job_id]
            # Same doomed-deadline pruning as the unpacked variant.
            if required > _max_reachable_rate(coeff[i], cap) + 1e-12:
                continue
            rows.append(-coeff[i])
            rhs.append(-required)
        if rows:
            A = np.vstack([A_base, np.array(rows)])
            b = np.concatenate([b_base, np.array(rhs)])
            x = max_sum_lp_general(objective, A, b, zero_mask=zero_mask)
            if x is None:
                x = max_sum_lp_general(
                    objective, A_base, b_base, zero_mask=zero_mask
                )
        else:
            x = max_sum_lp_general(objective, A_base, b_base, zero_mask=zero_mask)
        if x is None:
            return None
        return self.unflatten(x.reshape(C, W).clip(0.0, 1.0), index)
