"""Finish-time fairness (Themis): minimize the worst rho across jobs,
rho = expected completion time under the allocation / expected completion
time under an isolated (equal-split) cluster share. Stateful across rounds:
each job's realized isolated time accumulates from observed step progress.
Reference: scheduler/policies/finish_time_fairness.py:1-250.

The reference solves min max_i (t_i + S_i / a_i(x)) / E_i with cvxpy's
inv_pos (a convex program). Here the same optimum is found by bisection on
rho: for fixed rho the constraint set {a_i(x) >= S_i / (rho * E_i - t_i)}
is a feasibility LP (HiGHS), and rho* is the smallest feasible rho —
exact, solver-native, and reusing the shared LP backend.
"""

from __future__ import annotations

import copy
from typing import Dict

import numpy as np

from shockwave_tpu.policies.base import (
    Policy,
    PolicyWithPacking,
    constraint_matrices,
    packed_constraint_matrices,
)
from shockwave_tpu.policies.isolated import IsolatedPolicy
from shockwave_tpu.policies.lp_backend import feasibility_lp_general


def _bisect_rho(coeff_rows, times_since_start, num_steps, isolated_times,
                A_base, b_base, zero_mask=None, tol=1e-3, max_iter=60):
    """Smallest rho with a feasible allocation; returns (rho, x)."""
    t = np.asarray(times_since_start, dtype=np.float64)
    S = np.asarray(num_steps, dtype=np.float64)
    E = np.asarray(isolated_times, dtype=np.float64)

    def rates_for(rho):
        # a_i >= S_i / (rho * E_i - t_i); infeasible if rho * E_i <= t_i
        # for a job that still has steps left.
        denom = rho * E - t
        if np.any((denom <= 0) & (S > 0)):
            return None
        with np.errstate(divide="ignore"):
            return np.where(S > 0, S / np.maximum(denom, 1e-12), 0.0)

    def solve(rho):
        rates = rates_for(rho)
        if rates is None:
            return None
        return feasibility_lp_general(
            coeff_rows, rates, A_base, b_base, zero_mask=zero_mask
        )

    lo, hi = 0.0, 1.0
    x_hi = solve(hi)
    for _ in range(60):
        if x_hi is not None:
            break
        lo, hi = hi, hi * 2.0
        x_hi = solve(hi)
    if x_hi is None:
        return None, None
    for _ in range(max_iter):
        if hi - lo <= tol * max(1.0, hi):
            break
        mid = 0.5 * (lo + hi)
        x_mid = solve(mid)
        if x_mid is not None:
            hi, x_hi = mid, x_mid
        else:
            lo = mid
    return hi, x_hi


class FinishTimeFairnessPolicyWithPerf(Policy):
    name = "FinishTimeFairness_Perf"

    def __init__(self, solver=None):
        super().__init__(solver)
        self._isolated_policy = IsolatedPolicy()
        self._cumulative_isolated_time: Dict = {}
        self._isolated_throughputs_prev_iteration: Dict = {}
        self._num_steps_remaining_prev_iteration: Dict = {}

    def get_allocation(
        self,
        throughputs,
        scale_factors,
        priority_weights,
        times_since_start,
        num_steps_remaining,
        cluster_spec,
    ):
        matrix, index = self.flatten(throughputs, cluster_spec)
        if matrix is None:
            self._isolated_throughputs_prev_iteration = {}
            self._num_steps_remaining_prev_iteration = {}
            return None
        m, n = matrix.shape
        job_ids, _ = index
        sf = self.scale_factors_array(scale_factors, job_ids, m, n)
        isolated_throughputs = self._isolated_policy.get_throughputs(
            matrix, index, scale_factors, self._num_workers
        ).reshape(-1)

        # Accumulate each job's realized isolated time from the progress
        # observed since the last call (reference: ftf.py:98-105).
        expected_isolated = np.zeros(m)
        for i, job_id in enumerate(job_ids):
            self._cumulative_isolated_time.setdefault(job_id, 0)
            if job_id in self._num_steps_remaining_prev_iteration:
                self._cumulative_isolated_time[job_id] += (
                    self._num_steps_remaining_prev_iteration[job_id]
                    - num_steps_remaining[job_id]
                ) / self._isolated_throughputs_prev_iteration[job_id]
            expected_isolated[i] = self._cumulative_isolated_time[job_id] + (
                num_steps_remaining[job_id] / isolated_throughputs[i]
            )

        coeff_rows = np.zeros((m, m * n))
        for i in range(m):
            coeff_rows[i, i * n : (i + 1) * n] = matrix[i]
        A_base, b_base = constraint_matrices(sf, self._num_workers)
        _, x = _bisect_rho(
            coeff_rows,
            [times_since_start[j] for j in job_ids],
            [num_steps_remaining[j] for j in job_ids],
            expected_isolated,
            A_base,
            b_base,
        )

        self._num_steps_remaining_prev_iteration = copy.copy(num_steps_remaining)
        self._isolated_throughputs_prev_iteration = {
            job_ids[i]: isolated_throughputs[i] for i in range(m)
        }

        if x is None:
            # Mirror the reference's fallback to the isolated allocation
            # (ftf.py:139-142).
            return self._isolated_policy.get_allocation(
                throughputs, scale_factors, cluster_spec
            )
        return self.unflatten(x.reshape(m, n).clip(0.0, 1.0), index)


class FinishTimeFairnessPolicy(Policy):
    """Throughput-agnostic wrapper: every worker type behaves like v100
    (reference: ftf.py:22-52)."""

    name = "FinishTimeFairness"

    def __init__(self, solver=None):
        super().__init__(solver)
        self._perf_policy = FinishTimeFairnessPolicyWithPerf(solver)

    def get_allocation(
        self,
        throughputs,
        scale_factors,
        priority_weights,
        times_since_start,
        num_steps_remaining,
        cluster_spec,
    ):
        from shockwave_tpu.policies.base import canonical_throughputs

        flat = canonical_throughputs(throughputs)
        return self._perf_policy.get_allocation(
            flat,
            scale_factors,
            priority_weights,
            times_since_start,
            num_steps_remaining,
            cluster_spec,
        )


class FinishTimeFairnessPolicyWithPacking(PolicyWithPacking):
    name = "FinishTimeFairness_Packing"

    def __init__(self, solver=None):
        super().__init__(solver)
        self._isolated_policy = IsolatedPolicy()
        self._cumulative_isolated_time: Dict = {}
        self._isolated_throughputs_prev_iteration: Dict = {}
        self._num_steps_remaining_prev_iteration: Dict = {}

    def get_allocation(
        self,
        throughputs,
        scale_factors,
        priority_weights,
        times_since_start,
        num_steps_remaining,
        cluster_spec,
    ):
        all_m, index = self.flatten(
            throughputs, cluster_spec, priority_weights=priority_weights
        )
        if all_m is None or len(all_m) == 0:
            self._isolated_throughputs_prev_iteration = {}
            self._num_steps_remaining_prev_iteration = {}
            return None
        job_ids, single_job_ids, worker_types, relevant = index
        C, W = len(job_ids), len(worker_types)
        S = len(single_job_ids)
        sf = self.scale_factors_array(scale_factors, job_ids, C, W)

        singles_matrix = np.array(
            [[throughputs[s][wt] for wt in worker_types] for s in single_job_ids]
        )
        isolated_throughputs = self._isolated_policy.get_throughputs(
            singles_matrix,
            (single_job_ids, worker_types),
            scale_factors,
            self._num_workers,
        ).reshape(-1)

        expected_isolated = np.zeros(S)
        for i, job_id in enumerate(single_job_ids):
            self._cumulative_isolated_time.setdefault(job_id, 0)
            if job_id in self._num_steps_remaining_prev_iteration:
                self._cumulative_isolated_time[job_id] += (
                    self._num_steps_remaining_prev_iteration[job_id]
                    - num_steps_remaining[job_id]
                ) / self._isolated_throughputs_prev_iteration[job_id]
            expected_isolated[i] = self._cumulative_isolated_time[job_id] + (
                num_steps_remaining[job_id] / isolated_throughputs[i]
            )

        coeff_rows = all_m.reshape(S, C * W)
        A_base, b_base = packed_constraint_matrices(
            sf, self._num_workers, single_job_ids, relevant
        )
        zero_mask = (sf.reshape(-1) == 0).astype(bool)
        _, x = _bisect_rho(
            coeff_rows,
            [times_since_start[s] for s in single_job_ids],
            [num_steps_remaining[s] for s in single_job_ids],
            expected_isolated,
            A_base,
            b_base,
            zero_mask=zero_mask,
        )

        self._num_steps_remaining_prev_iteration = copy.copy(num_steps_remaining)
        self._isolated_throughputs_prev_iteration = {
            single_job_ids[i]: isolated_throughputs[i] for i in range(S)
        }
        if x is None:
            return None
        return self.unflatten(x.reshape(C, W).clip(0.0, 1.0), index)
