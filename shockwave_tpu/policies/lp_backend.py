"""LP backends for the LP-shaped Gavel policies.

``scipy`` (HiGHS) is the exact CPU backend — the stand-in for the
reference's ECOS/GUROBI cvxpy solves. A JAX backend (shared with the
Shockwave EG solver in :mod:`shockwave_tpu.solver`) can be selected with
``solver="jax"`` for on-device solves; it returns an eps-feasible point of
the same program.
"""

from __future__ import annotations

from typing import Sequence

import numpy as np
from scipy.optimize import linprog

from shockwave_tpu.policies.base import constraint_matrices


def max_min_lp(
    coeffs: np.ndarray,
    scale_factors_array: np.ndarray,
    num_workers: Sequence[int],
    backend: str = "scipy",
) -> np.ndarray:
    """maximize  min_j sum_w coeffs[j,w] * x[j,w]  over the base polytope.

    This is the core of max-min fairness (reference:
    scheduler/policies/max_min_fairness.py:44-100, where coeffs =
    throughput * priority * scale_factor).
    """
    if backend == "jax":
        from shockwave_tpu.solver.lp_jax import max_min_lp_jax

        return max_min_lp_jax(coeffs, scale_factors_array, np.asarray(num_workers))
    m, n = coeffs.shape
    # Variables: vec(x) followed by t; maximize t.
    A_base, b_base = constraint_matrices(scale_factors_array, num_workers)
    A_ub = np.zeros((A_base.shape[0] + m, m * n + 1))
    A_ub[: A_base.shape[0], : m * n] = A_base
    b_ub = np.concatenate([b_base, np.zeros(m)])
    # t - coeffs[j] . x[j] <= 0
    for j in range(m):
        A_ub[A_base.shape[0] + j, j * n : (j + 1) * n] = -coeffs[j]
        A_ub[A_base.shape[0] + j, -1] = 1.0
    c = np.zeros(m * n + 1)
    c[-1] = -1.0
    bounds = [(0, None)] * (m * n) + [(None, None)]
    res = linprog(c, A_ub=A_ub, b_ub=b_ub, bounds=bounds, method="highs")
    if not res.success:
        raise RuntimeError(f"max_min LP failed: {res.message}")
    return res.x[: m * n].reshape(m, n)


def feasibility_lp(
    rate_requirements: np.ndarray,
    coeffs: np.ndarray,
    scale_factors_array: np.ndarray,
    num_workers: Sequence[int],
) -> np.ndarray | None:
    """Find x in the base polytope with coeffs[j].x[j] >= rate_requirements[j]
    for every job, or None if infeasible. Used by makespan-minimization's
    binary search (reference: scheduler/policies/min_total_duration.py:46-59).
    """
    m, n = coeffs.shape
    A_base, b_base = constraint_matrices(scale_factors_array, num_workers)
    A_req = np.zeros((m, m * n))
    for j in range(m):
        A_req[j, j * n : (j + 1) * n] = -coeffs[j]
    A_ub = np.vstack([A_base, A_req])
    b_ub = np.concatenate([b_base, -rate_requirements])
    res = linprog(
        np.zeros(m * n), A_ub=A_ub, b_ub=b_ub, bounds=[(0, None)] * (m * n),
        method="highs",
    )
    if not res.success:
        return None
    return res.x.reshape(m, n)


def max_sum_lp(
    objective_coeffs: np.ndarray,
    scale_factors_array: np.ndarray,
    num_workers: Sequence[int],
    extra_A_ub: np.ndarray | None = None,
    extra_b_ub: np.ndarray | None = None,
) -> np.ndarray | None:
    """maximize sum_jw objective_coeffs[j,w] * x[j,w] over the base polytope
    (plus optional extra rows over vec(x)); None if infeasible."""
    m, n = objective_coeffs.shape
    A_ub, b_ub = constraint_matrices(scale_factors_array, num_workers)
    if extra_A_ub is not None:
        A_ub = np.vstack([A_ub, extra_A_ub])
        b_ub = np.concatenate([b_ub, extra_b_ub])
    res = linprog(
        -objective_coeffs.reshape(-1),
        A_ub=A_ub,
        b_ub=b_ub,
        bounds=[(0, None)] * (m * n),
        method="highs",
    )
    if not res.success:
        return None
    return res.x.reshape(m, n)
