"""LP backends for the LP-shaped Gavel policies.

scipy's HiGHS is the solver — the stand-in for the reference's
ECOS/GUROBI cvxpy solves. These programs are small (jobs x worker types)
and run once per allocation update on the host; the on-device JAX path is
reserved for the Shockwave planning solver, where the scale lives
(:mod:`shockwave_tpu.solver.eg_jax`).

The ``*_general`` forms take arbitrary objective rows over vec(x) plus a
prebuilt (A_base, b_base) polytope, which is what the packed policies need
(an objective row spans every (combination, worker) cell a job appears
in); the simpler wrappers below build the standard per-job rows over the
base polytope and delegate.

Failure contract: all solvers return None when the program is infeasible
or the solver fails; callers decide between fallback and raise.
"""

from __future__ import annotations

from typing import Sequence

import numpy as np
from scipy.optimize import linprog

from shockwave_tpu.policies.base import constraint_matrices


def _bounds(n_var: int, zero_mask: np.ndarray | None):
    if zero_mask is None:
        return [(0, None)] * n_var
    return [(0, 0) if zero_mask[i] else (0, None) for i in range(n_var)]


def max_min_lp_general(
    coeff_rows: np.ndarray,
    A_base: np.ndarray,
    b_base: np.ndarray,
    zero_mask: np.ndarray | None = None,
) -> np.ndarray | None:
    """maximize min_s coeff_rows[s] . x over {A_base x <= b_base, x >= 0}.

    ``zero_mask`` flags variables pinned to zero (e.g. mixed-scale pairs).
    """
    S, n_var = coeff_rows.shape
    A_ub = np.zeros((A_base.shape[0] + S, n_var + 1))
    A_ub[: A_base.shape[0], :n_var] = A_base
    b_ub = np.concatenate([b_base, np.zeros(S)])
    for s in range(S):
        A_ub[A_base.shape[0] + s, :n_var] = -coeff_rows[s]
        A_ub[A_base.shape[0] + s, -1] = 1.0
    c = np.zeros(n_var + 1)
    c[-1] = -1.0
    bounds = _bounds(n_var, zero_mask) + [(None, None)]
    res = linprog(c, A_ub=A_ub, b_ub=b_ub, bounds=bounds, method="highs")
    if not res.success:
        return None
    return res.x[:n_var]


def feasibility_lp_general(
    coeff_rows: np.ndarray,
    rates: np.ndarray,
    A_base: np.ndarray,
    b_base: np.ndarray,
    zero_mask: np.ndarray | None = None,
) -> np.ndarray | None:
    """Find x >= 0 with A_base x <= b_base and coeff_rows[s] . x >= rates[s]
    for every s, or None."""
    S, n_var = coeff_rows.shape
    A_ub = np.vstack([A_base, -coeff_rows])
    b_ub = np.concatenate([b_base, -np.asarray(rates, dtype=np.float64)])
    res = linprog(
        np.zeros(n_var),
        A_ub=A_ub,
        b_ub=b_ub,
        bounds=_bounds(n_var, zero_mask),
        method="highs",
    )
    if not res.success:
        return None
    return res.x


def max_sum_lp_general(
    objective: np.ndarray,
    A_base: np.ndarray,
    b_base: np.ndarray,
    zero_mask: np.ndarray | None = None,
) -> np.ndarray | None:
    """maximize objective . x over {A_base x <= b_base, x >= 0}; None if
    infeasible."""
    n_var = len(objective)
    res = linprog(
        -np.asarray(objective, dtype=np.float64),
        A_ub=A_base,
        b_ub=b_base,
        bounds=_bounds(n_var, zero_mask),
        method="highs",
    )
    if not res.success:
        return None
    return res.x


def _per_job_rows(coeffs: np.ndarray) -> np.ndarray:
    """Block-diagonal objective rows: row j covers x[j, :] only."""
    m, n = coeffs.shape
    rows = np.zeros((m, m * n))
    for j in range(m):
        rows[j, j * n : (j + 1) * n] = coeffs[j]
    return rows


def max_min_lp(
    coeffs: np.ndarray,
    scale_factors_array: np.ndarray,
    num_workers: Sequence[int],
    backend: str = "scipy",
) -> np.ndarray:
    """maximize  min_j sum_w coeffs[j,w] * x[j,w]  over the base polytope.

    This is the core of max-min fairness (reference:
    scheduler/policies/max_min_fairness.py:44-100, where coeffs =
    throughput * priority * scale_factor). Raises on solver failure (the
    base polytope is never empty, so failure is exceptional).
    """
    m, n = coeffs.shape
    A_base, b_base = constraint_matrices(scale_factors_array, num_workers)
    x = max_min_lp_general(_per_job_rows(coeffs), A_base, b_base)
    if x is None:
        raise RuntimeError("max_min LP failed")
    return x.reshape(m, n)


def feasibility_lp(
    rate_requirements: np.ndarray,
    coeffs: np.ndarray,
    scale_factors_array: np.ndarray,
    num_workers: Sequence[int],
) -> np.ndarray | None:
    """Find x in the base polytope with coeffs[j].x[j] >= rate_requirements[j]
    for every job, or None if infeasible. Used by makespan-minimization's
    binary search (reference: scheduler/policies/min_total_duration.py:46-59).
    """
    m, n = coeffs.shape
    A_base, b_base = constraint_matrices(scale_factors_array, num_workers)
    x = feasibility_lp_general(
        _per_job_rows(coeffs), rate_requirements, A_base, b_base
    )
    if x is None:
        return None
    return x.reshape(m, n)


def max_sum_lp(
    objective_coeffs: np.ndarray,
    scale_factors_array: np.ndarray,
    num_workers: Sequence[int],
    extra_A_ub: np.ndarray | None = None,
    extra_b_ub: np.ndarray | None = None,
) -> np.ndarray | None:
    """maximize sum_jw objective_coeffs[j,w] * x[j,w] over the base polytope
    (plus optional extra rows over vec(x)); None if infeasible."""
    m, n = objective_coeffs.shape
    A_ub, b_ub = constraint_matrices(scale_factors_array, num_workers)
    if extra_A_ub is not None:
        A_ub = np.vstack([A_ub, extra_A_ub])
        b_ub = np.concatenate([b_ub, extra_b_ub])
    x = max_sum_lp_general(objective_coeffs.reshape(-1), A_ub, b_ub)
    if x is None:
        return None
    return x.reshape(m, n)
