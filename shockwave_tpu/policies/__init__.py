"""Policy registry: name -> policy factory.

Names match the reference's CLI vocabulary (reference:
scheduler/utils.py:484-551) plus the TPU-native ``shockwave_tpu``.
"""

from __future__ import annotations

from typing import Optional

from shockwave_tpu.policies.base import Policy
from shockwave_tpu.policies.fifo import (
    FIFOPolicy,
    FIFOPolicyWithPacking,
    FIFOPolicyWithPerf,
)
from shockwave_tpu.policies.isolated import IsolatedPolicy, ProportionalPolicy
from shockwave_tpu.policies.max_min_fairness import (
    MaxMinFairnessPolicy,
    MaxMinFairnessPolicyWithPerf,
)


def get_policy(
    policy_name: str,
    solver: Optional[str] = None,
    seed: Optional[int] = None,
    priority_reweighting_policies=None,
) -> Policy:
    if policy_name.startswith("allox"):
        from shockwave_tpu.policies.allox import AlloXPolicy

        alpha = 1.0
        if policy_name != "allox":
            alpha = float(policy_name.split("allox_alpha=")[1])
        return AlloXPolicy(alpha=alpha)
    if policy_name == "fifo":
        return FIFOPolicy(seed=seed)
    if policy_name == "fifo_perf":
        return FIFOPolicyWithPerf()
    if policy_name == "fifo_packed":
        return FIFOPolicyWithPacking()
    if policy_name == "gandiva":
        from shockwave_tpu.policies.gandiva import GandivaPolicy

        return GandivaPolicy(seed=seed)
    if policy_name == "isolated":
        return IsolatedPolicy()
    if policy_name == "max_min_fairness":
        return MaxMinFairnessPolicy(solver=solver)
    if policy_name == "max_min_fairness_perf":
        return MaxMinFairnessPolicyWithPerf(solver=solver)
    if policy_name == "max_min_fairness_packed":
        from shockwave_tpu.policies.max_min_fairness_packed import (
            MaxMinFairnessPolicyWithPacking,
        )

        return MaxMinFairnessPolicyWithPacking(solver=solver)
    if policy_name.startswith("max_min_fairness_water_filling"):
        from shockwave_tpu.policies.water_filling import (
            MaxMinFairnessWaterFillingPolicy,
            MaxMinFairnessWaterFillingPolicyWithPacking,
            MaxMinFairnessWaterFillingPolicyWithPerf,
        )

        cls = {
            "max_min_fairness_water_filling": MaxMinFairnessWaterFillingPolicy,
            "max_min_fairness_water_filling_perf": MaxMinFairnessWaterFillingPolicyWithPerf,
            "max_min_fairness_water_filling_packed": MaxMinFairnessWaterFillingPolicyWithPacking,
        }[policy_name]
        return cls(priority_reweighting_policies=priority_reweighting_policies)
    if policy_name == "max_min_fairness_strategy_proof":
        from shockwave_tpu.policies.strategy_proof import (
            MaxMinFairnessStrategyProofPolicyWithPerf,
        )

        return MaxMinFairnessStrategyProofPolicyWithPerf(solver=solver)
    if policy_name == "finish_time_fairness":
        from shockwave_tpu.policies.finish_time_fairness import (
            FinishTimeFairnessPolicy,
        )

        return FinishTimeFairnessPolicy(solver=solver)
    if policy_name == "finish_time_fairness_perf":
        from shockwave_tpu.policies.finish_time_fairness import (
            FinishTimeFairnessPolicyWithPerf,
        )

        return FinishTimeFairnessPolicyWithPerf(solver=solver)
    if policy_name == "finish_time_fairness_packed":
        from shockwave_tpu.policies.finish_time_fairness import (
            FinishTimeFairnessPolicyWithPacking,
        )

        return FinishTimeFairnessPolicyWithPacking(solver=solver)
    if policy_name == "max_sum_throughput_perf":
        from shockwave_tpu.policies.max_sum_throughput import ThroughputSumWithPerf

        return ThroughputSumWithPerf(solver=solver)
    if policy_name == "max_sum_throughput_normalized_by_cost_perf":
        from shockwave_tpu.policies.max_sum_throughput import (
            ThroughputNormalizedByCostSumWithPerf,
        )

        return ThroughputNormalizedByCostSumWithPerf(solver=solver)
    if policy_name == "max_sum_throughput_normalized_by_cost_perf_SLOs":
        from shockwave_tpu.policies.max_sum_throughput import (
            ThroughputNormalizedByCostSumWithPerfSLOs,
        )

        return ThroughputNormalizedByCostSumWithPerfSLOs(solver=solver)
    if policy_name == "max_sum_throughput_normalized_by_cost_packed_SLOs":
        from shockwave_tpu.policies.max_sum_throughput import (
            ThroughputNormalizedByCostSumWithPackingSLOs,
        )

        return ThroughputNormalizedByCostSumWithPackingSLOs(solver=solver)
    if policy_name == "min_total_duration":
        from shockwave_tpu.policies.min_total_duration import MinTotalDurationPolicy

        return MinTotalDurationPolicy(solver=solver)
    if policy_name == "min_total_duration_perf":
        from shockwave_tpu.policies.min_total_duration import (
            MinTotalDurationPolicyWithPerf,
        )

        return MinTotalDurationPolicyWithPerf(solver=solver)
    if policy_name == "min_total_duration_packed":
        from shockwave_tpu.policies.min_total_duration import (
            MinTotalDurationPolicyWithPacking,
        )

        return MinTotalDurationPolicyWithPacking(solver=solver)
    if policy_name == "shockwave":
        from shockwave_tpu.policies.shockwave import ShockwavePolicy

        return ShockwavePolicy(backend="reference")
    if policy_name == "shockwave_tpu":
        from shockwave_tpu.policies.shockwave import ShockwavePolicy

        return ShockwavePolicy(backend="tpu")
    if policy_name == "shockwave_native":
        from shockwave_tpu.policies.shockwave import ShockwavePolicy

        return ShockwavePolicy(backend="native")
    if policy_name == "shockwave_tpu_level":
        from shockwave_tpu.policies.shockwave import ShockwavePolicy

        return ShockwavePolicy(backend="level")
    if policy_name == "shockwave_tpu_relaxed":
        from shockwave_tpu.policies.shockwave import ShockwavePolicy

        return ShockwavePolicy(backend="relaxed")
    if policy_name == "shockwave_tpu_sharded":
        from shockwave_tpu.policies.shockwave import ShockwavePolicy

        return ShockwavePolicy(backend="sharded")
    if policy_name == "shockwave_tpu_pdhg":
        from shockwave_tpu.policies.shockwave import ShockwavePolicy

        return ShockwavePolicy(backend="pdhg")
    if policy_name == "shockwave_tpu_cells":
        from shockwave_tpu.policies.shockwave import ShockwavePolicy

        return ShockwavePolicy(backend="cells")
    raise ValueError(f"Unknown policy: {policy_name!r}")


# Full target vocabulary (parity with reference utils.py:484-551 plus the
# TPU-native shockwave_tpu). Only names whose modules exist are advertised.
_ALL_POLICY_NAMES = [
    "allox",
    "fifo",
    "fifo_perf",
    "fifo_packed",
    "finish_time_fairness",
    "finish_time_fairness_perf",
    "finish_time_fairness_packed",
    "gandiva",
    "isolated",
    "max_min_fairness",
    "max_min_fairness_perf",
    "max_min_fairness_packed",
    "max_min_fairness_water_filling",
    "max_min_fairness_water_filling_perf",
    "max_min_fairness_water_filling_packed",
    "max_min_fairness_strategy_proof",
    "max_sum_throughput_perf",
    "max_sum_throughput_normalized_by_cost_perf",
    "max_sum_throughput_normalized_by_cost_perf_SLOs",
    "max_sum_throughput_normalized_by_cost_packed_SLOs",
    "min_total_duration",
    "min_total_duration_perf",
    "min_total_duration_packed",
    "shockwave",
    "shockwave_tpu",
    "shockwave_native",
    "shockwave_tpu_level",
    "shockwave_tpu_relaxed",
    "shockwave_tpu_sharded",
    "shockwave_tpu_pdhg",
    "shockwave_tpu_cells",
]

_POLICY_MODULES = {
    "allox": "allox",
    "gandiva": "gandiva",
    "finish_time_fairness": "finish_time_fairness",
    "max_min_fairness_packed": "max_min_fairness_packed",
    "max_min_fairness_water_filling": "water_filling",
    "max_min_fairness_strategy_proof": "strategy_proof",
    "max_sum_throughput": "max_sum_throughput",
    "min_total_duration": "min_total_duration",
    "shockwave": "shockwave",
    "shockwave_tpu": "shockwave",
}


def _module_exists(name: str) -> bool:
    import importlib.util

    spec = importlib.util.find_spec(f"shockwave_tpu.policies.{name}")
    return spec is not None


def get_available_policies():
    available = []
    for name in _ALL_POLICY_NAMES:
        module = None
        for prefix, mod in _POLICY_MODULES.items():
            if name.startswith(prefix):
                module = mod
                break
        if module is None or _module_exists(module):
            available.append(name)
    return available


__all__ = [
    "Policy",
    "get_policy",
    "get_available_policies",
    "FIFOPolicy",
    "FIFOPolicyWithPerf",
    "FIFOPolicyWithPacking",
    "IsolatedPolicy",
    "ProportionalPolicy",
    "MaxMinFairnessPolicy",
    "MaxMinFairnessPolicyWithPerf",
]
