"""Max-min fairness (Least Attained Service) — Gavel's headline policy.

Maximizes the minimum, over jobs, of priority-normalized effective
throughput. The throughput-agnostic variant runs the same program with all
throughputs set to 1 (pure time shares). Reference:
scheduler/policies/max_min_fairness.py:12-100.
"""

from __future__ import annotations

import numpy as np

from shockwave_tpu.policies.base import Policy
from shockwave_tpu.policies.isolated import ProportionalPolicy
from shockwave_tpu.policies.lp_backend import max_min_lp


class MaxMinFairnessPolicyWithPerf(Policy):
    name = "MaxMinFairness_Perf"

    def __init__(self, solver=None):
        super().__init__(solver)
        self._proportional = ProportionalPolicy()

    def get_allocation(
        self, throughputs, scale_factors, priority_weights, cluster_spec
    ):
        matrix, index = self.flatten(throughputs, cluster_spec)
        if matrix is None:
            return None
        m, n = matrix.shape
        job_ids, _ = index
        sf = self.scale_factors_array(scale_factors, job_ids, m, n)

        # Normalize by priority and by the job's proportional-share
        # throughput so "fair" means equal progress relative to an equal
        # split, and multiply by scale_factor so gang jobs are not charged
        # per-GPU (reference: max_min_fairness.py:60-90).
        inv_priority = np.array(
            [1.0 / priority_weights[j] for j in job_ids]
        ).reshape((m, 1))
        proportional = self._proportional.get_throughputs(
            matrix, index, self._num_workers
        )
        coeffs = matrix * inv_priority / proportional * sf
        x = max_min_lp(coeffs, sf, self._num_workers, backend=self.solver)
        return self.unflatten(x.clip(0.0, 1.0), index)


class MaxMinFairnessPolicy(Policy):
    name = "MaxMinFairness"

    def __init__(self, solver=None):
        super().__init__(solver)
        self._perf_policy = MaxMinFairnessPolicyWithPerf(solver)

    def get_allocation(
        self, throughputs, scale_factors, priority_weights, cluster_spec
    ):
        flat = {
            job_id: {wt: 1.0 for wt in throughputs[job_id]}
            for job_id in throughputs
        }
        return self._perf_policy.get_allocation(
            flat, scale_factors, priority_weights, cluster_spec
        )
