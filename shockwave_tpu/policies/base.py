"""Allocation-policy base machinery.

A policy maps a throughput matrix (jobs x worker types) plus per-job scale
factors to a fractional time-share allocation ``{job_id: {worker_type:
fraction}}`` subject to the cluster's capacity. Shapes and constraint
semantics match the reference (reference: scheduler/policies/policy.py:11-63):

  x >= 0
  sum_j scale_factor_j * x[j, w] <= num_workers[w]   (capacity per type)
  sum_w x[j, w] <= 1                                 (a job's total share)
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from shockwave_tpu.core.ids import JobId

Allocation = Dict[JobId, Dict[str, float]]


class Policy:
    """Base class: flatten/unflatten between dict-of-dicts and arrays."""

    name: str = "Policy"

    def __init__(self, solver: Optional[str] = None):
        # ``solver`` names the host LP backend (only "scipy"/HiGHS today —
        # the on-device JAX path lives in the Shockwave planning solver);
        # policies with closed forms ignore it.
        self.solver = solver or "scipy"
        self._num_workers: Optional[List[int]] = None

    def flatten(self, throughputs: dict, cluster_spec: Dict[str, int]):
        job_ids = sorted(throughputs.keys())
        if not job_ids:
            return None, None
        worker_types = sorted(throughputs[job_ids[0]].keys())
        if not worker_types:
            return None, None
        self._num_workers = [cluster_spec[wt] for wt in worker_types]
        matrix = np.array(
            [[throughputs[j][wt] for wt in worker_types] for j in job_ids],
            dtype=np.float64,
        )
        return matrix, (job_ids, worker_types)

    def unflatten(self, matrix: np.ndarray, index) -> Allocation:
        job_ids, worker_types = index
        return {
            job_id: {wt: float(matrix[i][k]) for k, wt in enumerate(worker_types)}
            for i, job_id in enumerate(job_ids)
        }

    def scale_factors_array(
        self, scale_factors: dict, job_ids: Sequence[JobId], m: int, n: int
    ) -> np.ndarray:
        col = np.array([scale_factors[j] for j in job_ids], dtype=np.float64)
        return np.tile(col[:, None], (1, n))


class PolicyWithPacking(Policy):
    """Base for policies over packed (space-shared) job pairs.

    The packed throughput dict keys are JobIds that may be pairs; a pair's
    value per worker type is a 2-list of co-located throughputs. ``flatten``
    produces one throughput matrix PER SINGLE JOB over all (combination,
    worker type) cells the job participates in
    (reference: scheduler/policies/policy.py:87-155).
    """

    def scale_factors_array(self, scale_factors, job_ids, m, n) -> np.ndarray:
        out = np.zeros((m, n))
        for i, job_id in enumerate(job_ids):
            sfs = {scale_factors[s] for s in job_id.singletons()}
            # Mixed-scale pairs are invalid: effective scale factor 0
            # (reference: policy.py:70-86).
            out[i, :] = sfs.pop() if len(sfs) == 1 else 0
        return out

    def flatten(self, d: dict, cluster_spec, priority_weights=None):
        job_ids = sorted(d.keys())
        if not job_ids:
            return None, None
        worker_types = sorted(d[job_ids[0]].keys())
        if not worker_types:
            return None, None
        self._num_workers = [cluster_spec[wt] for wt in worker_types]

        relevant_combinations: Dict[JobId, list] = {}
        single_job_ids = []
        for i, job_id in enumerate(job_ids):
            for single in job_id.singletons():
                relevant_combinations.setdefault(single, []).append(i)
            if not job_id.is_pair:
                single_job_ids.append(job_id)

        S, C, W = len(single_job_ids), len(job_ids), len(worker_types)
        all_m = np.zeros((S, C, W), dtype=np.float64)
        for i, single in enumerate(single_job_ids):
            for c in relevant_combinations[single]:
                job_id = job_ids[c]
                for k, wt in enumerate(worker_types):
                    if not job_id.is_pair:
                        if job_id == single:
                            all_m[i, c, k] = d[job_id][wt]
                    else:
                        idx = job_id.as_tuple().index(single[0])
                        all_m[i, c, k] = d[job_id][wt][idx]
            if priority_weights is not None:
                all_m[i] /= priority_weights[single]
        return all_m, (job_ids, single_job_ids, worker_types, relevant_combinations)

    def unflatten(self, m: np.ndarray, index) -> Allocation:
        job_ids, _, worker_types, _ = index
        return {
            job_id: {wt: float(m[i][k]) for k, wt in enumerate(worker_types)}
            for i, job_id in enumerate(job_ids)
        }


def packed_constraint_matrices(
    scale_factors_array: np.ndarray,
    num_workers: Sequence[int],
    single_job_ids: Sequence,
    relevant_combinations: dict,
) -> Tuple[np.ndarray, np.ndarray]:
    """Dense (A_ub, b_ub) over vec(x) with x of shape (combinations, types):
    per-type capacity plus per-single-job total share <= 1
    (reference: policy.py:168-190)."""
    C, W = scale_factors_array.shape
    rows, rhs = [], []
    for w in range(W):
        row = np.zeros(C * W)
        for c in range(C):
            row[c * W + w] = scale_factors_array[c, w]
        rows.append(row)
        rhs.append(num_workers[w])
    for single in single_job_ids:
        row = np.zeros(C * W)
        for c in relevant_combinations[single]:
            row[c * W : (c + 1) * W] = 1.0
        rows.append(row)
        rhs.append(1.0)
    return np.array(rows), np.array(rhs)


def constraint_matrices(
    scale_factors_array: np.ndarray, num_workers: Sequence[int]
) -> Tuple[np.ndarray, np.ndarray]:
    """Dense (A_ub, b_ub) for the base constraints over vec(x), excluding
    x >= 0 which callers express as variable bounds."""
    m, n = scale_factors_array.shape
    rows = []
    rhs = []
    # Capacity per worker type.
    for w in range(n):
        row = np.zeros(m * n)
        for j in range(m):
            row[j * n + w] = scale_factors_array[j, w]
        rows.append(row)
        rhs.append(num_workers[w])
    # Per-job total share <= 1.
    for j in range(m):
        row = np.zeros(m * n)
        row[j * n : (j + 1) * n] = 1.0
        rows.append(row)
        rhs.append(1.0)
    return np.array(rows), np.array(rhs)


def canonical_throughputs(throughputs: dict) -> dict:
    """Type-agnostic throughput view: every worker type gets the job's
    canonical rate — the reference's v100 number, or the sole type on
    single-type clusters (e.g. a measured tpu_v5e pool). Multi-type
    clusters without a v100 pool are ambiguous and raise rather than
    silently optimizing against an arbitrary type's rate."""
    flat = {}
    for job_id, tput in throughputs.items():
        if "v100" in tput:
            canonical = tput["v100"]
        elif len(tput) == 1:
            canonical = next(iter(tput.values()))
        else:
            raise ValueError(
                "type-agnostic policy needs a 'v100' pool or a single "
                f"worker type, got {sorted(tput)} for job {job_id}"
            )
        flat[job_id] = {wt: canonical for wt in tput}
    return flat
