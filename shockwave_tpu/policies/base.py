"""Allocation-policy base machinery.

A policy maps a throughput matrix (jobs x worker types) plus per-job scale
factors to a fractional time-share allocation ``{job_id: {worker_type:
fraction}}`` subject to the cluster's capacity. Shapes and constraint
semantics match the reference (reference: scheduler/policies/policy.py:11-63):

  x >= 0
  sum_j scale_factor_j * x[j, w] <= num_workers[w]   (capacity per type)
  sum_w x[j, w] <= 1                                 (a job's total share)
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from shockwave_tpu.core.ids import JobId

Allocation = Dict[JobId, Dict[str, float]]


class Policy:
    """Base class: flatten/unflatten between dict-of-dicts and arrays."""

    name: str = "Policy"

    def __init__(self, solver: Optional[str] = None):
        # ``solver`` selects the LP backend ("jax" or "scipy"); policies
        # with closed forms ignore it.
        self.solver = solver or "scipy"
        self._num_workers: Optional[List[int]] = None

    def flatten(self, throughputs: dict, cluster_spec: Dict[str, int]):
        job_ids = sorted(throughputs.keys())
        if not job_ids:
            return None, None
        worker_types = sorted(throughputs[job_ids[0]].keys())
        if not worker_types:
            return None, None
        self._num_workers = [cluster_spec[wt] for wt in worker_types]
        matrix = np.array(
            [[throughputs[j][wt] for wt in worker_types] for j in job_ids],
            dtype=np.float64,
        )
        return matrix, (job_ids, worker_types)

    def unflatten(self, matrix: np.ndarray, index) -> Allocation:
        job_ids, worker_types = index
        return {
            job_id: {wt: float(matrix[i][k]) for k, wt in enumerate(worker_types)}
            for i, job_id in enumerate(job_ids)
        }

    def scale_factors_array(
        self, scale_factors: dict, job_ids: Sequence[JobId], m: int, n: int
    ) -> np.ndarray:
        col = np.array([scale_factors[j] for j in job_ids], dtype=np.float64)
        return np.tile(col[:, None], (1, n))


def constraint_matrices(
    scale_factors_array: np.ndarray, num_workers: Sequence[int]
) -> Tuple[np.ndarray, np.ndarray]:
    """Dense (A_ub, b_ub) for the base constraints over vec(x), excluding
    x >= 0 which callers express as variable bounds."""
    m, n = scale_factors_array.shape
    rows = []
    rhs = []
    # Capacity per worker type.
    for w in range(n):
        row = np.zeros(m * n)
        for j in range(m):
            row[j * n + w] = scale_factors_array[j, w]
        rows.append(row)
        rhs.append(num_workers[w])
    # Per-job total share <= 1.
    for j in range(m):
        row = np.zeros(m * n)
        row[j * n : (j + 1) * n] = 1.0
        rows.append(row)
        rhs.append(1.0)
    return np.array(rows), np.array(rhs)
