"""Max-min fairness via iterative water-filling.

Lexicographic max-min: repeatedly raise the common normalized-effective-
throughput level of all unsaturated jobs, detect the bottleneck jobs that
cannot rise further, freeze them at their level, and continue with the
rest. Supports entity-level priority reweighting ("fairness" splits an
entity's weight across its active jobs; "fifo" activates an entity's jobs
one at a time). Reference:
scheduler/policies/max_min_fairness_water_filling.py:1-691.

The reference alternates a cvxpy LP (raise the water level) with a GLPK
MILP (find which jobs moved). Here the level raise is the same LP on
HiGHS, and bottleneck detection is a per-job feasibility LP: job i is
saturated iff no feasible allocation pushes it ``slack`` above its current
level while every job keeps its lower bound.
"""

from __future__ import annotations

from typing import Dict, Optional

import numpy as np
from scipy.optimize import linprog

from shockwave_tpu.policies.base import (
    Policy,
    PolicyWithPacking,
    constraint_matrices,
    packed_constraint_matrices,
)
from shockwave_tpu.policies.isolated import ProportionalPolicy

SLACK = 1.0001
EPSILON = 1e-5


class WaterFillingAlgorithm:
    """Shared core: operates on generic objective rows (one per job) over
    vec(x), so the perf and packing variants differ only in how rows and
    base constraints are built."""

    def __init__(self, priority_reweighting_policies=None):
        self._priority_reweighting_policies = priority_reweighting_policies

    def _compute_priority_weights(
        self, entity_weights, priority_weights, entity_to_job_mapping, finalized,
        job_ids,
    ):
        """(reference: water_filling.py:21-77)"""
        if self._priority_reweighting_policies is None:
            return priority_weights
        if entity_to_job_mapping is None:
            raise ValueError(
                "entity_to_job_mapping required with priority reweighting"
            )
        out: Dict = {}
        for entity_id, entity_jobs in entity_to_job_mapping.items():
            policy = self._priority_reweighting_policies[entity_id]
            entity_weight = entity_weights[entity_id]
            if policy == "fairness":
                total = sum(
                    float(priority_weights[j])
                    for j in entity_jobs
                    if j not in finalized
                )
                for j in entity_jobs:
                    if j in finalized or total == 0.0:
                        out[j] = 0.0
                    else:
                        out[j] = entity_weight * float(priority_weights[j]) / total
            elif policy == "fifo":
                entity_jobs.sort()
                given = False
                for j in entity_jobs:
                    if j not in finalized and not given:
                        out[j] = entity_weight
                        given = True
                    else:
                        out[j] = 0.0
            else:
                raise ValueError(f"Unknown priority reweighting policy {policy!r}")
        return out

    def _raise_level(
        self, coeff_rows, weights, lower_bounds, unsaturated, A_base, b_base,
        zero_mask=None,
    ):
        """LP: maximize t s.t. weights_i * (net_i - lower_i) >= t for
        unsaturated i; net_j >= lower_j for all j."""
        n_var = coeff_rows.shape[1]
        n_rows = A_base.shape[0] + len(lower_bounds) + int(np.sum(unsaturated))
        A = np.zeros((n_rows, n_var + 1))
        b = np.zeros(n_rows)
        A[: A_base.shape[0], :n_var] = A_base
        b[: A_base.shape[0]] = b_base
        r = A_base.shape[0]
        for i in range(len(lower_bounds)):
            A[r, :n_var] = -coeff_rows[i]
            b[r] = -lower_bounds[i]
            r += 1
        for i in np.where(unsaturated)[0]:
            A[r, :n_var] = -weights[i] * coeff_rows[i]
            A[r, -1] = 1.0
            b[r] = -weights[i] * lower_bounds[i]
            r += 1
        c = np.zeros(n_var + 1)
        c[-1] = -1.0
        bounds = [
            (0, 0) if zero_mask is not None and zero_mask[i] else (0, None)
            for i in range(n_var)
        ]
        bounds.append((None, None))
        res = linprog(c, A_ub=A, b_ub=b, bounds=bounds, method="highs")
        if not res.success:
            return None, None, None
        # Duals of the per-job level rows: only jobs whose row binds with
        # a nonzero multiplier can be bottlenecks this round — the rest
        # provably have headroom, so the saturation probe can skip them.
        level_duals = np.zeros(len(lower_bounds))
        level_duals[np.where(unsaturated)[0]] = res.ineqlin.marginals[
            A_base.shape[0] + len(lower_bounds):
        ]
        return res.x[:n_var], res.x[-1], level_duals

    def _is_saturated(
        self, i, A_sat, b_base, coeff_rows, lower_bounds, zero_mask=None
    ):
        """Feasibility LP: can job i exceed its level by SLACK while every
        job keeps its lower bound? (counterpart of the reference's MILP,
        water_filling.py:191-302). ``A_sat`` is the prebuilt
        [A_base; -coeff_rows] matrix — only the rhs changes per probe."""
        n_var = coeff_rows.shape[1]
        target = lower_bounds.copy()
        target[i] = lower_bounds[i] * SLACK + EPSILON
        b = np.concatenate([b_base, -target])
        bounds = [
            (0, 0) if zero_mask is not None and zero_mask[j] else (0, None)
            for j in range(n_var)
        ]
        res = linprog(
            np.zeros(n_var), A_ub=A_sat, b_ub=b, bounds=bounds,
            method="highs",
        )
        return not res.success

    def _run(
        self,
        job_ids,
        coeff_rows,
        scale_factors_vec,
        priority_weights,
        entity_weights,
        entity_to_job_mapping,
        A_base,
        b_base,
        zero_mask=None,
    ):
        m = len(job_ids)
        lower_bounds = np.zeros(m)
        finalized: Dict = {}
        x = None
        prev_level = None
        A_sat = np.vstack([A_base, -coeff_rows])
        for _ in range(m + 1):
            weights_dict = self._compute_priority_weights(
                entity_weights, priority_weights, entity_to_job_mapping,
                finalized, job_ids,
            )
            weights = np.array(
                [
                    float(weights_dict[j]) * scale_factors_vec[i]
                    for i, j in enumerate(job_ids)
                ]
            )
            unsaturated = np.array(
                [
                    j not in finalized and weights[i] > 0.0
                    for i, j in enumerate(job_ids)
                ]
            )
            if not unsaturated.any():
                break
            x_new, level, level_duals = self._raise_level(
                coeff_rows, weights, lower_bounds, unsaturated, A_base, b_base,
                zero_mask,
            )
            if x_new is None:
                break
            x = x_new
            # A stalled level (no increase over the previous iteration)
            # means SOMETHING is stuck even if every binding row drew a
            # zero dual at a degenerate optimum; widen the probe to the
            # skipped set below rather than deferring detection (which
            # the m+1 iteration cap cannot always absorb).
            stalled = prev_level is not None and level - prev_level <= 1e-9
            prev_level = level
            nets = coeff_rows @ x
            for i in np.where(unsaturated)[0]:
                lower_bounds[i] = nets[i]
            candidates = [
                i for i in np.where(unsaturated)[0]
                if abs(level_duals[i]) > 1e-9
            ]
            skipped = [
                i for i in np.where(unsaturated)[0] if i not in candidates
            ]
            newly_saturated = []
            for i in candidates:
                if self._is_saturated(
                    i, A_sat, b_base, coeff_rows, lower_bounds, zero_mask
                ):
                    newly_saturated.append(i)
            if not newly_saturated or stalled:
                # A degenerate optimum can leave a genuinely stuck job
                # with a zero dual on its binding row; before concluding
                # nothing is stuck (or when the level has stopped rising),
                # probe the jobs the filter skipped.
                for i in skipped:
                    if self._is_saturated(
                        i, A_sat, b_base, coeff_rows, lower_bounds, zero_mask
                    ):
                        newly_saturated.append(i)
            if not newly_saturated:
                # Nothing is provably stuck: the remaining jobs rose
                # together and will again; finalize them all at this level.
                for i in np.where(unsaturated)[0]:
                    finalized[job_ids[i]] = lower_bounds[i]
                break
            for i in newly_saturated:
                finalized[job_ids[i]] = lower_bounds[i]
        return x


class MaxMinFairnessWaterFillingPolicyWithPerf(Policy, WaterFillingAlgorithm):
    name = "MaxMinFairnessWaterFilling_Perf"

    def __init__(self, priority_reweighting_policies=None):
        Policy.__init__(self)
        WaterFillingAlgorithm.__init__(self, priority_reweighting_policies)
        self._proportional = ProportionalPolicy()

    def get_allocation(
        self,
        throughputs,
        scale_factors,
        priority_weights,
        cluster_spec,
        entity_weights=None,
        entity_to_job_mapping=None,
    ):
        matrix, index = self.flatten(throughputs, cluster_spec)
        if matrix is None:
            return None
        m, n = matrix.shape
        job_ids, _ = index
        sf = self.scale_factors_array(scale_factors, job_ids, m, n)
        proportional = self._proportional.get_throughputs(
            matrix, index, self._num_workers
        ).reshape(-1)
        coeff_rows = np.zeros((m, m * n))
        for i in range(m):
            coeff_rows[i, i * n : (i + 1) * n] = matrix[i] / proportional[i]
        A_base, b_base = constraint_matrices(sf, self._num_workers)
        x = self._run(
            job_ids,
            coeff_rows,
            sf[:, 0],
            priority_weights,
            entity_weights,
            entity_to_job_mapping,
            A_base,
            b_base,
        )
        if x is None:
            return None
        return self.unflatten(x.reshape(m, n).clip(0.0, 1.0), index)


class MaxMinFairnessWaterFillingPolicy(Policy):
    """Throughput-agnostic water filling (time shares: all throughputs 1)."""

    name = "MaxMinFairnessWaterFilling"

    def __init__(self, priority_reweighting_policies=None):
        super().__init__()
        self._perf_policy = MaxMinFairnessWaterFillingPolicyWithPerf(
            priority_reweighting_policies
        )

    def get_allocation(
        self,
        throughputs,
        scale_factors,
        priority_weights,
        cluster_spec,
        entity_weights=None,
        entity_to_job_mapping=None,
    ):
        flat = {
            job_id: {wt: 1.0 for wt in throughputs[job_id]}
            for job_id in throughputs
        }
        return self._perf_policy.get_allocation(
            flat,
            scale_factors,
            priority_weights,
            cluster_spec,
            entity_weights=entity_weights,
            entity_to_job_mapping=entity_to_job_mapping,
        )


class MaxMinFairnessWaterFillingPolicyWithPacking(
    PolicyWithPacking, WaterFillingAlgorithm
):
    name = "MaxMinFairnessWaterFilling_Packing"

    def __init__(self, priority_reweighting_policies=None):
        PolicyWithPacking.__init__(self)
        WaterFillingAlgorithm.__init__(self, priority_reweighting_policies)
        self._proportional = ProportionalPolicy()

    def get_allocation(
        self,
        throughputs,
        scale_factors,
        priority_weights,
        cluster_spec,
        entity_weights=None,
        entity_to_job_mapping=None,
    ):
        all_m, index = self.flatten(throughputs, cluster_spec)
        if all_m is None or len(all_m) == 0:
            return None
        job_ids, single_job_ids, worker_types, relevant = index
        C, W = len(job_ids), len(worker_types)
        S = len(single_job_ids)
        sf = self.scale_factors_array(scale_factors, job_ids, C, W)
        singles_matrix = np.array(
            [[throughputs[s][wt] for wt in worker_types] for s in single_job_ids]
        )
        proportional = self._proportional.get_throughputs(
            singles_matrix, (single_job_ids, worker_types), self._num_workers
        ).reshape(-1)
        coeff_rows = all_m.reshape(S, C * W) / proportional[:, None]
        A_base, b_base = packed_constraint_matrices(
            sf, self._num_workers, single_job_ids, relevant
        )
        zero_mask = (sf.reshape(-1) == 0).astype(bool)
        sf_vec = np.array([scale_factors[s] for s in single_job_ids], dtype=float)
        x = self._run(
            single_job_ids,
            coeff_rows,
            sf_vec,
            priority_weights,
            entity_weights,
            entity_to_job_mapping,
            A_base,
            b_base,
            zero_mask=zero_mask,
        )
        if x is None:
            return None
        return self.unflatten(x.reshape(C, W).clip(0.0, 1.0), index)
