"""AlloX: jobs-to-(worker, position) assignment minimizing total completion
time via the Hungarian method. Each worker processes its queue in position
order; assigning a job to position p on a worker contributes p * processing
time to the sum of completion times. Only scale factor 1 supported.
Reference: scheduler/policies/allox.py:1-141.
"""

from __future__ import annotations

import copy

import numpy as np
from scipy.optimize import linear_sum_assignment

from shockwave_tpu.policies.base import Policy


class AlloXPolicy(Policy):
    name = "AlloX_Perf"

    def __init__(self, alpha=1.0):
        super().__init__()
        self._alpha = alpha
        self._prev_allocation = {}

    def get_allocation(
        self,
        throughputs,
        scale_factors,
        times_since_start,
        num_steps_remaining,
        cluster_spec,
    ):
        matrix, index = self.flatten(throughputs, cluster_spec)
        if matrix is None:
            return None
        job_ids, worker_types = index
        for job_id in scale_factors:
            if scale_factors[job_id] != 1:
                raise ValueError("AlloX supports only scale factor 1")

        # Workers already held by fully-allocated jobs are not reassigned
        # (reference: allox.py:40-63).
        unallocated, already_allocated = [], []
        for job_id in throughputs:
            prev = self._prev_allocation.get(job_id)
            if prev is not None and sum(prev.values()) == 1.0:
                already_allocated.append(job_id)
            else:
                unallocated.append(job_id)

        worker_id_to_type = {}
        n = 0
        for wt in worker_types:
            num = cluster_spec[wt]
            for job_id in already_allocated:
                if self._prev_allocation[job_id][wt] == 1.0:
                    num -= 1
            for _ in range(num):
                worker_id_to_type[n] = wt
                n += 1

        # Oldest jobs first; optionally truncate to alpha * m
        # (reference: allox.py:65-68).
        unallocated.sort(key=lambda j: -times_since_start[j])
        m = len(unallocated)
        unallocated = unallocated[: max(int(self._alpha * m), n)]
        m = len(unallocated)
        if m == 0 or n == 0:
            allocation = {
                job_id: {wt: 0.0 for wt in cluster_spec} for job_id in job_ids
            }
            for job_id in already_allocated:
                allocation[job_id] = copy.copy(self._prev_allocation[job_id])
            self._prev_allocation = copy.copy(allocation)
            return allocation

        # Cost of (job i, worker j, position p): queueing delay so far plus
        # p * processing time; flattened as [q 2q 3q ...] per the classic
        # sum-of-completion-times reduction (reference: allox.py:70-95).
        q_base = np.zeros((m, n))
        for i, job_id in enumerate(unallocated):
            for j in range(n):
                tput = throughputs[job_id][worker_id_to_type[j]]
                q_base[i, j] = num_steps_remaining[job_id] / max(tput, 1e-10)
        delays = np.array([times_since_start[j] for j in unallocated])
        q = np.concatenate(
            [k * q_base + delays[:, None] for k in range(1, m + 1)], axis=1
        )

        row_idx, col_idx = linear_sum_assignment(q)

        per_worker_assignment = {j: [] for j in range(n)}
        for r, c in zip(row_idx, col_idx):
            per_worker_assignment[c % n].append((unallocated[r], c // n))
        for j in range(n):
            entries = per_worker_assignment[j]
            # Position k in the cost reduction means k-th FROM THE END of
            # the worker's queue (reference: allox.py:101-107).
            per_worker_assignment[j] = sorted(
                [(job_id, len(entries) - 1 - pos) for job_id, pos in entries],
                key=lambda e: e[1],
            )

        allocation = {
            job_id: {wt: 0.0 for wt in cluster_spec} for job_id in job_ids
        }
        for job_id in already_allocated:
            allocation[job_id] = copy.copy(self._prev_allocation[job_id])
        for j in range(n):
            if per_worker_assignment[j]:
                head_job = per_worker_assignment[j][0][0]
                allocation[head_job][worker_id_to_type[j]] = 1.0
        self._prev_allocation = copy.copy(allocation)
        return allocation
