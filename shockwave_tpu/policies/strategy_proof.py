"""Strategy-proof max-min fairness: maximize the geometric mean of
normalized effective throughputs (proportional fairness), then discount
each job's allocation by its leave-one-out externality so misreporting
throughputs cannot help. Reference:
scheduler/policies/max_min_fairness_strategy_proof.py:1-136.

The geo-mean program max prod_i (c_i . x_i)^(1/m) == max sum_i log(c_i .
x_i) is solved with SLSQP over the base polytope (small, smooth, concave);
the reference uses cvxpy's geo_mean atom.
"""

from __future__ import annotations

import copy

import numpy as np
from scipy.optimize import LinearConstraint, minimize

from shockwave_tpu.policies.base import Policy, constraint_matrices
from shockwave_tpu.policies.isolated import ProportionalPolicy


def _max_log_sum(coeffs: np.ndarray, A_base, b_base) -> np.ndarray | None:
    """maximize sum_i log(coeffs[i] . x[i]) over the base polytope."""
    m, n = coeffs.shape
    n_var = m * n

    def rates(x):
        return np.maximum((coeffs * x.reshape(m, n)).sum(axis=1), 1e-12)

    def neg_obj(x):
        return -float(np.sum(np.log(rates(x))))

    def grad(x):
        r = rates(x)
        g = -(coeffs / r[:, None])
        return g.reshape(-1)

    # Feasible interior start: an equal split scaled to strict feasibility.
    x0 = np.full(n_var, 1.0 / (m * n))
    scale = np.max(A_base @ x0 / np.maximum(b_base, 1e-12))
    if scale > 0:
        x0 = x0 / (scale * 1.01)
    res = minimize(
        neg_obj,
        x0,
        jac=grad,
        method="SLSQP",
        bounds=[(0, None)] * n_var,
        constraints=[LinearConstraint(A_base, -np.inf, b_base)],
        options={"maxiter": 200, "ftol": 1e-10},
    )
    if not res.success and res.status != 4:  # 4: inequality incompatible noise
        return None
    return res.x.reshape(m, n)


class MaxMinFairnessStrategyProofPolicyWithPerf(Policy):
    name = "MaxMinFairness_Perf"

    def __init__(self, solver=None):
        super().__init__(solver)
        self._proportional_policy = ProportionalPolicy()

    def get_allocation(
        self,
        throughputs,
        scale_factors,
        priority_weights,
        cluster_spec,
        recurse_deeper=True,
    ):
        matrix, index = self.flatten(throughputs, cluster_spec)
        if matrix is None:
            return None
        m, n = matrix.shape
        job_ids, _ = index

        if recurse_deeper:
            # Leave-one-out solves for the externality discounts
            # (reference: :58-71).
            all_throughputs_minus_job = []
            for job_id in job_ids:
                minus = copy.copy(throughputs)
                del minus[job_id]
                all_throughputs_minus_job.append(
                    self.get_allocation(
                        minus,
                        scale_factors,
                        priority_weights,
                        cluster_spec,
                        recurse_deeper=False,
                    )
                )

        sf = self.scale_factors_array(scale_factors, job_ids, m, n)
        inv_priority = np.array(
            [1.0 / priority_weights[j] for j in job_ids]
        ).reshape((m, 1))
        proportional = self._proportional_policy.get_throughputs(
            matrix, index, self._num_workers
        ).reshape((m, 1))
        coeffs = matrix * inv_priority / proportional * sf

        A_base, b_base = constraint_matrices(sf, self._num_workers)
        x = _max_log_sum(coeffs, A_base, b_base)
        if x is None:
            return None

        effective = (matrix * x).sum(axis=1)
        throughputs_dict = {job_ids[i]: effective[i] for i in range(m)}
        if not recurse_deeper:
            return throughputs_dict

        # discount_i = prod over others of (their throughput with i present
        # / their throughput with i absent) <= 1 (reference: :120-131).
        discount_factors = np.zeros(m)
        for i, job_id in enumerate(job_ids):
            d = 1.0
            for other, minus_val in all_throughputs_minus_job[i].items():
                d *= throughputs_dict[other] / max(minus_val, 1e-12)
            discount_factors[i] = d
        discounted = (x.T * discount_factors).T
        return (
            self.unflatten(discounted.clip(0.0, 1.0), index),
            discount_factors,
        )


class MaxMinFairnessStrategyProofPolicy(Policy):
    """Throughput-agnostic variant (all throughputs 1.0)."""

    name = "MaxMinFairness"

    def __init__(self, solver=None):
        super().__init__(solver)
        self._perf = MaxMinFairnessStrategyProofPolicyWithPerf(solver)

    def get_allocation(
        self, throughputs, scale_factors, priority_weights, cluster_spec
    ):
        flat = {
            job_id: {wt: 1.0 for wt in throughputs[job_id]}
            for job_id in throughputs
        }
        return self._perf.get_allocation(
            flat, scale_factors, priority_weights, cluster_spec
        )
