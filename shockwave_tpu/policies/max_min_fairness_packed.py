"""Max-min fairness over packed job pairs (the pairwise LP formulation):
maximize the minimum, over single jobs, of priority-normalized effective
throughput summed across every combination the job participates in.
Reference: scheduler/policies/max_min_fairness.py:304-400.
"""

from __future__ import annotations

import numpy as np

from shockwave_tpu.policies.base import (
    PolicyWithPacking,
    packed_constraint_matrices,
)
from shockwave_tpu.policies.isolated import ProportionalPolicy
from shockwave_tpu.policies.lp_backend import max_min_lp_general


class MaxMinFairnessPolicyWithPacking(PolicyWithPacking):
    name = "MaxMinFairness_Packing"

    def __init__(self, solver=None):
        super().__init__(solver)
        self._proportional_policy = ProportionalPolicy()

    def get_allocation(
        self, throughputs, scale_factors, priority_weights, cluster_spec
    ):
        all_m, index = self.flatten(
            throughputs, cluster_spec, priority_weights=priority_weights
        )
        if all_m is None or len(all_m) == 0:
            return None
        job_ids, single_job_ids, worker_types, relevant = index
        C, W = len(job_ids), len(worker_types)
        S = len(single_job_ids)
        sf = self.scale_factors_array(scale_factors, job_ids, C, W)

        singles_matrix = np.array(
            [[throughputs[s][wt] for wt in worker_types] for s in single_job_ids]
        )
        proportional = self._proportional_policy.get_throughputs(
            singles_matrix, (single_job_ids, worker_types), self._num_workers
        ).reshape(-1)

        # Objective row for single s: scale-factor-weighted, proportional-
        # normalized throughput across every cell of every combination it
        # appears in (reference: max_min_fairness.py:336-369).
        coeff_rows = (all_m * sf[None, :, :]).reshape(S, C * W) / proportional[
            :, None
        ]
        A_base, b_base = packed_constraint_matrices(
            sf, self._num_workers, single_job_ids, relevant
        )
        zero_mask = (sf.reshape(-1) == 0).astype(bool)
        x = max_min_lp_general(coeff_rows, A_base, b_base, zero_mask=zero_mask)
        if x is None:
            return None
        return self.unflatten(x.reshape(C, W).clip(0.0, 1.0), index)
