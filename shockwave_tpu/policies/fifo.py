"""FIFO: jobs hold a whole worker (type) in arrival order until done.

Stateful across allocation calls: a scheduled job keeps its worker type
until it completes. ``perf`` mode re-derives the whole assignment each call
picking each job's best worker type; ``packing`` mode greedily space-shares
queued jobs with running ones when the combined normalized throughput beats
a threshold. Reference: scheduler/policies/fifo.py (the reference's base
mode draws a random index but then assigns a stale loop variable,
fifo.py:147-160; here the drawn index is used).
"""

from __future__ import annotations

import random
from typing import Dict, Optional

from shockwave_tpu.core.ids import JobId
from shockwave_tpu.policies.base import Policy


class FIFOPolicy(Policy):
    name = "FIFO"

    def __init__(self, mode: str = "base", seed: Optional[int] = None,
                 packing_threshold: float = 1.5):
        super().__init__()
        self._mode = mode
        self._assigned_type: Dict[JobId, str] = {}
        self._rng = random.Random(seed)
        self._packing_threshold = packing_threshold

    def _pack(self, queue, throughputs, scale_factors):
        """Greedily merge queued jobs into running singletons when the pair's
        normalized combined throughput clears the threshold."""
        while queue:
            candidate = queue.pop(0)
            best_gain = self._packing_threshold
            best_partner = None
            for scheduled, worker_type in self._assigned_type.items():
                if scheduled.is_pair:
                    continue
                if scale_factors[scheduled] != scale_factors[candidate]:
                    continue
                merged = JobId(scheduled[0], candidate[0])
                if merged not in throughputs:
                    continue
                packed = throughputs[merged][worker_type]
                normalized = 0.0
                for i, single in enumerate(merged.singletons()):
                    if packed[i] > 0:
                        normalized += packed[i] / throughputs[single][worker_type]
                if normalized > best_gain:
                    best_gain = normalized
                    best_partner = scheduled
            if best_partner is None:
                # FIFO order: nothing may jump the queue.
                break
            worker_type = self._assigned_type.pop(best_partner)
            self._assigned_type[JobId(best_partner[0], candidate[0])] = worker_type

    def get_allocation(self, throughputs, scale_factors, cluster_spec):
        available = dict(cluster_spec)
        if self._mode != "base":
            self._assigned_type = {}

        queue = [
            j for j in sorted(throughputs)
            if not j.is_pair and j not in self._assigned_type
        ]

        # Release slots of completed jobs; requeue surviving pair members.
        for scheduled in sorted(self._assigned_type):
            worker_type = self._assigned_type[scheduled]
            if scheduled not in throughputs:
                for single in scheduled.singletons():
                    if single in throughputs and single not in queue:
                        queue.append(single)
                queue.sort()
                del self._assigned_type[scheduled]
            else:
                available[worker_type] -= scale_factors[
                    scheduled.singletons()[0]
                ]

        available_types = sorted(t for t in available if available[t] > 0)

        while queue and available_types:
            job_id = queue.pop(0)
            sf = scale_factors[job_id]
            fitting = [t for t in available_types if available[t] >= sf]
            if not fitting:
                # Keep the head job in the queue so packing mode can still
                # consider it (the reference pops-and-drops it,
                # fifo.py:139-147, losing its packing opportunity).
                queue.insert(0, job_id)
                break
            if self._mode == "base":
                worker_type = fitting[self._rng.randrange(len(fitting))]
            else:
                worker_type = max(fitting, key=lambda t: throughputs[job_id][t])
            if throughputs[job_id][worker_type] > 0:
                self._assigned_type[job_id] = worker_type
                available[worker_type] -= sf
                if available[worker_type] == 0:
                    available_types.remove(worker_type)

        if self._mode == "packing":
            self._pack(queue, throughputs, scale_factors)

        allocation = {
            job_id: {wt: 0.0 for wt in cluster_spec} for job_id in throughputs
        }
        for job_id, worker_type in self._assigned_type.items():
            if job_id in allocation:
                allocation[job_id][worker_type] = 1.0
        return allocation


class FIFOPolicyWithPerf(Policy):
    name = "FIFO_Perf"

    def __init__(self, solver=None):
        super().__init__(solver)
        self._policy = FIFOPolicy(mode="perf")

    def get_allocation(self, throughputs, scale_factors, cluster_spec):
        return self._policy.get_allocation(throughputs, scale_factors, cluster_spec)


class FIFOPolicyWithPacking(Policy):
    name = "FIFO_Packing"

    def __init__(self, packing_threshold: float = 1.5, solver=None):
        super().__init__(solver)
        self._policy = FIFOPolicy(mode="packing", packing_threshold=packing_threshold)

    def get_allocation(self, throughputs, scale_factors, cluster_spec):
        return self._policy.get_allocation(throughputs, scale_factors, cluster_spec)
