"""Gandiva: time-slicing with opportunistic random packing.

When the cluster is oversubscribed, jobs are randomly paired (same scale
factor only); pairs whose combined normalized throughput drops below 1.0
are dissolved. Each scheduled combination gets an equal cluster split.
Reference: scheduler/policies/gandiva.py:1-150.
"""

from __future__ import annotations

import random

import numpy as np

from shockwave_tpu.core.ids import JobId
from shockwave_tpu.policies.base import PolicyWithPacking


class GandivaPolicy(PolicyWithPacking):
    name = "Gandiva_Packing"

    def __init__(self, seed=None):
        super().__init__()
        self._assigned_combinations = {}
        self._rng = random.Random(seed)

    def _equal_split(self, combos_to_schedule, index, scale_factors, cluster_spec):
        job_ids, _, worker_types, _ = index
        sf = self.scale_factors_array(
            scale_factors, job_ids, len(job_ids), len(worker_types)
        )
        x = np.zeros((len(job_ids), len(worker_types)))
        m = len(combos_to_schedule)
        for combo in combos_to_schedule:
            i = job_ids.index(combo)
            x[i] = np.array(
                [cluster_spec[wt] / m for wt in worker_types]
            ) / np.maximum(sf[i], 1.0)
        row_sums = np.maximum(x.sum(axis=1), 1.0)
        return x / row_sums[:, None]

    def _normalized_throughput(self, combo, throughputs, worker_types):
        if not combo.is_pair:
            return 0.0
        total = 0.0
        for wt in worker_types:
            packed = throughputs[combo][wt]
            for i, single in enumerate(combo.singletons()):
                if packed[i] <= 0.0:
                    return 0.0
                total += packed[i] / throughputs[single][wt]
        return total

    def get_allocation(self, throughputs, scale_factors, cluster_spec):
        all_m, index = self.flatten(throughputs, cluster_spec)
        if all_m is None or len(all_m) == 0:
            return None
        job_ids, single_job_ids, worker_types, _ = index

        # Dissolve combinations whose members left or whose packed
        # throughput regressed below isolated (reference: :79-104).
        to_delete = []
        for job_id, (combo, other) in list(self._assigned_combinations.items()):
            if job_id not in job_ids or (other is not None and other not in job_ids):
                to_delete += [job_id, other]
                continue
            if (
                combo.is_pair
                and combo in throughputs
                and self._normalized_throughput(combo, throughputs, worker_types) < 1.0
            ):
                to_delete += [job_id, other]
        for job_id in to_delete:
            if job_id is not None:
                self._assigned_combinations.pop(job_id, None)

        requested = sum(scale_factors[s] for s in single_job_ids)
        available = sum(cluster_spec[wt] for wt in worker_types)

        if requested <= available:
            x = self._equal_split(single_job_ids, index, scale_factors, cluster_spec)
        else:
            unassigned = [
                s for s in single_job_ids if s not in self._assigned_combinations
            ]
            attempts = len(unassigned)
            while len(unassigned) > 1 and attempts > 0:
                attempts -= 1
                a, b = self._rng.sample(unassigned, 2)
                if scale_factors[a] != scale_factors[b]:
                    continue
                unassigned.remove(a)
                unassigned.remove(b)
                combo = JobId(a[0], b[0])
                self._assigned_combinations[a] = (combo, b)
                self._assigned_combinations[b] = (combo, a)
            for s in unassigned:
                self._assigned_combinations[s] = (s, None)
            combos = list(
                {combo for combo, _ in self._assigned_combinations.values()}
            )
            # A freshly drawn pair may have no oracle entry yet this round;
            # only schedule combos present in the throughput dict.
            combos = [c for c in combos if c in job_ids]
            x = self._equal_split(combos, index, scale_factors, cluster_spec)

        return self.unflatten(x, index)
