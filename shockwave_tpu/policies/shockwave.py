"""The Shockwave policy: per-round Volatile Fisher Market planning.

``ShockwavePolicy`` is a name-only marker the scheduler dispatches on
(reference: scheduler/policies/shockwave.py:6-8 plus the scheduler hooks
gated on the policy name); the planning logic lives in
:class:`ShockwavePlanner`, the equivalent of the reference's
``ShockwaveScheduler`` (reference: scheduler/shockwave.py:12-91).

Interchangeable solver backends:
  * ``reference`` — the exact boolean program on host CPU via HiGHS
    (:mod:`shockwave_tpu.solver.eg_milp`), reference-math ground truth.
  * ``tpu`` — the production path: latency-aware dispatch between the
    C++ host greedy (small solves, where device round-trip latency
    dominates) and the jitted level-set solve on the accelerator
    (:func:`shockwave_tpu.solver.eg_jax.solve_eg_level`).
  * ``pdhg`` — restarted primal-dual hybrid gradient on the exact
    continuous relaxation (:mod:`shockwave_tpu.solver.eg_pdhg`):
    matrix-free, one compile per fleet size, solution-warm-started from
    the previous plan. The scaling backend for 10k-100k-job plans, and
    the degradation ladder's first fallback rung.
  * ``level`` / ``native`` / ``relaxed`` — each of the above forced,
    for tests, benchmarks, and cross-checks.
"""

from __future__ import annotations

import time
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

import numpy as np

from shockwave_tpu import obs
from shockwave_tpu.policies.base import Policy
from shockwave_tpu.policies.speculation import SpeculativePlannerMixin
from shockwave_tpu.predictor import JobMetadata
from shockwave_tpu.solver.eg_problem import EGProblem

DEFAULT_LOG_BASES = [0.0, 0.2, 0.4, 0.6, 0.8, 1.0]

# Fleet scale at which the production backend routes one planning solve
# to the multi-chip sharded path (when >1 device is visible) instead of
# the single-device level solve / native greedy. Anchored by the
# committed mesh sweeps (results/sharded_solve_scaling.json,
# results/pdhg_sharded_mesh.json): on shared-core virtual meshes the
# sharded path never wins wall-clock, so the default stays at the
# memory-headroom scale; override per deployment (from a measured
# crossover on real chips) via SHOCKWAVE_SHARDED_MIN_JOBS.
SHARDED_DISPATCH_MIN_JOBS = 8192


def sharded_dispatch_min_jobs() -> int:
    """Live threshold for the "tpu" backend's sharded dispatch:
    SHOCKWAVE_SHARDED_MIN_JOBS when set, else the module default."""
    import os

    raw = os.environ.get("SHOCKWAVE_SHARDED_MIN_JOBS", "").strip()
    return int(raw) if raw else SHARDED_DISPATCH_MIN_JOBS


class ShockwavePlanner(SpeculativePlannerMixin):
    """Plans a boolean (job x future-round) schedule each planning window.

    State: per-job predictor metadata, finish-time-estimate history, the
    schedule cache keyed by absolute round index, and the recompute flag
    (set on batch-size changes; reference: scheduler/scheduler.py:3590-3591).
    """

    def __init__(self, config: dict, backend: str = "tpu"):
        self.config = dict(config)
        self.backend = backend
        self.num_gpus = int(config["num_gpus"])
        self.round_duration = float(config["time_per_iteration"])
        self.future_rounds = int(config.get("future_rounds", 20))
        self.priority_power = float(config.get("lambda", 5.0))
        self.regularizer = float(config.get("k", 10.0))
        self.log_bases = list(
            config.get("log_approximation_bases", DEFAULT_LOG_BASES)
        )
        self.solver_rel_gap = float(config.get("solver_rel_gap", 1e-3))
        self.solver_timeout = float(config.get("solver_timeout", 15.0))
        self.solver_num_steps = int(config.get("solver_num_steps", 256))
        # Fixed-point tolerance of the restarted-PDHG backend (the
        # objective-stall stop usually fires first; see eg_pdhg).
        self.pdhg_tol = float(config.get("pdhg_tol", 1e-4))
        # Preemption-aware planning: scale on the per-job measured
        # relaunch overheads the scheduler threads through add_job. 0
        # disables the switching-cost term even when overheads are known.
        self.switch_cost_weight = float(config.get("switch_cost_weight", 1.0))
        # Migration hysteresis for the stickiness pass: the round-0
        # swap that keeps an incumbent running must beat the fairness
        # reorder regression by this factor. 1.0 (default) is the
        # original break-even rule, bit-identical to before the knob;
        # <1 pulls incumbents more aggressively (stickier placements
        # under churn), >1 demands a larger win before displacing
        # another job. Tuned on the chaos soak by
        # scripts/sweeps/sweep_chaos_stickiness.py.
        self.stickiness_hysteresis = float(
            config.get("stickiness_hysteresis", 1.0)
        )
        # Per-round planning deadline (seconds) for the degradation
        # ladder: primary backend -> restarted PDHG -> relaxed PGD ->
        # native greedy, each rung budgeted against what remains. None (default) keeps the
        # single-backend behavior; the ladder also engages when fault
        # injection is armed so injected solver slowdowns/timeouts have
        # a recovery path instead of a wedged round.
        raw_deadline = config.get("plan_deadline_s")
        self.plan_deadline_s = (
            float(raw_deadline) if raw_deadline is not None else None
        )
        # Ladder outcome of the most recent solve (consumed by
        # _record_solve to tag degraded rounds in solve_records).
        self._last_ladder: Optional[dict] = None

        # Plan-ahead pipelining (shockwave_tpu/policies/speculation.py):
        # the shared scaffolding lives on SpeculativePlannerMixin.
        # ``speculate`` in the config is read by the SCHEDULER (which
        # owns the execution model and supplies predicted outcomes);
        # the planner only reconciles.
        self._init_speculation(config)
        # Set by a repair reconcile: the next solve goes through the
        # delta-patched warm-started PDHG backend before anything else.
        self._repair_with_spec = False

        self.round_index = 0
        self.recompute_flag = False
        self.schedules: "OrderedDict[int, list]" = OrderedDict()
        self.job_metadata: "OrderedDict[object, JobMetadata]" = OrderedDict()
        self.finish_time_estimates: Dict[object, list] = {}
        # Per-job measured relaunch overhead (seconds), from the
        # scheduler's per-family table; 0.0 = overhead-blind.
        self.job_overheads: Dict[object, float] = {}
        # Jobs scheduled in the round that just executed — the incumbent
        # placements a replan is charged for dropping.
        self.last_round_jobs: List[object] = []
        # Wall-clock seconds of each plan solve (consumed by bench.py).
        # Failed/timed-out solves are recorded too — an exception path
        # that vanishes from the timing series hides exactly the solves
        # an operator must see.
        self.solve_times: List[float] = []
        # One record per solve attempt: {"backend": the backend that
        # actually produced (or failed) the solve — "tpu" dispatches to
        # sharded/native/level per problem size — "seconds", "ok",
        # "round", "num_jobs", and "error" on failures}.
        self.solve_records: List[dict] = []
        # Worker-type tag when owned by a PoolSetPlanner (flight-recorder
        # records carry it so per-pool decisions stay attributable).
        self.pool_label: Optional[str] = None
        # Last committed replan's per-job spend snapshot (job key ->
        # chip-rounds) for the scheduler's per-tenant spend gauges.
        # Observability-only: NOT part of state_dict/replay.
        self.last_market: Optional[dict] = None

    # -- scheduler-facing interface -------------------------------------
    def add_job(
        self, job_id, profile: dict, round_len: float, scale_factor: int,
        submit_time: Optional[float] = None, overhead_s: float = 0.0,
    ) -> None:
        md = JobMetadata(profile, round_len, scale_factor)
        if submit_time is not None:
            md.submit(submit_time)
        self.job_metadata[job_id] = md
        self.job_overheads[job_id] = float(overhead_s)

    def remove_job(self, job_id) -> None:
        self.job_metadata.pop(job_id, None)
        self.finish_time_estimates.pop(job_id, None)
        self.job_overheads.pop(job_id, None)

    def record_round_throughput(self, job_id, round_id, throughput, bs) -> None:
        md = self.job_metadata.get(job_id)
        if md is not None:
            md.record_round_throughput(round_id, throughput, bs)

    def mark_complete(self, job_id) -> None:
        md = self.job_metadata.get(job_id)
        if md is not None:
            md.complete()

    def set_progress(self, job_id, num_epochs: int) -> None:
        md = self.job_metadata.get(job_id)
        if md is not None:
            md.complete(min(int(num_epochs), md.total_epochs))

    def get_metadata(self, job_id) -> Optional[JobMetadata]:
        """The job's predictor state (calibration scoring reads the
        live remaining-runtime forecast through this)."""
        return self.job_metadata.get(job_id)

    def increment_round(self) -> None:
        # The round at the cursor has just executed: its jobs are the
        # incumbents the next replan's switching-cost term protects.
        self.last_round_jobs = list(self.schedules.get(self.round_index, []))
        self.round_index += 1

    def set_capacity(self, num_gpus: int) -> None:
        """Capacity changed under the planner (worker death, spot
        reclamation, churn re-add): solve the next plan against the
        fleet that actually exists. Clamped to >= 1 — a zero-chip plan
        has no meaning and the applier never reclaims the last chip."""
        num_gpus = max(1, int(num_gpus))
        if num_gpus == self.num_gpus:
            return
        self.num_gpus = num_gpus
        self.config["num_gpus"] = num_gpus
        self.recompute_flag = True

    def set_recompute_flag(self, jobs=None) -> None:
        """Force a replan. ``jobs`` names the jobs whose state changed;
        a single global market replans fully either way, but federated
        planners (pool set, cells) use it to stale only the children
        owning them."""
        self.recompute_flag = True

    @property
    def num_jobs(self) -> int:
        return len(self.job_metadata)

    # -- serialization (simulator checkpoint fast-forward) --------------
    def state_dict(self) -> dict:
        """Plain dicts/arrays snapshot of the full planner state: config,
        round cursor, plan cache, per-job predictor metadata, and
        finish-time history. Nothing jitted is captured — solver functions
        are module-level, so a restored planner re-uses the process's
        compiled solvers untouched."""
        return {
            "config": dict(self.config),
            "backend": self.backend,
            "round_index": self.round_index,
            "recompute_flag": self.recompute_flag,
            "schedules": OrderedDict(
                (r, list(s)) for r, s in self.schedules.items()
            ),
            "job_metadata": OrderedDict(
                (j, md.state_dict()) for j, md in self.job_metadata.items()
            ),
            "finish_time_estimates": {
                j: list(h) for j, h in self.finish_time_estimates.items()
            },
            "job_overheads": dict(self.job_overheads),
            "last_round_jobs": list(self.last_round_jobs),
            "solve_times": list(self.solve_times),
            "solve_records": [dict(r) for r in self.solve_records],
        }

    @classmethod
    def from_state(cls, state: dict) -> "ShockwavePlanner":
        planner = cls(state["config"], backend=state["backend"])
        planner.round_index = int(state["round_index"])
        planner.recompute_flag = bool(state["recompute_flag"])
        planner.schedules = OrderedDict(
            (r, list(s)) for r, s in state["schedules"].items()
        )
        planner.job_metadata = OrderedDict(
            (j, JobMetadata.from_state(md))
            for j, md in state["job_metadata"].items()
        )
        planner.finish_time_estimates = {
            j: list(h) for j, h in state["finish_time_estimates"].items()
        }
        planner.job_overheads = dict(state.get("job_overheads", {}))
        planner.last_round_jobs = list(state.get("last_round_jobs", []))
        if state.get("pdhg_warm_start") is not None:
            # Replayed snapshot: the plan cache this vector was derived
            # from is not in the record — carry the vector itself.
            planner._replay_warm_start = list(state["pdhg_warm_start"])
        planner.solve_times = list(state["solve_times"])
        planner.solve_records = [
            dict(r) for r in state.get("solve_records", [])
        ]
        return planner

    # -- plan-ahead pipelining ------------------------------------------
    # speculate_next_round / _reconcile_speculation / _observe_boundary
    # come from SpeculativePlannerMixin; the hooks below are this
    # planner kind's reconcile semantics.
    def _spec_solve_base(self) -> int:
        """Solve-bookkeeping length at snapshot time (install appends
        only the clone's records past this point)."""
        return len(self.solve_records)

    def _augment_mismatch(self, mismatch: dict) -> dict:
        """External staleness (batch-size switch, capacity event) the
        fingerprint math cannot see is still churn."""
        if self.recompute_flag:
            mismatch = dict(mismatch)
            mismatch.setdefault("", []).append("recompute_flagged")
        return mismatch

    def _install_speculation(self, spec) -> None:
        """No-churn boundary: adopt the clone's post-replan outputs —
        the plan window, the finish-time history its problem build
        appended, and the solve bookkeeping. The live predictor inputs
        (measured throughput schedules) are NOT touched: in simulation
        they equal the clone's by exact prediction; in physical mode
        the measured values stay authoritative for the next build."""
        clone = spec.clone
        if not spec.solved:
            return  # the boundary serves from cache either way
        self.schedules = OrderedDict(
            (r, list(s)) for r, s in clone.schedules.items()
        )
        self.finish_time_estimates = {
            j: list(h) for j, h in clone.finish_time_estimates.items()
        }
        self.solve_times.extend(
            clone.solve_times[spec.base_solve_records:]
        )
        self.solve_records.extend(
            dict(r)
            for r in clone.solve_records[spec.base_solve_records:]
        )
        self.recompute_flag = False

    def _boundary_stale(self) -> bool:
        """Whether the boundary's cache-serve check would replan:
        recompute flagged, no cached round at the cursor, or a cached
        round whose jobs all completed while incomplete jobs remain
        (mirrors :meth:`current_round_schedule`)."""
        if self.recompute_flag or self.round_index not in self.schedules:
            return True
        schedule = self.schedules[self.round_index]
        live = [
            j
            for j in schedule
            if j in self.job_metadata
            and self.job_metadata[j].completed_epochs
            < self.job_metadata[j].total_epochs
        ]
        return not live and self._has_incomplete_jobs()

    def _prepare_repair(self, spec, mismatch: dict) -> bool:
        """Churned boundary. Only when the boundary was going to replan
        anyway (so pipelining never re-plans more eagerly than serial):
        the speculative window (when one was solved) becomes the
        plan-cache warm basis, and the boundary replan is forced
        through the delta-patched PDHG path —
        :func:`shockwave_tpu.solver.warm_start.delta_patch_counts`
        aligns the speculative solution across exactly the
        arrival/departure/progress delta that invalidated it. Returns
        whether a repair solve was armed."""
        if not self._boundary_stale():
            return False
        if spec.solved:
            self.schedules = OrderedDict(
                (r, list(s)) for r, s in spec.clone.schedules.items()
            )
        self.recompute_flag = True
        self._repair_with_spec = True
        return True

    def current_round_schedule(self) -> list:
        """This round's job list, from the plan cache or a fresh solve
        (reference: shockwave.py:77-91).

        Beyond the reference's cache semantics, a cached round whose
        scheduled jobs have all since completed triggers a replan while
        incomplete jobs remain — the reference returns the stale empty
        round, which the scheduler interprets as end-of-trace and wedges
        the remaining jobs (scheduler.py:1731-1732).

        With plan-ahead pipelining armed, a pending speculative solve
        for this boundary is reconciled first; the wall time this call
        spends on reconcile + any solve is the run's EXPOSED planning
        time (hidden speculative solve time rides its own histogram).
        """
        start = time.perf_counter()
        reconciled = self._reconcile_speculation()
        if not self.recompute_flag and self.round_index in self.schedules:
            schedule = self.schedules[self.round_index]
            live = [
                j
                for j in schedule
                if j in self.job_metadata
                and self.job_metadata[j].completed_epochs
                < self.job_metadata[j].total_epochs
            ]
            if live or not self._has_incomplete_jobs():
                if reconciled is not None:
                    self._observe_boundary(time.perf_counter() - start)
                return schedule
        self._replan()
        self.recompute_flag = False
        self._observe_boundary(time.perf_counter() - start)
        return self.schedules[self.round_index]

    def _has_incomplete_jobs(self) -> bool:
        return any(
            md.completed_epochs < md.total_epochs
            for md in self.job_metadata.values()
        )

    # -- planning -------------------------------------------------------
    def _build_problem(self):
        """Predictor state -> EGProblem arrays + this window's priorities.

        Finish-time fairness per job (reference: shockwave.py:244-279):
        predicted JCT under contention divided by the window-weighted
        running average of its isolated finish-time estimates.
        """
        job_ids = [
            j
            for j, md in self.job_metadata.items()
            if md.completed_epochs < md.total_epochs
        ]
        # Plan-order job ids of the problem being built (the PDHG
        # backend's solution warm start maps cached future schedules
        # back onto problem rows through this).
        self._plan_job_ids = list(job_ids)
        if not job_ids:
            return None, []
        J = len(job_ids)
        completed = np.zeros(J)
        total = np.zeros(J)
        epoch_dur = np.zeros(J)
        remaining = np.zeros(J)
        nworkers = np.zeros(J)
        priorities = np.zeros(J)
        contention = self.num_jobs / self.num_gpus
        round_time = (self.round_index + self.future_rounds) * self.round_duration
        for i, job_id in enumerate(job_ids):
            md = self.job_metadata[job_id]
            md.recompute_epoch_durations()
            completed[i] = md.completed_epochs
            total[i] = md.total_epochs
            epoch_dur[i] = md.mean_epoch_duration()
            rem = md.remaining_runtime()
            remaining[i] = rem
            nworkers[i] = md.nworkers
            predicted_jct = round_time + rem * contention
            predicted_finish = (
                float(np.sum(md.epoch_durations[: md.completed_epochs])) + rem
            )
            history = self.finish_time_estimates.setdefault(job_id, [])
            history.append((self.round_index, predicted_finish))
            ftf = predicted_jct / self._interpolated_finish_time(job_id)
            priorities[i] = ftf ** self.priority_power
        # Switching-cost inputs: measured relaunch overhead per job and
        # the incumbent mask (who held workers in the round that just
        # ran). All-zero overheads leave the problem bit-identical to
        # the overhead-blind formulation.
        incumbent_set = set(self.last_round_jobs)
        switch_cost = np.array(
            [
                self.switch_cost_weight * self.job_overheads.get(j, 0.0)
                for j in job_ids
            ]
        )
        incumbent = np.array(
            [1.0 if j in incumbent_set else 0.0 for j in job_ids]
        )
        problem = EGProblem(
            priorities=priorities,
            completed_epochs=completed,
            total_epochs=total,
            epoch_duration=epoch_dur,
            remaining_runtime=remaining,
            nworkers=nworkers,
            num_gpus=self.num_gpus,
            round_duration=self.round_duration,
            future_rounds=self.future_rounds,
            regularizer=self.regularizer,
            log_bases=np.asarray(self.log_bases, dtype=np.float64),
            switch_cost=switch_cost,
            incumbent=incumbent,
        )
        return problem, job_ids

    def _interpolated_finish_time(self, job_id, alpha: float = 0.9) -> float:
        """Window-weighted running average blended with the latest estimate
        (reference: shockwave.py:224-242, including the quirk that the
        weight vector's length truncates the estimate list)."""
        history = self.finish_time_estimates[job_id]
        round_ids = np.array([r for r, _ in history], dtype=np.float64)
        windows = np.diff(round_ids)
        if windows.size == 0 or np.sum(windows) == 0:
            weights = np.array([1.0])
        else:
            weights = windows / np.sum(windows)
        finish_times = np.array([ft for _, ft in history[: weights.size]])
        avg = float(np.dot(weights, finish_times))
        return max(1e-6, alpha * avg + (1 - alpha) * history[-1][1])

    def _solve(self, problem: EGProblem) -> "Tuple[np.ndarray, str]":
        """Returns (schedule, backend_used) — ``backend_used`` is the
        backend that actually produced the solve, which for the "tpu"
        latency-aware dispatch differs per problem size.

        With a per-round planning deadline (``plan_deadline_s``) or
        armed fault injection, the solve runs under the degradation
        ladder (:meth:`_solve_with_ladder`); otherwise this is a
        straight dispatch to the configured backend."""
        from shockwave_tpu.runtime import faults

        # A speculative clone never consumes injected solver faults:
        # they are the LIVE ladder's events, and a hidden solve burning
        # one would de-synchronize a chaos run from its serial baseline.
        injector = (
            None if getattr(self, "_speculative", False) else faults.active()
        )
        self._last_ladder = None
        # Repair reconcile (plan-ahead pipelining): this solve follows
        # churn against a speculative plan — go through the
        # delta-patched warm-started PDHG path first, falling back to
        # the configured backend / degradation ladder only when the
        # delta path cannot apply.
        repair = self._repair_with_spec
        self._repair_with_spec = False
        self._last_repair = repair
        self._attempted_backend = self.backend
        # Computed once per solve, BEFORE the plan cache is overwritten:
        # consumed by the pdhg branch (primary, repair, or ladder rung)
        # and stamped into the flight-recorder snapshot — the recorder
        # slims the plan cache out of the log, so replay must carry the
        # derived warm-start vector itself to re-enter the same solve.
        # Skipped entirely when no pdhg solve can happen this round
        # (non-pdhg backend, ladder unarmed, no repair): the counts walk
        # over the cached window is pure-Python and the planner hot path
        # should not pay it to produce a value nothing reads.
        pdhg_possible = (
            self.backend == "pdhg"
            or repair
            or self.plan_deadline_s is not None
            or injector is not None
        )
        self._solve_warm_start = (
            self._solution_warm_start() if pdhg_possible else None
        )
        if self.plan_deadline_s is None and injector is None:
            if repair and self.backend != "pdhg":
                try:
                    return self._solve_backend("pdhg", problem)
                except Exception:
                    # The delta path could not apply (solver raised on
                    # the patched problem): the configured backend is
                    # the fallback, exactly as if no speculation ran.
                    obs.counter(
                        "speculation_repair_fallbacks_total",
                        "repair solves that fell back to the "
                        "configured backend",
                    ).inc()
                    self._attempted_backend = self.backend
            return self._solve_backend(self.backend, problem)
        return self._solve_with_ladder(problem, injector, repair=repair)

    def _ladder_rungs(self) -> List[str]:
        """Degradation ladder: configured backend, then the restarted
        PDHG first-order solve (cheapest device path with a quality
        story at any fleet size), then the relaxed PGD solve, then the
        native greedy (host-only). Rungs the host cannot run (no C++
        toolchain) are dropped; the primary always stays."""
        rungs = [self.backend]
        for fallback in ("pdhg", "relaxed", "native"):
            if fallback not in rungs:
                rungs.append(fallback)
        from shockwave_tpu import native as native_mod

        if not native_mod.available():
            rungs = [r for r in rungs if r != "native"] or [self.backend]
        return rungs

    def _solve_with_ladder(
        self, problem: EGProblem, injector, repair: bool = False
    ) -> "Tuple[np.ndarray, str]":
        """Run the solve down the degradation ladder under the round's
        planning budget. Every rung but the last is bounded by the
        remaining deadline (a rung that blows it is abandoned — its
        thread is left to finish into the void); the FINAL rung runs to
        completion unconditionally, because a plan is mandatory.
        Injected solver faults are consumed one per attempt:
        ``solver_timeout`` charges the rung as timed out without
        burning wall-clock (deterministic in simulation),
        ``solver_slowdown`` stretches the attempt by ``delay_s`` so a
        real deadline can overrun naturally."""
        import threading

        start = time.monotonic()
        deadline = self.plan_deadline_s
        rungs = self._ladder_rungs()
        if repair and "pdhg" in rungs:
            # Repair reconcile under an armed ladder: the delta-patched
            # PDHG solve is the designated repair path, so it leads the
            # ladder; the configured primary becomes the next rung.
            rungs = ["pdhg"] + [r for r in rungs if r != "pdhg"]
        attempts: List[dict] = []
        faults_hit: list = []
        last_error: Optional[BaseException] = None
        for i, backend in enumerate(rungs):
            is_last = i == len(rungs) - 1
            fault = (
                injector.next_solver_fault(self.round_index)
                if injector is not None
                else None
            )
            if fault is not None:
                faults_hit.append(fault)
                injector.mark_applied(
                    fault, round=self.round_index, backend=backend
                )
                obs.counter(
                    "fault_injected_total",
                    "fault events delivered by the injector",
                ).inc(kind=fault.kind)
            if (
                fault is not None
                and fault.kind == "solver_timeout"
                and not is_last
            ):
                # A plan is mandatory: an injected timeout charges every
                # rung but the last, which always runs (the docstring's
                # contract — raising here would turn a survivable
                # injected fault into a crashed round).
                attempts.append(
                    {"backend": backend, "outcome": "timeout_injected"}
                )
                last_error = TimeoutError(
                    f"injected solver timeout (fault {fault.event_id})"
                )
                continue
            remaining = (
                None
                if deadline is None
                else deadline - (time.monotonic() - start)
            )
            if remaining is not None and remaining <= 0 and not is_last:
                attempts.append(
                    {"backend": backend, "outcome": "skipped_budget"}
                )
                continue
            delay_s = fault.delay_s if fault is not None else 0.0
            box: dict = {}

            def run_attempt(backend=backend, delay_s=delay_s, fb=(i > 0)):
                try:
                    if delay_s:
                        time.sleep(delay_s)
                    box["result"] = self._solve_backend(
                        backend, problem, as_fallback=fb
                    )
                except Exception as e:  # noqa: BLE001 - re-raised below
                    box["error"] = e

            if remaining is None or is_last:
                run_attempt()
            else:
                worker = threading.Thread(target=run_attempt, daemon=True)
                worker.start()
                worker.join(remaining)
                if worker.is_alive():
                    attempts.append(
                        {"backend": backend, "outcome": "timeout"}
                    )
                    last_error = TimeoutError(
                        f"{backend} solve exceeded the remaining "
                        f"{remaining:.3f}s of the {deadline}s plan budget"
                    )
                    continue
            if "error" in box:
                attempts.append(
                    {
                        "backend": backend,
                        "outcome": type(box["error"]).__name__,
                    }
                )
                last_error = box["error"]
                continue
            Y, used = box["result"]
            attempts.append({"backend": used, "outcome": "ok"})
            degraded = i > 0
            self._last_ladder = {
                "degraded": degraded,
                "fallback_from": rungs[0] if degraded else None,
                "attempts": attempts,
            }
            if degraded:
                obs.counter(
                    "shockwave_solver_degraded_total",
                    "plan solves that fell down the degradation ladder",
                ).inc(backend=used)
                obs.instant(
                    "solver_degraded", cat="plan", pid="solver",
                    tid="planner",
                    args={
                        "round": self.round_index,
                        "fallback_from": rungs[0],
                        "backend": used,
                        "attempts": len(attempts),
                    },
                )
            recorder = obs.get_recorder()
            for fault in faults_hit:
                how = "ladder_fallback" if degraded else "ladder_absorbed"
                injector.mark_recovered(
                    fault.event_id, how=how, backend=used
                )
                if recorder.enabled:
                    record = {
                        "fault_id": fault.event_id,
                        "kind": fault.kind,
                        "round": self.round_index,
                        "pool": self.pool_label,
                    }
                    recorder.record_fault(record)
                    recorder.record_recovery(
                        {**record, "how": how, "backend": used}
                    )
            return Y, used
        if last_error is not None:
            raise last_error
        raise RuntimeError("degradation ladder produced no plan")

    def _solve_backend(
        self, backend: str, problem: EGProblem, as_fallback: bool = False
    ) -> "Tuple[np.ndarray, str]":
        """One backend's solve (the ladder's rung body).
        ``_attempted_backend`` tracks the in-flight choice so a raising
        solver is attributed to the backend that actually raised, not
        the configured dispatch name. ``as_fallback`` marks a ladder
        rung below the primary: the relaxed rung then skips its PDHG
        polish, so a failing (or deadline-blowing) PDHG kernel cannot
        take out the rung that exists to recover from it."""
        self._attempted_backend = backend
        if backend == "reference":
            from shockwave_tpu.solver.eg_milp import (
                reorder_unfair_jobs_milp,
                solve_eg_milp,
            )

            Y = solve_eg_milp(
                problem,
                rel_gap=self.solver_rel_gap,
                time_limit=self.solver_timeout,
            )
            return (
                reorder_unfair_jobs_milp(
                    Y,
                    problem,
                    rel_gap=self.solver_rel_gap,
                    time_limit=self.solver_timeout,
                ),
                "reference",
            )
        from shockwave_tpu.solver.rounding import reorder_rounds

        used = backend
        if backend == "native":
            from shockwave_tpu.native import solve_eg_greedy_native

            Y = solve_eg_greedy_native(problem)
        elif backend == "level":
            # Forced JAX level-set solve (the device path of "tpu").
            from shockwave_tpu.solver.eg_jax import solve_eg_level

            Y = solve_eg_level(problem)
        elif backend == "sharded":
            # Forced multi-chip solve: ONE planning problem's job
            # dimension sharded over every visible device
            # (shockwave_tpu/solver/eg_sharded.py). Bit-identical
            # counts to the single-device level solve, so the schedule
            # (and every downstream metric) matches the "level"
            # backend exactly; the win is headroom past one chip's
            # memory/latency at 10k+-job fleets.
            from shockwave_tpu.solver.eg_sharded import (
                solve_eg_level_sharded,
            )

            Y = solve_eg_level_sharded(problem)
        elif backend == "pdhg":
            # Restarted PDHG on the exact continuous relaxation
            # (matrix-free first-order; routes itself to the sharded
            # mesh at fleet scale), solution-warm-started from the
            # previous plan's round counts when one is cached.
            from shockwave_tpu.solver.eg_pdhg import solve_eg_pdhg

            Y = solve_eg_pdhg(
                problem,
                s0=getattr(self, "_solve_warm_start", None),
                tol=self.pdhg_tol,
            )
        elif backend == "relaxed":
            # Projected-gradient ascent on the exact continuous relaxation,
            # then integer rounding + per-round placement on host.
            from shockwave_tpu.solver.eg_jax import solve_eg_jax
            from shockwave_tpu.solver.rounding import schedule_from_relaxed

            s = solve_eg_jax(
                problem,
                num_steps=self.solver_num_steps,
                pdhg_polish=not as_fallback,
            )
            Y = schedule_from_relaxed(
                s,
                problem.priorities,
                problem.nworkers,
                problem.num_gpus,
                problem.future_rounds,
                problem=problem,
            )
        else:
            # "tpu", the production path: latency-aware dispatch. A plan
            # solve is a single problem whose result the round loop needs
            # back on host immediately, so for SMALL instances the
            # device's fixed dispatch + fetch latency dominates any
            # compute advantage and the C++ host core wins (the same
            # reasoning XLA itself applies when it keeps tiny ops on
            # host). Above the work threshold — or when no C++ toolchain
            # is available — the jitted level-set solve runs on the
            # accelerator, where its grid of candidate levels evaluates
            # in one batched launch. Both paths optimize the identical
            # objective and are cross-checked by tests.
            Y = None
            if problem.num_jobs >= sharded_dispatch_min_jobs():
                # Fleet scale trumps the native fast path: shard the
                # single solve over every chip (counts bit-identical
                # to the single-device path).
                import jax

                if len(jax.devices()) > 1:
                    from shockwave_tpu.solver.eg_sharded import (
                        solve_eg_level_sharded,
                    )

                    self._attempted_backend = "sharded"
                    Y = solve_eg_level_sharded(problem)
                    used = "sharded"
            work = (
                float(problem.num_gpus)
                * problem.future_rounds
                * problem.num_jobs
            )
            if Y is None and work < 4e6:
                from shockwave_tpu import native

                if native.available():
                    self._attempted_backend = "native"
                    Y = native.solve_eg_greedy_native(problem)
                    used = "native"
            if Y is None:
                from shockwave_tpu.solver.eg_jax import solve_eg_level

                self._attempted_backend = "level"
                Y = solve_eg_level(problem)
                used = "level"
        return (
            reorder_rounds(
                Y, problem.priorities, problem.nworkers, problem.num_gpus
            ),
            used,
        )

    def _solution_warm_start(self) -> "Optional[np.ndarray]":
        """Previous-plan round counts delta-patched onto the new job
        set, or None.

        The cached schedules for rounds >= the cursor are the
        still-valid tail of the last plan; counting each job's
        occurrences gives the s-vector that plan chose, which is a
        near-feasible saddle-point guess for the incremental replan.
        :func:`shockwave_tpu.solver.warm_start.delta_patch_counts`
        aligns it across the churn delta — departures/reclaims drop
        rows, survivors keep their counts, arrivals are seeded at an
        even split of the plan's free budget — so a 1-job delta costs
        a few moved coordinates, not a cold solve (and never a
        recompile: the job axis is padded to a fleet-size band).
        The flight recorder slims the plan cache out of its snapshots,
        so a replayed planner carries the derived vector instead
        (``pdhg_warm_start`` in the record, restored by from_state) —
        replay re-enters the exact solve the live round ran."""
        override = getattr(self, "_replay_warm_start", None)
        job_ids = getattr(self, "_plan_job_ids", None)
        if override is not None:
            # One recorded vector, one solve: clear on consumption so a
            # restored planner that keeps planning (job set drifting)
            # falls back to recomputing from its live plan cache, and
            # drop it if it no longer matches the problem rows.
            self._replay_warm_start = None
            if job_ids is not None and len(override) == len(job_ids):
                return np.asarray(override, dtype=np.float64)
        if not job_ids:
            return None
        future = [
            s for r, s in self.schedules.items() if r >= self.round_index
        ]
        if not future:
            return None
        prev_counts: Dict[object, int] = {}
        for schedule in future:
            for j in schedule:
                prev_counts[j] = prev_counts.get(j, 0) + 1
        if not prev_counts:
            return None
        from shockwave_tpu.solver import warm_start

        prev_ids = list(prev_counts)
        nworkers = np.array(
            [
                float(self.job_metadata[j].nworkers)
                if j in self.job_metadata
                else 1.0
                for j in job_ids
            ]
        )
        patched = warm_start.delta_patch_counts(
            prev_ids,
            np.array([float(prev_counts[j]) for j in prev_ids]),
            job_ids,
            nworkers,
            self.num_gpus,
            self.future_rounds,
        )
        if patched is not None:
            # Streamed mid-round arrivals ride this seeded-rows path
            # (the ingest tick admits between boundaries); count them
            # so a soak can verify delta-replans — not cold solves —
            # absorbed the stream.
            arrivals = sum(1 for j in job_ids if j not in prev_counts)
            if arrivals:
                obs.counter(
                    "planner_delta_arrivals_total",
                    "new jobs absorbed into a replan via the "
                    "delta-patched warm start (no cold solve, no "
                    "recompile)",
                ).inc(arrivals)
        return patched

    def _record_solve(
        self, seconds: float, backend: str, num_jobs: int,
        ok: bool, error: Optional[str] = None,
    ) -> None:
        """Every solve attempt lands in the timing series — including
        failed/timed-out solves, which are precisely the ones a
        debugging operator needs to see — tagged with the backend that
        produced it."""
        self.solve_times.append(seconds)
        record = {
            "backend": backend,
            "seconds": seconds,
            "ok": ok,
            "round": self.round_index,
            "num_jobs": num_jobs,
        }
        if error is not None:
            record["error"] = error
        ladder = self._last_ladder
        if ladder is not None and ladder["degraded"]:
            # A degraded round must be visible wherever operators look:
            # tagged here, counted in shockwave_solver_degraded_total,
            # and picked up by the watchdog's solver_degraded rule.
            record["degraded"] = True
            record["fallback_from"] = ladder["fallback_from"]
            record["ladder"] = [dict(a) for a in ladder["attempts"]]
        if getattr(self, "_last_repair", False):
            # Pipelining repair: this solve re-planned churn against a
            # speculative window through the delta-patched PDHG path.
            record["repair"] = True
        self.solve_records.append(record)
        obs.histogram(
            "shockwave_solve_seconds",
            "plan-solve wall time per backend (ok=False: failed solves)",
        ).observe(seconds, backend=backend, ok=str(ok))
        if not ok:
            obs.counter(
                "shockwave_solve_failures_total",
                "plan solves that raised or timed out",
            ).inc(backend=backend)

    def _replan(self) -> None:
        # Flight recorder: snapshot the PRE-replan planner state —
        # _build_problem appends to the finish-time history it also
        # reads, so replay must re-enter from exactly this point to
        # reproduce the priorities (and hence the plan) bit-for-bit.
        recorder = obs.get_recorder()
        pre_state = self.state_dict() if recorder.enabled else None
        self._replan_epoch += 1
        # Past rounds are never read again; keep the cache bounded.
        for r in [r for r in self.schedules if r < self.round_index]:
            del self.schedules[r]
        phase_h = obs.histogram(
            "shockwave_plan_phase_seconds",
            "wall time of each planning phase (build/solve/stickiness/"
            "backfill)",
        )
        with obs.span(
            "replan", cat="plan", pid="solver", tid="planner",
            args={"round": self.round_index, "backend": self.backend},
        ):
            start = time.time()
            problem, job_ids = self._build_problem()
            phase_h.observe(time.time() - start, phase="build")
            if problem is None:
                for i in range(self.future_rounds):
                    self.schedules[self.round_index + i] = []
                return
            start = time.time()
            try:
                with obs.span(
                    "solve", cat="plan", pid="solver", tid="planner",
                    args={"num_jobs": problem.num_jobs},
                ):
                    Y, backend_used = self._solve(problem)
            except Exception as e:
                elapsed = time.time() - start
                phase_h.observe(elapsed, phase="solve")
                self._record_solve(
                    elapsed,
                    getattr(self, "_attempted_backend", self.backend),
                    problem.num_jobs,
                    ok=False,
                    error=type(e).__name__,
                )
                raise
            elapsed = time.time() - start
            phase_h.observe(elapsed, phase="solve")
            self._record_solve(elapsed, backend_used, problem.num_jobs, ok=True)
            start = time.time()
            Y = self._apply_stickiness(Y, problem)
            phase_h.observe(time.time() - start, phase="stickiness")
            start = time.time()
            Y = self._backfill(Y, problem)
            phase_h.observe(time.time() - start, phase="backfill")
            for r in range(self.future_rounds):
                self.schedules[self.round_index + r] = [
                    job_ids[j] for j in range(len(job_ids)) if Y[j, r]
                ]
            if pre_state is not None:
                # Stamp the backend that ACTUALLY produced the plan into
                # the snapshot: a degraded solve (ladder fallback) must
                # replay through the same backend or the offline replan
                # would re-derive the primary backend's different plan.
                pre_state["backend"] = backend_used
                # Likewise the pdhg solution warm start: derived from
                # the pre-replan plan cache, which the recorder slims
                # out of the log — record the vector itself.
                warm = getattr(self, "_solve_warm_start", None)
                pre_state["pdhg_warm_start"] = (
                    None if warm is None else [float(x) for x in warm]
                )
                recorder.record_plan(
                    planner_state=pre_state,
                    plan={
                        r: list(self.schedules[self.round_index + r])
                        for r in range(self.future_rounds)
                    },
                    backend=backend_used,
                    objective=float(problem.objective_value(Y)),
                    solve_record=self.solve_records[-1],
                    problem_summary={
                        "job_ids": list(job_ids),
                        "remaining_runtime_s": problem.remaining_runtime,
                        "priorities": problem.priorities,
                        "switch_cost": problem.switch_cost,
                        "incumbent": problem.incumbent,
                        "nworkers": problem.nworkers,
                        "num_gpus": problem.num_gpus,
                        "future_rounds": problem.future_rounds,
                    },
                    pool=self.pool_label,
                    tags=self._plan_record_tags,
                )
            self._market_attribution(problem, job_ids, Y, backend_used)

    def _market_attribution(
        self,
        problem: EGProblem,
        job_ids: list,
        Y: np.ndarray,
        backend_used: str,
    ) -> None:
        """Market explainability tap: extract the dual/price report at
        the final plan, publish the fleet price gauges, and — when the
        flight recorder is on — stamp the per-(job, round) attribution
        record that pairs with this replan's plan record. Pure reads of
        ``(problem, Y)``: the plan itself is untouched, and with both
        the recorder and metrics off this is one boolean check."""
        speculative = bool(
            self._plan_record_tags
            and self._plan_record_tags.get("speculative")
        )
        recorder = obs.get_recorder()
        if not (recorder.enabled or obs.metrics_enabled()):
            return
        from shockwave_tpu.solver.duals import dual_report

        report = dual_report(problem, Y=Y)
        if not speculative:
            # Clone prices commit only if the reconcile accepts the
            # speculative plan; the gauges track committed plans.
            obs.gauge(
                "market_price",
                "fleet congestion price (budget dual) of the last plan",
            ).set(report.budget_dual)
            obs.gauge(
                "market_fairness_drift",
                "budget-weighted fair-share deficit of the last plan "
                "[0,1]",
            ).set(report.fairness_drift)
            # Per-job spend snapshot for the scheduler's tenant-spend
            # gauges (the planner has no tenant notion; the scheduler
            # owns the job -> tenant map).
            self.last_market = {
                "round": int(self.round_index),
                "keys": [str(j) for j in job_ids],
                "spend": [float(x) for x in report.spend],
                "price": float(report.budget_dual),
            }
        if not recorder.enabled:
            return
        from shockwave_tpu.obs.recorder import _job_key

        bonus = problem.switch_bonus()
        granted = report.s >= 0.5
        bonus_state = [
            ("applied" if g else "forfeited") if b > 0.0 else "none"
            for b, g in zip(bonus, granted)
        ]
        solve_record = self.solve_records[-1] if self.solve_records else {}
        detail = {
            "round": int(self.round_index),
            "backend": backend_used,
            "market": report.to_dict(),
            "degraded": bool(solve_record.get("degraded", False)),
            "fallback_from": solve_record.get("fallback_from"),
            "jobs": {
                "keys": [_job_key(j) for j in job_ids],
                "share": [float(x) for x in report.s],
                "fair_share": [float(x) for x in report.fair_share],
                "welfare": [float(x) for x in report.welfare_contribution],
                "marginal": [float(x) for x in report.marginal_welfare],
                "price": [float(x) for x in report.price],
                "spend": [float(x) for x in report.spend],
                "bonus": [float(x) for x in bonus],
                "bonus_state": bonus_state,
                "switch_cost": [float(x) for x in problem.switch_cost],
                "makespan_binding": [
                    int(x) for x in report.makespan_binding
                ],
                "predicted_finish_s": [
                    float(self.finish_time_estimates[j][-1][1])
                    if self.finish_time_estimates.get(j)
                    else None
                    for j in job_ids
                ],
            },
        }
        if self.pool_label is not None:
            detail["pool"] = self.pool_label
        if speculative:
            # The narrative builder admits this record only when the
            # round-boundary reconcile commits the speculative plan
            # (``speculation`` record, kind ``hit``).
            detail["speculative"] = True
        recorder.record_attribution(detail)

    def _apply_stickiness(self, Y: np.ndarray, problem: EGProblem) -> np.ndarray:
        """Lease stickiness: pull granted incumbents into the plan's first
        round so the scheduler's keep-previous-workers pass (and physical
        mode's lease extension) can hold their placements.

        The switching-cost term decides WHETHER an incumbent keeps any
        rounds; this pass decides WHERE. All moves preserve per-job round
        counts and per-round capacity, so utility and makespan are
        untouched — only the (secondary) unfairness-reordering objective
        can regress, and a swap is taken only when the avoided relaunch
        delay beats that regression in the reorder program's own currency
        (priority-rate x rounds): displacing job k from round 0 to round
        r costs (rate_k - rate_j) * r, keeping incumbent j running saves
        it a rate_j * overhead_j / round_duration re-launch delay.
        """
        bonus = problem.switch_bonus()
        if not np.any(bonus > 0.0):
            return Y
        J, R = Y.shape
        counts = Y.sum(axis=1)
        with np.errstate(divide="ignore", invalid="ignore"):
            rate = np.where(
                counts > 0, problem.priorities / np.maximum(counts, 1), 0.0
            )
        free0 = float(problem.num_gpus) - float(
            np.sum(problem.nworkers * Y[:, 0])
        )
        # Largest overheads first: they have the strongest claim on the
        # scarce round-0 capacity.
        for j in np.argsort(-bonus):
            if bonus[j] <= 0.0 or Y[j, 0] == 1 or counts[j] == 0:
                continue
            r_star = int(np.argmax(Y[j] == 1))
            if problem.nworkers[j] <= free0:
                # Free capacity in round 0: moving j earlier also
                # improves the reordering objective. Always take it.
                Y[j, 0], Y[j, r_star] = 1, 0
                free0 -= problem.nworkers[j]
                continue
            # Swap with a round-0 occupant: never preempt another
            # incumbent-with-overhead to save this one, keep both rounds
            # within capacity, and require the relaunch delay avoided to
            # beat the fairness-ordering regression.
            delay_rounds = problem.switch_cost[j] / max(
                problem.round_duration, 1e-9
            )
            load_r = float(np.sum(problem.nworkers * Y[:, r_star]))
            best_k, best_delta = None, None
            for k in range(J):
                if k == j or Y[k, 0] == 0 or Y[k, r_star] == 1:
                    continue
                if bonus[k] > 0.0:
                    continue
                if problem.nworkers[j] - problem.nworkers[k] > free0:
                    continue
                if (
                    load_r - problem.nworkers[j] + problem.nworkers[k]
                    > problem.num_gpus
                ):
                    continue
                delta = (rate[k] - rate[j]) * r_star  # reorder regression
                if rate[j] * delay_rounds <= self.stickiness_hysteresis * delta:
                    continue
                if best_delta is None or delta < best_delta:
                    best_k, best_delta = k, delta
            if best_k is not None:
                Y[j, 0], Y[j, r_star] = 1, 0
                Y[best_k, 0], Y[best_k, r_star] = 0, 1
                free0 += problem.nworkers[best_k] - problem.nworkers[j]
        return Y

    def _backfill(self, Y: np.ndarray, problem: EGProblem) -> np.ndarray:
        """Fill any round left completely idle while unfinished jobs exist
        (the scheduler treats an empty round as end-of-trace; the MILP can
        legitimately leave a round empty when every job's utility is
        saturated, which would wedge the mechanism)."""
        J, R = Y.shape
        order = np.argsort(-problem.priorities, kind="stable")
        for r in range(R):
            if Y[:, r].any():
                continue
            capacity = float(problem.num_gpus)
            for j in order:
                if problem.nworkers[j] <= capacity:
                    Y[j, r] = 1
                    capacity -= problem.nworkers[j]
                if capacity <= 0:
                    break
        return Y


class PoolSetPlanner:
    """Heterogeneous Shockwave: one independent EG plan per worker-type
    pool.

    BEYOND REFERENCE: the reference's Shockwave plans a single
    homogeneous pool and leaves every other worker type idle (reference:
    scheduler/scheduler.py:991-1014 filters scheduling to the planned
    pool). Here a mixed cluster gets one child :class:`ShockwavePlanner`
    per worker type; each job is assigned to a pool at admission (by the
    scheduler, which owns the throughput oracle) with its profile
    durations rescaled to that pool's speed, and every pool plans —
    and runs — its own jobs each round.

    Exposes the same interface the scheduler drives a single planner
    with, routing per-job calls through the job->pool map, plus
    ``current_round_schedule_by_pool`` for pool-aware dispatch and
    ``pool_of`` for per-pool progress accounting.
    """

    def __init__(self, config: dict, backend: str, pools: Dict[str, int]):
        self.config = dict(config)
        self.backend = backend
        self.pools = dict(pools)
        self.children: "OrderedDict[str, ShockwavePlanner]" = OrderedDict(
            (wt, ShockwavePlanner({**config, "num_gpus": n}, backend=backend))
            for wt, n in sorted(pools.items())
        )
        for wt, child in self.children.items():
            child.pool_label = wt
        self.job_pool: Dict[object, str] = {}
        # Cumulative admissions per pool (observability; the live load
        # used for balancing is pool_incomplete_jobs).
        self.assignments: Dict[str, int] = {wt: 0 for wt in self.children}

    # -- scheduler-facing interface (same vocabulary as ShockwavePlanner)
    def add_job(
        self, job_id, profile: dict, round_len: float, scale_factor: int,
        submit_time: Optional[float] = None, pool: Optional[str] = None,
        duration_scale: float = 1.0, overhead_s: float = 0.0,
    ) -> None:
        pool = pool if pool in self.children else next(iter(self.children))
        if duration_scale != 1.0:
            profile = dict(profile)
            profile["duration_every_epoch"] = [
                d * duration_scale for d in profile["duration_every_epoch"]
            ]
        self.job_pool[job_id] = pool
        self.assignments[pool] = self.assignments.get(pool, 0) + 1
        self.children[pool].add_job(
            job_id, profile, round_len, scale_factor, submit_time,
            overhead_s=overhead_s,
        )

    def pool_incomplete_jobs(self, pool: str) -> int:
        """Live count of the pool's incomplete jobs (the fair-share
        population the scheduler's assignment estimate divides by)."""
        child = self.children.get(pool)
        if child is None:
            return 0
        return sum(
            1
            for md in child.job_metadata.values()
            if md.completed_epochs < md.total_epochs
        )

    def _child_of(self, job_id) -> Optional[ShockwavePlanner]:
        pool = self.job_pool.get(job_id)
        return self.children.get(pool) if pool is not None else None

    def pool_of(self, job_id) -> Optional[str]:
        return self.job_pool.get(job_id)

    def remove_job(self, job_id) -> None:
        child = self._child_of(job_id)
        if child is not None:
            child.remove_job(job_id)
        self.job_pool.pop(job_id, None)

    def record_round_throughput(self, job_id, round_id, throughput, bs) -> None:
        child = self._child_of(job_id)
        if child is not None:
            child.record_round_throughput(job_id, round_id, throughput, bs)

    def mark_complete(self, job_id) -> None:
        child = self._child_of(job_id)
        if child is not None:
            child.mark_complete(job_id)

    def set_progress(self, job_id, num_epochs: int) -> None:
        child = self._child_of(job_id)
        if child is not None:
            child.set_progress(job_id, num_epochs)

    def get_metadata(self, job_id):
        child = self._child_of(job_id)
        return child.get_metadata(job_id) if child is not None else None

    def increment_round(self) -> None:
        for child in self.children.values():
            child.increment_round()

    def set_pool_capacity(self, worker_type: str, num_gpus: int) -> None:
        """Capacity change inside one pool (worker death / churn)."""
        child = self.children.get(worker_type)
        if child is None:
            return
        self.pools[worker_type] = max(1, int(num_gpus))
        child.set_capacity(num_gpus)

    def set_recompute_flag(self, jobs=None) -> None:
        if jobs is not None:
            owners = [
                child
                for child in self.children.values()
                if any(j in child.job_metadata for j in jobs)
            ]
            if all(
                any(j in c.job_metadata for c in self.children.values())
                for j in jobs
            ):
                for child in owners:
                    child.set_recompute_flag(jobs)
                return
        # Bare call, or a job no child owns: stale everything.
        for child in self.children.values():
            child.set_recompute_flag()

    @property
    def num_jobs(self) -> int:
        return sum(c.num_jobs for c in self.children.values())

    @property
    def solve_times(self) -> List[float]:
        return [t for c in self.children.values() for t in c.solve_times]

    @property
    def solve_records(self) -> List[dict]:
        return [
            {**r, "pool": wt}
            for wt, c in self.children.items()
            for r in c.solve_records
        ]

    def current_round_schedule_by_pool(self) -> "OrderedDict[str, list]":
        return OrderedDict(
            (wt, child.current_round_schedule())
            for wt, child in self.children.items()
        )

    def current_round_schedule(self) -> list:
        return [
            j
            for schedule in self.current_round_schedule_by_pool().values()
            for j in schedule
        ]

    # -- serialization --------------------------------------------------
    def state_dict(self) -> dict:
        return {
            "kind": "pool_set",
            "config": dict(self.config),
            "backend": self.backend,
            "pools": dict(self.pools),
            "children": OrderedDict(
                (wt, c.state_dict()) for wt, c in self.children.items()
            ),
            "job_pool": dict(self.job_pool),
            "assignments": dict(self.assignments),
        }

    @classmethod
    def from_state(cls, state: dict) -> "PoolSetPlanner":
        planner = cls(state["config"], state["backend"], state["pools"])
        planner.children = OrderedDict(
            (wt, ShockwavePlanner.from_state(cs))
            for wt, cs in state["children"].items()
        )
        for wt, child in planner.children.items():
            child.pool_label = wt
        planner.job_pool = dict(state["job_pool"])
        planner.assignments = dict(state.get("assignments", {}))
        return planner


def planner_from_state(state: dict):
    """Restore whichever planner kind a checkpoint carries."""
    if state.get("kind") == "pool_set":
        return PoolSetPlanner.from_state(state)
    if state.get("kind") == "cell_set":
        from shockwave_tpu.cells.planner import CellPlanner

        return CellPlanner.from_state(state)
    return ShockwavePlanner.from_state(state)


class ShockwavePolicy(Policy):
    """Marker policy selecting the Shockwave mechanism path in the
    scheduler; carries the planner factory."""

    def __init__(self, backend: str = "tpu"):
        super().__init__()
        self.backend = backend
        self.name = {
            "reference": "Shockwave",
            "native": "Shockwave_Native",
            "level": "Shockwave_TPU_Level",
            "relaxed": "Shockwave_TPU_Relaxed",
            "sharded": "Shockwave_TPU_Sharded",
            "pdhg": "Shockwave_TPU_PDHG",
            "cells": "Shockwave_TPU_Cells",
        }.get(backend, "Shockwave_TPU")

    def make_planner(self, config: dict):
        # Cell-decomposed dispatch: the "cells" backend — or any
        # backend with a "cells" count in the config — plans through
        # the partitioned-market federation (shockwave_tpu/cells/)
        # instead of one global solve.
        if self.backend == "cells" or int(config.get("cells", 0) or 0) >= 2:
            from shockwave_tpu.cells.planner import CellPlanner

            return CellPlanner(config, backend=self.backend)
        return ShockwavePlanner(config, backend=self.backend)

    def get_allocation(self, *args, **kwargs):
        # The scheduler never requests a fractional allocation for
        # Shockwave; rounds come from the planner.
        return {}
