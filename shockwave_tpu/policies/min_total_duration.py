"""Makespan minimization: binary search over a horizon T for the smallest T
such that a feasibility LP ("every job finishes its remaining steps within
T") admits an allocation. Reference:
scheduler/policies/min_total_duration.py:1-195.
"""

from __future__ import annotations

import numpy as np

from shockwave_tpu.policies.base import (
    Policy,
    PolicyWithPacking,
    constraint_matrices,
    packed_constraint_matrices,
)
from shockwave_tpu.policies.lp_backend import feasibility_lp_general

MIN_T = 100.0
MAX_T = 1000000.0


def _binary_search_T(coeff_rows, num_steps, A_base, b_base, zero_mask=None):
    """Smallest T (within 5%) with a feasible x; expands the bracket by
    10x while infeasible (reference: min_total_duration.py:80-103)."""
    steps = np.asarray(num_steps, dtype=np.float64)

    def solve(T):
        return feasibility_lp_general(
            coeff_rows, steps / T, A_base, b_base, zero_mask=zero_mask
        )

    min_T, max_T = MIN_T, MAX_T
    last_max_T = MAX_T
    best = None
    while best is None:
        while 1.05 * min_T < max_T:
            T = (min_T + max_T) / 2.0
            x = solve(T)
            if x is not None:
                best = x
                max_T = T
            else:
                min_T = T
        if best is not None:
            break
        min_T, max_T = last_max_T, last_max_T * 10.0
        last_max_T *= 10.0
        if last_max_T > 1e12:
            return None
    return best


class MinTotalDurationPolicyWithPerf(Policy):
    name = "MinTotalDuration_Perf"

    def get_allocation(
        self, throughputs, scale_factors, num_steps_remaining, cluster_spec
    ):
        matrix, index = self.flatten(throughputs, cluster_spec)
        if matrix is None:
            return None
        m, n = matrix.shape
        job_ids, _ = index
        sf = self.scale_factors_array(scale_factors, job_ids, m, n)
        coeff_rows = np.zeros((m, m * n))
        for i in range(m):
            coeff_rows[i, i * n : (i + 1) * n] = matrix[i]
        A_base, b_base = constraint_matrices(sf, self._num_workers)
        x = _binary_search_T(
            coeff_rows, [num_steps_remaining[j] for j in job_ids], A_base, b_base
        )
        if x is None:
            return None
        return self.unflatten(x.reshape(m, n).clip(0.0, 1.0), index)


class MinTotalDurationPolicy(Policy):
    """Throughput-agnostic wrapper: every type behaves like v100
    (reference: min_total_duration.py:11-36)."""

    name = "MinTotalDuration"

    def __init__(self, solver=None):
        super().__init__(solver)
        self._perf_policy = MinTotalDurationPolicyWithPerf(solver)

    def get_allocation(
        self, throughputs, scale_factors, num_steps_remaining, cluster_spec
    ):
        from shockwave_tpu.policies.base import canonical_throughputs

        flat = canonical_throughputs(throughputs)
        return self._perf_policy.get_allocation(
            flat, scale_factors, num_steps_remaining, cluster_spec
        )


class MinTotalDurationPolicyWithPacking(PolicyWithPacking):
    name = "MinTotalDuration_Packing"

    def get_allocation(
        self, throughputs, scale_factors, num_steps_remaining, cluster_spec
    ):
        all_m, index = self.flatten(throughputs, cluster_spec)
        if all_m is None or len(all_m) == 0:
            return None
        job_ids, single_job_ids, worker_types, relevant = index
        C, W = len(job_ids), len(worker_types)
        S = len(single_job_ids)
        sf = self.scale_factors_array(scale_factors, job_ids, C, W)
        coeff_rows = all_m.reshape(S, C * W)
        A_base, b_base = packed_constraint_matrices(
            sf, self._num_workers, single_job_ids, relevant
        )
        zero_mask = (sf.reshape(-1) == 0).astype(bool)
        x = _binary_search_T(
            coeff_rows,
            [num_steps_remaining[s] for s in single_job_ids],
            A_base,
            b_base,
            zero_mask=zero_mask,
        )
        if x is None:
            return None
        return self.unflatten(x.reshape(C, W).clip(0.0, 1.0), index)
