"""Plan-ahead pipelining: speculative next-round solves.

Round r's execution and round r+1's plan solve are serialized in the
baseline scheduler: the solve bill lands at the round boundary, inside
the round loop (and, in physical mode, under the round loop's condition
lock). This module overlaps them. While round r runs, the planner is
cloned from a snapshot of its state, the round's *predicted* outcome is
applied to the clone (progress, throughput records, completions), and
the clone solves round r+1 — on a background thread in physical mode,
inline at the same control point in simulation (where solver wall time
never advances virtual time, so the "background" is free by
construction and the machinery is exercised identically).

At the round boundary the speculation is **reconciled** against
reality:

* **hit** — nothing churned between snapshot and boundary (same job
  set, same capacity, per-job progress within
  ``speculate_epoch_tolerance`` epochs, no external recompute flag):
  the speculative plan window is installed directly and the boundary
  pays no solve at all. In simulation the predicted outcome is exact,
  so an installed plan is bit-identical to what the serial boundary
  solve would have produced (pinned by tests).
* **repair** — jobs arrived/departed/were reclaimed, capacity moved,
  or progress diverged past the tolerance, AND the boundary was going
  to re-solve anyway (recompute flagged, or the cached round went
  stale): the speculative plan window is installed as the warm-start
  basis and the boundary re-solves with the delta-patched warm-started
  PDHG backend (:func:`shockwave_tpu.solver.warm_start
  .delta_patch_counts` aligns the speculative solution across the
  churn delta), falling back to the existing degradation ladder only
  when the delta path cannot apply. A repair costs a warm first-order
  solve (~ms), not a cold solve. Churn that the serial boundary would
  have absorbed WITHOUT a re-solve (e.g. an arrival waiting for the
  next natural replan) discards the speculation instead — pipelining
  never re-plans more eagerly than the serial scheduler, so the two
  runs make identical admission/planning decisions and the A/B
  isolates pure overhead.
* **miss** — the speculative solve failed, never finished inside the
  join budget, targeted a different round than the one being
  reconciled, or churned while the boundary still serves its cache:
  the boundary falls back to the serial path untouched.

Flight-recorder exactness: the speculative solve *is* a ``_replan`` on
the clone, so it records a normal plan record (tagged
``speculative: true``) whose snapshot is the clone's pre-replan state —
replay re-enters the identical solve. Reconcile outcomes are stamped as
``speculation`` records. Because the clone's throughput schedules carry
*predicted* tail entries that the live planner may never see (physical
mode measures different values), speculative records are slimmed as
overlays: their predicted tails are not folded into the recorder's
delta-encoded accumulation, so every non-speculative record downstream
still replays from the measured history (see
:meth:`shockwave_tpu.obs.recorder.FlightRecorder`).

The cell-decomposed planner speculates the whole federation and
reconciles per cell: cells whose predicted state matches reality
install their speculative windows, churned cells alone are marked stale
and re-solve at the boundary (warm-started from the installed
speculative windows through the existing batched path).
"""

from __future__ import annotations

import copy
import threading
import time
from typing import Dict, List, Optional

from shockwave_tpu import obs

# Epochs of per-job progress divergence a speculation survives before
# reality is declared churned (0 = any divergence repairs). Simulation
# predicts outcomes exactly, so the tolerance only matters in physical
# mode, where epoch-boundary races against measured throughput are the
# common benign divergence.
DEFAULT_EPOCH_TOLERANCE = 0
# Seconds the boundary reconcile waits for a still-running background
# speculative solve before declaring a miss and solving serially.
DEFAULT_JOIN_TIMEOUT_S = 10.0


class SpecOutcome:
    """The predicted state delta between the speculation snapshot and
    the next round boundary, supplied by the scheduler (which owns the
    execution model): per-job progress after the boundary's
    ``set_progress`` pass, the throughput records the round's completion
    merge will append, the jobs predicted to complete (and leave the
    planner), and the fleet capacity."""

    __slots__ = (
        "target_round", "progress", "throughputs", "completions",
        "capacity",
    )

    def __init__(
        self,
        target_round: int,
        progress: Dict[object, int],
        throughputs: List[tuple],
        completions: List[object],
        capacity: int,
    ):
        self.target_round = int(target_round)
        self.progress = dict(progress)
        self.throughputs = list(throughputs)
        self.completions = list(completions)
        self.capacity = int(capacity)


class SpeculativePlannerMixin:
    """The pipelining scaffolding both planner kinds share: the
    speculation slot + knobs (``_init_speculation``, called from
    ``__init__``), the public ``speculate_next_round`` /
    ``_reconcile_speculation`` entry points, and the exposed-boundary
    ledger. The kind-specific reconcile hooks
    (``_install_speculation`` / ``_prepare_repair`` /
    ``_augment_mismatch`` / ``_spec_solve_base``) stay on the
    planners."""

    def _init_speculation(self, config: dict) -> None:
        self._speculation: Optional[Speculation] = None
        self._speculate_epoch_tolerance = int(
            config.get("speculate_epoch_tolerance", DEFAULT_EPOCH_TOLERANCE)
        )
        self._speculate_join_s = float(
            config.get("speculate_join_s", DEFAULT_JOIN_TIMEOUT_S)
        )
        # Tags merged into the next flight-recorder plan record
        # (speculative clones stamp {"speculative": True}).
        self._plan_record_tags: Optional[dict] = None
        self._last_repair = False
        # Monotone replan counter (speculation detects whether its
        # clone actually solved) and the exposed side of the
        # hidden-vs-exposed pipelining ledger: planning wall time spent
        # ON THE ROUND LOOP'S THREAD (a boundary serve, or physical
        # mode's mid-round pass — which overlaps worker execution
        # wall-clock-wise but runs under the condition lock, blocking
        # completion RPCs and bounding how short rounds can get).
        # Speculative solves run off-thread and ride the hidden
        # histogram instead.
        self._replan_epoch = 0
        self.exposed_plan_times: List[float] = []
        self.spec_stats: Dict[str, int] = {
            "hit": 0, "repair": 0, "miss": 0,
        }

    def speculate_next_round(self, outcome, background: bool = False):
        """Kick a speculative solve of ``outcome.target_round`` from a
        snapshot of the current planner state plus the scheduler's
        predicted round outcome. ``background=True`` (physical mode)
        runs the apply+solve on a daemon thread sharing nothing
        mutable with the live planner; simulation runs it inline —
        solver wall time never advances virtual time, so the overlap
        is free by construction and the machinery is identical."""
        return begin_speculation(self, outcome, background)

    def _reconcile_speculation(self) -> Optional[str]:
        return reconcile_speculation(self)

    def reconcile_at_boundary(self) -> Optional[str]:
        """Public boundary entry for schedulers that reconcile ahead of
        their own schedule passes (the physical round loop does, so a
        hit's installed window feeds the assignment pass and a repair
        is armed before it solves). Reconciles the pending speculation
        and self-observes the wall time as exposed planning time —
        identical protocol to ``current_round_schedule``'s internal
        reconcile, kept here so the two planner kinds and the physical
        scheduler can never drift apart. Returns the reconcile outcome
        ("hit"/"repair"/"miss") or None when nothing was pending."""
        if self._speculation is None:
            return None
        start = time.perf_counter()
        outcome = self._reconcile_speculation()
        if outcome is not None:
            self._observe_boundary(time.perf_counter() - start)
        return outcome

    def _observe_boundary(self, seconds: float) -> None:
        if getattr(self, "_speculative", False):
            # A speculation clone's solve is HIDDEN time; it rides
            # observe_hidden_solve, never the exposed-boundary ledger.
            return
        self.exposed_plan_times.append(seconds)
        observe_exposed(seconds, self.round_duration)


class Speculation:
    """One in-flight (or finished) speculative solve."""

    def __init__(self, outcome: SpecOutcome):
        self.outcome = outcome
        self.clone = None
        self.fingerprint: Optional[dict] = None
        # True once the clone ran an actual replan (vs predicting the
        # boundary would serve from cache — a solve-free "hit").
        self.solved = False
        self.error: Optional[BaseException] = None
        self.solve_seconds = 0.0
        # The live planner's solve-bookkeeping lengths at snapshot time
        # (``_spec_solve_base()`` — an int for a flat planner, a dict
        # for the cell federation): install/repair appends only the
        # clone's NEW records, immune to live solves that land between
        # snapshot and boundary (physical mode's mid-round pass).
        self.base_solve_records = 0
        self.done = threading.Event()

    @property
    def ok(self) -> bool:
        return self.done.is_set() and self.error is None


# ----------------------------------------------------------------------
# Cloning. state_dict() is shallow where it can afford to be (the
# checkpoint path pickles, which copies implicitly); a speculation clone
# shares the process with the live planner, so every structure either
# side mutates must be deep-copied: per-job throughput schedules (the
# clone applies predicted records), the Dirichlet posterior (mutated by
# the change-point reweight), and the batch-size tripwire.
# ----------------------------------------------------------------------
_MUTABLE_MD_FIELDS = ("throughput_schedule", "dirichlet")


def _copy_flat_state(flat: dict) -> dict:
    out = dict(flat)
    out["job_metadata"] = {
        job_id: {
            **md_state,
            **{
                f: copy.copy(md_state[f])
                for f in _MUTABLE_MD_FIELDS
                if f in md_state
            },
        }
        for job_id, md_state in flat["job_metadata"].items()
    }
    return out


def clone_planner(planner):
    """An isolated planner clone sharing no mutable state with the
    live planner (numpy profile arrays are shared read-only — nothing
    rebinding them in place exists on either side)."""
    from shockwave_tpu.policies.shockwave import planner_from_state

    state = planner.state_dict()
    if "children" in state:
        state = dict(state)
        state["children"] = type(state["children"])(
            (name, _copy_flat_state(child))
            for name, child in state["children"].items()
        )
    else:
        state = _copy_flat_state(state)
    return planner_from_state(state)


# ----------------------------------------------------------------------
# Fingerprints: what must agree between prediction and reality for a
# speculative plan to install. Computed identically on the clone (after
# the predicted outcome is applied) and on the live planner at the
# boundary.
# ----------------------------------------------------------------------
def _flat_fingerprint(planner) -> dict:
    return {
        "capacity": int(planner.num_gpus),
        "progress": {
            j: int(md.completed_epochs)
            for j, md in planner.job_metadata.items()
            if md.completed_epochs < md.total_epochs
        },
    }


def planner_fingerprint(planner) -> dict:
    children = getattr(planner, "children", None)
    if children is None:
        return _flat_fingerprint(planner)
    return {
        "capacity": int(planner.num_gpus),
        "cells": {
            name: {
                **_flat_fingerprint(child),
                "capacity": int(planner.cells[name]),
            }
            for name, child in children.items()
        },
    }


def _diff_flat(predicted: dict, live: dict, tolerance: int) -> List[str]:
    reasons = []
    if predicted["capacity"] != live["capacity"]:
        reasons.append(
            f"capacity {predicted['capacity']} -> {live['capacity']}"
        )
    pred_jobs, live_jobs = predicted["progress"], live["progress"]
    arrived = sorted(str(j) for j in live_jobs.keys() - pred_jobs.keys())
    departed = sorted(str(j) for j in pred_jobs.keys() - live_jobs.keys())
    if arrived:
        reasons.append(f"arrived:{','.join(arrived[:4])}")
    if departed:
        reasons.append(f"departed:{','.join(departed[:4])}")
    drifted = sorted(
        str(j)
        for j in pred_jobs.keys() & live_jobs.keys()
        if abs(pred_jobs[j] - live_jobs[j]) > tolerance
    )
    if drifted:
        reasons.append(f"progress:{','.join(drifted[:4])}")
    return reasons


def diff_fingerprints(
    predicted: dict, live: dict, tolerance: int
) -> Dict[str, List[str]]:
    """{} when the speculation still describes reality; otherwise a map
    of scope ("" for a flat planner, the cell name for a federation) to
    human-readable churn reasons."""
    if "cells" not in predicted or "cells" not in live:
        reasons = _diff_flat(predicted, live, tolerance)
        return {"": reasons} if reasons else {}
    out: Dict[str, List[str]] = {}
    if predicted["capacity"] != live["capacity"]:
        out[""] = [
            f"capacity {predicted['capacity']} -> {live['capacity']}"
        ]
    names = predicted["cells"].keys() | live["cells"].keys()
    for name in sorted(names):
        pred = predicted["cells"].get(name)
        liv = live["cells"].get(name)
        if pred is None or liv is None:
            out[name] = ["cell set changed"]
            continue
        reasons = _diff_flat(pred, liv, tolerance)
        if pred["capacity"] != liv["capacity"]:
            reasons.append(
                f"cell capacity {pred['capacity']} -> {liv['capacity']}"
            )
        if reasons:
            out[name] = reasons
    return out


# ----------------------------------------------------------------------
# Observability taps (shared by both planner kinds).
# ----------------------------------------------------------------------
def observe_reconcile(outcome: str, round_index: int, detail=None) -> None:
    obs.counter(
        "speculation_rounds_total",
        "boundary reconciles of speculative plans, by outcome",
    ).inc(outcome=outcome)
    recorder = obs.get_recorder()
    if recorder.enabled:
        record = {"kind": outcome, "round": int(round_index)}
        if detail:
            record["detail"] = detail
        recorder.record_speculation(record)
    obs.instant(
        "speculation_" + outcome, cat="plan", pid="solver",
        tid="speculation",
        args={"round": int(round_index), **({"detail": str(detail)} if detail else {})},
    )


def observe_hidden_solve(seconds: float) -> None:
    obs.histogram(
        "shockwave_plan_hidden_seconds",
        "speculative plan-solve wall time hidden behind round execution",
    ).observe(seconds)


def observe_exposed(seconds: float, round_duration: float) -> None:
    """Planning time spent on the round loop's thread — reconcile,
    install, and any (repair or serial) solve, whether it lands at the
    boundary or in physical mode's mid-round pass (overlapped with
    worker execution wall-clock-wise, but holding the condition lock).
    Both A/B arms count the same quantity; the speculative path's win
    is moving solves off this thread entirely."""
    obs.histogram(
        "shockwave_plan_exposed_seconds",
        "boundary planning wall time the round loop waited for",
    ).observe(seconds)
    if round_duration > 0:
        obs.gauge(
            "effective_planning_overhead",
            "exposed boundary planning time as a fraction of the round",
        ).set(seconds / round_duration)


def begin_speculation(planner, outcome: SpecOutcome, background: bool = False):
    """Shared entry point behind ``speculate_next_round``:
    snapshot+clone under the caller's lock discipline, and run the
    apply+solve inline or on a daemon thread. Reconcile identity needs
    no generation counter — the boundary pops ``planner._speculation``
    before judging it, so a newer speculation can never be reconciled
    against an older boundary."""
    spec = Speculation(outcome)
    spec.base_solve_records = planner._spec_solve_base()
    clone = clone_planner(planner)
    # The clone must never consume injected solver faults (they are the
    # LIVE ladder's events — a speculative solve burning one would
    # de-synchronize chaos runs from their serial baseline) and must
    # not write its hidden solve time into the exposed-boundary ledger.
    _mark_speculative(clone)
    planner._speculation = spec
    if background:
        threading.Thread(
            target=run_speculation, args=(spec, clone, {}), daemon=True
        ).start()
    else:
        run_speculation(spec, clone, {})
    return spec


def _mark_speculative(clone) -> None:
    clone._speculative = True
    for child in getattr(clone, "children", {}).values():
        child._speculative = True


def reconcile_speculation(planner) -> Optional[str]:
    """Reconcile a planner's pending speculation against reality at the
    round boundary. Returns None (nothing pending, or a mid-round pass
    before the target boundary) or the outcome: "hit" (speculative
    plan installed, boundary pays no solve), "repair" (churn on a
    boundary that was going to re-solve anyway — the planner arms its
    delta-patched repair path, warm-started from the speculative
    window), "miss" (speculation unusable, or churn on a cache-valid
    boundary; serial path untouched). The planner supplies the
    kind-specific hooks ``_install_speculation(spec)``,
    ``_prepare_repair(spec, mismatch) -> bool`` (True when a repair
    solve was armed) and ``_augment_mismatch(mismatch)``."""
    spec = planner._speculation
    if spec is None:
        return None
    if planner.round_index < spec.outcome.target_round:
        return None
    planner._speculation = None
    if not spec.done.wait(planner._speculate_join_s):
        planner.spec_stats["miss"] += 1
        observe_reconcile("miss", planner.round_index, "join_timeout")
        return "miss"
    if spec.error is not None or (
        planner.round_index != spec.outcome.target_round
    ):
        reason = (
            f"error:{type(spec.error).__name__}"
            if spec.error is not None
            else f"round_skew:{spec.outcome.target_round}"
            f"->{planner.round_index}"
        )
        planner.spec_stats["miss"] += 1
        observe_reconcile("miss", planner.round_index, reason)
        return "miss"
    mismatch = diff_fingerprints(
        spec.fingerprint,
        planner_fingerprint(planner),
        planner._speculate_epoch_tolerance,
    )
    mismatch = planner._augment_mismatch(mismatch)
    if not mismatch:
        planner._install_speculation(spec)
        planner.spec_stats["hit"] += 1
        observe_reconcile(
            "hit", planner.round_index,
            "installed" if spec.solved else "cache_valid",
        )
        return "hit"
    detail = {
        scope or "fleet": reasons for scope, reasons in mismatch.items()
    }
    if planner._prepare_repair(spec, mismatch):
        planner.spec_stats["repair"] += 1
        observe_reconcile("repair", planner.round_index, detail)
        return "repair"
    # Churned, but the serial boundary serves its cache: discard the
    # speculation so pipelined and serial runs make the same decision.
    planner.spec_stats["miss"] += 1
    observe_reconcile(
        "miss", planner.round_index, {"cache_valid": True, **detail}
    )
    return "miss"


def run_speculation(spec: Speculation, clone, tags: dict) -> None:
    """Apply the predicted outcome to the clone, advance it to the
    target round, and replan if (and only if) the boundary would. Runs
    inline in simulation, on a daemon thread in physical mode; touches
    nothing but the clone and the (locked) obs planes."""
    outcome = spec.outcome
    try:
        with obs.span(
            "speculate", cat="plan", pid="solver", tid="speculation",
            args={"round": outcome.target_round},
        ):
            for job, round_id, tput, bs in outcome.throughputs:
                clone.record_round_throughput(job, round_id, tput, bs)
            for job, epochs in outcome.progress.items():
                clone.set_progress(job, epochs)
            for job in outcome.completions:
                clone.mark_complete(job)
                clone.remove_job(job)
            if outcome.capacity != clone.num_gpus:
                clone.set_capacity(outcome.capacity)
            clone.increment_round()
            spec.fingerprint = planner_fingerprint(clone)
            clone._plan_record_tags = {"speculative": True, **tags}
            before = getattr(clone, "_replan_epoch", 0)
            t0 = time.perf_counter()
            # current_round_schedule is the boundary's own entry point:
            # it replans exactly when the boundary would (stale cache,
            # recompute flag, exhausted window) and serves from cache
            # otherwise — a cache-served boundary is a solve-free hit.
            clone.current_round_schedule()
            spec.solve_seconds = time.perf_counter() - t0
            spec.solved = getattr(clone, "_replan_epoch", 0) > before
            if spec.solved:
                observe_hidden_solve(spec.solve_seconds)
        spec.clone = clone
    except Exception as e:  # pragma: no cover - surfaced at reconcile
        spec.error = e
        obs.counter(
            "speculation_failures_total",
            "speculative solves that raised (reconciled as misses)",
        ).inc()
    finally:
        spec.done.set()
