"""Isolated allocation: the cluster split evenly across jobs, each job's
share scaled down by its gang size. Used directly and as the normalizer for
finish-time fairness (reference: scheduler/policies/isolated.py:33-66)."""

from __future__ import annotations

import numpy as np

from shockwave_tpu.policies.base import Policy


class IsolatedPolicy(Policy):
    name = "Isolated"

    def _allocation_matrix(self, m, n, scale_factors_array, num_workers):
        x = np.tile(np.asarray(num_workers, dtype=np.float64) / m, (m, 1))
        x = x / scale_factors_array
        row_sums = np.maximum(x.sum(axis=1), 1.0)
        return x / row_sums[:, None]

    def get_allocation(self, throughputs, scale_factors, cluster_spec):
        matrix, index = self.flatten(throughputs, cluster_spec)
        if matrix is None:
            return None
        m, n = matrix.shape
        sf = self.scale_factors_array(scale_factors, index[0], m, n)
        return self.unflatten(
            self._allocation_matrix(m, n, sf, self._num_workers), index
        )

    def get_throughputs(self, throughputs, index, scale_factors, num_workers):
        """Effective throughput of each job under the isolated allocation.
        ``num_workers`` is the per-worker-type count list aligned with the
        flattened matrix columns."""
        if throughputs is None:
            return None
        m, n = throughputs.shape
        sf = self.scale_factors_array(scale_factors, index[0], m, n)
        x = self._allocation_matrix(m, n, sf, num_workers)
        return (throughputs * x).sum(axis=1).reshape((m, 1))


class ProportionalPolicy(Policy):
    """Each job gets the same fraction of every worker type, normalized by
    the largest row sum (reference: scheduler/policies/proportional.py:27-55).
    Used as the normalizer inside max-min fairness."""

    name = "Proportional"

    def _allocation_matrix(self, m, num_workers):
        x = np.tile(np.asarray(num_workers, dtype=np.float64) / m, (m, 1))
        return x / x.sum(axis=1).max()

    def get_allocation(self, throughputs, cluster_spec):
        matrix, index = self.flatten(throughputs, cluster_spec)
        if matrix is None:
            return None
        m, _ = matrix.shape
        return self.unflatten(self._allocation_matrix(m, self._num_workers), index)

    def get_throughputs(self, throughputs, index, num_workers):
        if throughputs is None:
            return None
        m, _ = throughputs.shape
        x = self._allocation_matrix(m, num_workers)
        return (throughputs * x).sum(axis=1).reshape((m, 1))
