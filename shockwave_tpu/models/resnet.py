"""ResNet-18/50 image classifiers (flax), CIFAR-10 shapes.

Capability parity with the reference's image-classification workloads
(reference: workloads/pytorch/image_classification/cifar10/main.py). Convs
map directly onto the MXU; batch is sharded over "data".
"""

from __future__ import annotations

from functools import partial
from typing import Sequence

import flax.linen as nn
import jax.numpy as jnp


class BasicBlock(nn.Module):
    features: int
    strides: int = 1

    @nn.compact
    def __call__(self, x, train: bool = True):
        residual = x
        y = nn.Conv(self.features, (3, 3), (self.strides, self.strides),
                    use_bias=False)(x)
        y = nn.BatchNorm(use_running_average=not train)(y)
        y = nn.relu(y)
        y = nn.Conv(self.features, (3, 3), use_bias=False)(y)
        y = nn.BatchNorm(use_running_average=not train)(y)
        if residual.shape != y.shape:
            residual = nn.Conv(
                self.features, (1, 1), (self.strides, self.strides),
                use_bias=False,
            )(residual)
            residual = nn.BatchNorm(use_running_average=not train)(residual)
        return nn.relu(y + residual)


class Bottleneck(nn.Module):
    features: int
    strides: int = 1

    @nn.compact
    def __call__(self, x, train: bool = True):
        residual = x
        y = nn.Conv(self.features, (1, 1), use_bias=False)(x)
        y = nn.BatchNorm(use_running_average=not train)(y)
        y = nn.relu(y)
        y = nn.Conv(self.features, (3, 3), (self.strides, self.strides),
                    use_bias=False)(y)
        y = nn.BatchNorm(use_running_average=not train)(y)
        y = nn.relu(y)
        y = nn.Conv(self.features * 4, (1, 1), use_bias=False)(y)
        y = nn.BatchNorm(use_running_average=not train)(y)
        if residual.shape != y.shape:
            residual = nn.Conv(
                self.features * 4, (1, 1), (self.strides, self.strides),
                use_bias=False,
            )(residual)
            residual = nn.BatchNorm(use_running_average=not train)(residual)
        return nn.relu(y + residual)


class ResNet(nn.Module):
    stage_sizes: Sequence[int]
    block: type
    num_classes: int = 10

    @nn.compact
    def __call__(self, x, train: bool = True):
        y = nn.Conv(64, (3, 3), use_bias=False)(x)
        y = nn.BatchNorm(use_running_average=not train)(y)
        y = nn.relu(y)
        for i, size in enumerate(self.stage_sizes):
            for j in range(size):
                strides = 2 if i > 0 and j == 0 else 1
                y = self.block(64 * 2**i, strides)(y, train=train)
        y = jnp.mean(y, axis=(1, 2))
        return nn.Dense(self.num_classes)(y)


ResNet18 = partial(ResNet, stage_sizes=[2, 2, 2, 2], block=BasicBlock)
ResNet50 = partial(ResNet, stage_sizes=[3, 4, 6, 3], block=Bottleneck)
