"""The flagship workload: a decoder-only transformer LM, TPU-first.

Parallelism is declared, not hand-coded: parameters carry logical
partition annotations (tensor parallelism over the "model" axis: attention
heads and MLP hidden; vocab-sharded embeddings), activations shard batch
over "data" and sequence over "seq", and attention can run as exact ring
attention across the "seq" axis for long context
(shockwave_tpu/parallel/ring_attention.py). An optional mixture-of-experts
MLP shards experts over "model" (expert parallelism). XLA inserts all
collectives from these annotations.

The reference's transformer workload is a vanilla Multi30k NMT model
(reference: workloads/pytorch/translation/transformer/) — capability
parity is "a transformer family job the scheduler can run"; the
architecture here is what a TPU cluster would actually train.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from shockwave_tpu.parallel.ring_attention import (
    dense_causal_attention,
    ring_attention,
)


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 1024
    d_model: int = 128
    # Pick num_heads so d_model/num_heads is 128 on real chips: the
    # flash kernels are MXU-bound and a 64-wide head dim half-fills the
    # 128-wide systolic array on both attention matmuls (measured 1.5x
    # fwd / 2x bwd on a v5e at S=32k; results/long_context_tpu.json).
    num_heads: int = 4
    num_layers: int = 2
    d_ff: int = 512
    max_len: int = 512
    dtype: str = "float32"  # bfloat16 on real chips
    attention: str = "dense"  # "dense" | "ring" | "ulysses" | "flash"
    # Sliding window for the "flash" path (None = full causal): each
    # token attends its `attention_window` most recent positions, and
    # the kernel's compute + K/V DMA become O(S * window) — linear
    # long-context cost at a fixed window.
    attention_window: Optional[int] = None
    # Grouped-query attention (None = num_heads, i.e. plain MHA): K/V
    # projections emit this many heads, shared across query-head groups
    # of size num_heads // num_kv_heads. Cuts KV projection params and
    # FLOPs by the group factor; the flash kernels resolve the sharing
    # in their index maps, and ring attention ppermutes the SMALL K/V
    # tensors (ICI traffic / group). Dense repeats KV; ulysses rejects.
    num_kv_heads: Optional[int] = None
    num_experts: int = 0  # 0 = dense MLP; >0 = MoE over "model"
    # Switch-style load-balancing auxiliary loss weight for the MoE
    # router: num_experts * sum_e(fraction_dispatched_e * mean_gate_
    # prob_e), minimized (=1) at a uniform dispatch. Without it the
    # top-1 router collapses onto one expert (measured: moe4's 40-step
    # loss 14x dense, results/moe_pipeline_tpu.json v1). 0 disables.
    moe_aux_weight: float = 1e-2
    # Per-expert token capacity = ceil(capacity_factor * tokens /
    # num_experts) for the grouped dispatch path; tokens routed past an
    # expert's capacity are dropped (residual passes through), the
    # standard Switch overflow semantics.
    moe_capacity_factor: float = 1.25
    # "grouped": capacity-bucketed grouped expert matmuls (compute is
    # O(capacity_factor * tokens), the fast path). "dense": the one-hot
    # dispatch einsum, which computes EVERY expert's FFN for EVERY
    # token — O(num_experts * tokens) FLOPs, kept for A/B measurement.
    moe_dispatch: str = "grouped"
    # Position encoding: "learned" adds a (max_len, d_model) table to
    # the token embedding; "rope" rotates q/k per head instead (no
    # table — at 131k context the learned table is 134M parameters of
    # pure lookup plus their optimizer state in HBM, and rotary's
    # relative positions extrapolate; cos/sin are computed inline and
    # fuse into the projections).
    positional: str = "learned"  # "learned" | "rope"
    rope_base: float = 10000.0
    # Rematerialize each block in the backward pass (jax.checkpoint):
    # activations are recomputed instead of stored, trading ~1/3 more
    # FLOPs for O(num_layers) less HBM — the knob that moves the
    # longest trainable context on a fixed-memory chip.
    remat: bool = False
    # Checkpoint every remat_group-th block boundary instead of every
    # one: saved boundary activations shrink by the group factor (each
    # is [B, S, d_model] — 0.54 GB per boundary at 262k tokens) at the
    # cost of recomputing `remat_group` blocks per backward step. The
    # second context-length lever after remat itself.
    remat_group: int = 1


def apply_rope(x, base=10000.0):
    """Rotary position embedding over [B, S, H, D] (D even): rotate
    feature pairs (x_i, x_{i+D/2}) by pos * base^(-2i/D). Angles are
    float32 regardless of activation dtype (bf16 loses whole positions
    past ~8k context); the rotation is elementwise and fuses into the
    surrounding projections under XLA."""
    B, S, H, D = x.shape
    half = D // 2
    freqs = jnp.exp(
        -jnp.arange(0, half, dtype=jnp.float32) * (2.0 * math.log(base) / D)
    )
    angles = jnp.arange(S, dtype=jnp.float32)[:, None] * freqs[None, :]
    cos = jnp.cos(angles)[None, :, None, :]  # [1, S, 1, half]
    sin = jnp.sin(angles)[None, :, None, :]
    x1, x2 = x[..., :half].astype(jnp.float32), x[..., half:].astype(
        jnp.float32
    )
    return jnp.concatenate(
        [x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1
    ).astype(x.dtype)


def _dense(features, name, kernel_axes, dtype=None):
    """Dense with float32 params computing in ``dtype`` (mixed
    precision: bfloat16 activations on the MXU, float32 master
    weights)."""
    return nn.Dense(
        features,
        name=name,
        use_bias=False,
        dtype=dtype,
        kernel_init=nn.with_partitioning(
            nn.initializers.lecun_normal(), kernel_axes
        ),
    )


class Attention(nn.Module):
    config: TransformerConfig
    mesh: Optional[jax.sharding.Mesh] = None

    @nn.compact
    def __call__(self, x):
        cfg = self.config
        dtype = jnp.dtype(cfg.dtype)
        head_dim = cfg.d_model // cfg.num_heads
        kv_heads = (cfg.num_heads if cfg.num_kv_heads is None
                    else cfg.num_kv_heads)
        if kv_heads < 1 or cfg.num_heads % kv_heads:
            raise ValueError(
                f"num_kv_heads ({kv_heads}) must be >= 1 and divide "
                f"num_heads ({cfg.num_heads})"
            )

        def repeat_kv(k, v):
            group = cfg.num_heads // kv_heads
            return (jnp.repeat(k, group, axis=2),
                    jnp.repeat(v, group, axis=2))

        # QKV projections: heads sharded over "model" (tensor
        # parallelism). K/V emit num_kv_heads (GQA when fewer than the
        # query heads).
        def proj(name, heads):
            y = _dense(heads * head_dim, name, (None, "model"), dtype)(x)
            return y.reshape(x.shape[:-1] + (heads, head_dim))

        q = proj("query", cfg.num_heads)
        k = proj("key", kv_heads)
        v = proj("value", kv_heads)
        if cfg.positional == "rope":
            q = apply_rope(q, cfg.rope_base)
            k = apply_rope(k, cfg.rope_base)
        if kv_heads != cfg.num_heads and cfg.attention == "ulysses":
            # Ulysses reshards the head dim in its all-to-alls; GQA
            # there needs dedicated plumbing. Ring supports it natively
            # (the flash hop body reads shared KV through its index
            # maps, and the per-hop ppermute moves the small tensors).
            raise ValueError(
                "num_kv_heads != num_heads is not supported by the "
                "'ulysses' path; use 'flash', 'dense' or 'ring'"
            )
        if kv_heads != cfg.num_heads and cfg.attention == "dense":
            k, v = repeat_kv(k, v)
        if cfg.attention_window is not None and cfg.attention != "flash":
            # Only the flash kernels implement the window; training
            # quadratically while the config promises a window would be
            # a silent semantics change.
            raise ValueError(
                "attention_window is only supported by attention='flash'"
                f", got {cfg.attention!r}"
            )
        if cfg.attention == "ring":
            if self.mesh is None:
                raise ValueError("ring attention requires a mesh")
            out = ring_attention(q, k, v, self.mesh)
        elif cfg.attention == "ulysses":
            # All-to-all sequence parallelism: two collectives per call
            # instead of the ring's P-1 hops; needs heads divisible by
            # the seq axis (shockwave_tpu/parallel/ulysses.py).
            if self.mesh is None:
                raise ValueError("ulysses attention requires a mesh")
            from shockwave_tpu.parallel.ulysses import ulysses_attention

            # Each device holds the full gathered sequence after the
            # all-to-all; ulysses_attention downgrades to a dense local
            # kernel when that sequence doesn't tile into flash blocks.
            out = ulysses_attention(
                q, k, v, self.mesh, local_attention="flash"
            )
        elif cfg.attention == "flash":
            # Single-chip long-context path: the Pallas blockwise kernel
            # (shockwave_tpu/ops/flash_attention.py). Falls back to dense
            # when the sequence doesn't tile into kernel blocks.
            from shockwave_tpu.ops.flash_attention import (
                flash_attention,
                flash_tiles,
            )

            # TPU tiling needs full kernel blocks; anything shorter or
            # non-aligned takes the dense path.
            if flash_tiles(x.shape[1]):
                out = flash_attention(q, k, v,
                                      window=cfg.attention_window)
            else:
                if cfg.attention_window is not None:
                    raise ValueError(
                        "attention_window needs a flash-tiling sequence "
                        f"(multiple of 128), got {x.shape[1]}"
                    )
                if kv_heads != cfg.num_heads:
                    k, v = repeat_kv(k, v)
                out = dense_causal_attention(q, k, v)
        else:
            out = dense_causal_attention(q, k, v)
        out = out.reshape(x.shape)
        return _dense(cfg.d_model, "out", ("model", None), dtype)(out)


class MoEMlp(nn.Module):
    """Token-choice top-1 MoE; experts sharded over "model" (expert
    parallelism).

    Dispatch is capacity-bucketed grouped expert matmuls by default:
    each token is scattered into its expert's static-capacity bucket
    (position-in-expert from a running per-expert count — the scatter is
    the sort-by-expert, with static shapes), every expert runs ONE
    [capacity, d_model] x [d_model, d_ff] matmul, and outputs gather
    back to token order. Compute is O(capacity_factor * tokens) instead
    of the dense one-hot einsum's O(num_experts * tokens); the dense
    path is kept under ``moe_dispatch="dense"`` for A/B measurement.

    The router carries the Switch-style load-balancing auxiliary loss
    (fraction-dispatched x mean-gate-prob per expert, scaled by E),
    sown into the "losses" collection; ``lm_loss`` adds it with weight
    ``moe_aux_weight``.
    """

    config: TransformerConfig
    mesh: Optional[jax.sharding.Mesh] = None

    @nn.compact
    def __call__(self, x):
        cfg = self.config
        E = cfg.num_experts
        if cfg.moe_dispatch not in ("grouped", "dense"):
            raise ValueError(
                f"moe_dispatch must be 'grouped' or 'dense', got "
                f"{cfg.moe_dispatch!r}"
            )
        if cfg.moe_capacity_factor <= 0:
            raise ValueError(
                f"moe_capacity_factor must be > 0, got "
                f"{cfg.moe_capacity_factor}"
            )
        gates = nn.Dense(E, name="router", use_bias=False)(x)
        # Routing decisions in float32 regardless of activation dtype.
        weights = jax.nn.softmax(gates.astype(jnp.float32), axis=-1)
        top = jnp.argmax(weights, axis=-1)  # [B, S]
        one_hot = jax.nn.one_hot(top, E, dtype=jnp.float32)  # [B, S, E]
        gate_scale = jnp.sum(
            weights * one_hot, axis=-1, keepdims=True
        )

        # Load-balancing auxiliary loss (Switch Transformers eq. 4):
        # E * sum_e f_e * P_e with f_e the fraction of tokens dispatched
        # to expert e and P_e its mean router probability. 1.0 at
        # uniform; differentiable through P_e.
        if not self.is_initializing():
            frac = jnp.mean(one_hot, axis=(0, 1))
            prob = jnp.mean(weights, axis=(0, 1))
            self.sow("losses", "moe_aux", E * jnp.sum(frac * prob))

        w_in = self.param(
            "w_in",
            nn.with_partitioning(
                nn.initializers.lecun_normal(), ("model", None, None)
            ),
            (E, cfg.d_model, cfg.d_ff),
        )
        w_out = self.param(
            "w_out",
            nn.with_partitioning(
                nn.initializers.lecun_normal(), ("model", None, None)
            ),
            (E, cfg.d_ff, cfg.d_model),
        )
        # Expert weights cast to the activation dtype so the matmuls
        # stay on the MXU's bfloat16 path under mixed precision.
        w_in = jnp.asarray(w_in).astype(x.dtype)
        w_out = jnp.asarray(w_out).astype(x.dtype)

        if cfg.moe_dispatch == "dense":
            dispatch = one_hot.astype(x.dtype)
            hidden = jnp.einsum("bse,bsd,edf->bsf", dispatch, x, w_in)
            hidden = nn.gelu(hidden)
            out = jnp.einsum("bse,bsf,efd->bsd", dispatch, hidden, w_out)
            return out * gate_scale.astype(x.dtype)

        B, S, d = x.shape
        N = B * S
        # Static per-expert capacity, padded to a multiple of 8 so the
        # bucket tensor tiles cleanly on TPU.
        C = int(math.ceil(cfg.moe_capacity_factor * N / E))
        C = min(-(-C // 8) * 8, N) if N >= 8 else N
        xf = x.reshape(N, d)
        top_f = top.reshape(N)
        oh = one_hot.reshape(N, E).astype(jnp.int32)
        # Position-in-expert: running count of earlier tokens routed to
        # the same expert (the static-shape equivalent of sorting tokens
        # by expert id). Tokens at positions >= capacity overflow and
        # are dropped — their slot index lands out of bounds, the
        # scatter/gather modes below turn that into zero contribution.
        pos = jnp.sum(jnp.cumsum(oh, axis=0) * oh, axis=-1) - 1  # [N]
        slot = jnp.where(pos < C, top_f * C + pos, E * C)
        buckets = (
            jnp.zeros((E * C, d), x.dtype)
            .at[slot]
            .set(xf, mode="drop")
            .reshape(E, C, d)
        )
        if self.mesh is not None and "model" in self.mesh.axis_names:
            buckets = jax.lax.with_sharding_constraint(
                buckets,
                jax.sharding.NamedSharding(
                    self.mesh,
                    jax.sharding.PartitionSpec("model", None, None),
                ),
            )
        hidden = nn.gelu(jnp.einsum("ecd,edf->ecf", buckets, w_in))
        out = jnp.einsum("ecf,efd->ecd", hidden, w_out).reshape(E * C, d)
        y = jnp.take(out, slot, axis=0, mode="fill", fill_value=0)
        return y.reshape(B, S, d) * gate_scale.astype(x.dtype)


class Mlp(nn.Module):
    config: TransformerConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.config
        dtype = jnp.dtype(cfg.dtype)
        h = _dense(cfg.d_ff, "in", (None, "model"), dtype)(x)
        h = nn.gelu(h)
        return _dense(cfg.d_model, "out", ("model", None), dtype)(h)


class Block(nn.Module):
    config: TransformerConfig
    mesh: Optional[jax.sharding.Mesh] = None

    @nn.compact
    def __call__(self, x):
        cfg = self.config
        # LayerNorm statistics in float32; the next matmul casts back
        # down to the activation dtype.
        y = nn.LayerNorm(name="ln1", dtype=jnp.float32)(x)
        x = x + Attention(cfg, self.mesh, name="attention")(y)
        y = nn.LayerNorm(name="ln2", dtype=jnp.float32)(x)
        mlp = (
            MoEMlp(cfg, self.mesh, name="moe")
            if cfg.num_experts > 0
            else Mlp(cfg, name="mlp")
        )
        return x + mlp(y)


class BlockGroup(nn.Module):
    """remat_group consecutive Blocks as one checkpoint cell: only the
    group's input is saved for the backward; everything inside is
    recomputed."""

    config: TransformerConfig
    mesh: Optional[jax.sharding.Mesh] = None

    @nn.compact
    def __call__(self, x):
        for i in range(self.config.remat_group):
            x = Block(self.config, self.mesh, name=f"block_{i}")(x)
        return x


class TransformerLM(nn.Module):
    config: TransformerConfig
    mesh: Optional[jax.sharding.Mesh] = None

    @nn.compact
    def __call__(self, tokens, targets=None, logit_chunk=None):
        """Logits [B, S, V] for ``tokens`` [B, S]; or, when ``targets``
        is given, the scalar next-token cross entropy with the head
        evaluated in sequence chunks of ``logit_chunk`` tokens. The
        chunked path never materializes the full [B, S, V] logits —
        at 262k tokens x 8k vocab those are 8.6 GB in f32, more than
        half the chip, and the thing that caps trainable context once
        attention is windowed; each chunk's logits are recomputed in
        the backward (jax.checkpoint), so the live footprint is
        O(logit_chunk * V) in both passes."""
        cfg = self.config
        emb = self.param(
            "embedding",
            nn.with_partitioning(
                nn.initializers.normal(0.02), ("model", None)
            ),
            (cfg.vocab_size, cfg.d_model),
        )
        dtype = jnp.dtype(cfg.dtype)
        if cfg.positional == "rope":
            # Positions live in the attention rotations (apply_rope);
            # no table, no per-context parameter growth.
            x = jnp.asarray(emb)[tokens].astype(dtype)
        elif cfg.positional == "learned":
            pos = self.param(
                "positional",
                nn.with_partitioning(
                    nn.initializers.normal(0.02), (None, None)
                ),
                (cfg.max_len, cfg.d_model),
            )
            x = (
                jnp.asarray(emb)[tokens]
                + jnp.asarray(pos)[: tokens.shape[1]]
            ).astype(dtype)
        else:
            raise ValueError(
                f"positional must be 'learned' or 'rope', got "
                f"{cfg.positional!r}"
            )
        if cfg.remat_group < 1:
            raise ValueError(
                f"remat_group must be >= 1, got {cfg.remat_group}"
            )
        if cfg.remat_group > 1 and not cfg.remat:
            # Grouped checkpointing without remat would silently run a
            # plain model while the config promises grouping (same
            # convention as attention_window on non-flash paths).
            raise ValueError("remat_group > 1 requires remat=True")
        if cfg.remat and cfg.remat_group > 1:
            if cfg.num_layers % cfg.remat_group:
                raise ValueError(
                    f"remat_group ({cfg.remat_group}) must divide "
                    f"num_layers ({cfg.num_layers})"
                )
            group_cls = nn.remat(BlockGroup)
            for i in range(cfg.num_layers // cfg.remat_group):
                x = group_cls(cfg, self.mesh, name=f"group_{i}")(x)
        else:
            block_cls = nn.remat(Block) if cfg.remat else Block
            for i in range(cfg.num_layers):
                x = block_cls(cfg, self.mesh, name=f"block_{i}")(x)
        x = nn.LayerNorm(name="ln_f", dtype=jnp.float32)(x)
        # Tied output head: vocab matmul in the activation dtype, logits
        # accumulated in float32 for the softmax loss.
        head = jnp.asarray(emb).astype(dtype)

        def logits_of(xc):
            return jnp.einsum(
                "bsd,vd->bsv", xc.astype(dtype), head,
                preferred_element_type=jnp.float32,
            )

        if targets is None:
            return logits_of(x)

        B, S = targets.shape
        chunk = S if logit_chunk is None else int(logit_chunk)
        if chunk < 1 or S % chunk:
            raise ValueError(
                f"logit_chunk ({chunk}) must be >= 1 and divide the "
                f"sequence ({S})"
            )
        # [B, S, ...] -> [n_chunks, B, chunk, ...] for the scan.
        d = x.shape[-1]
        xc = x.reshape(B, S // chunk, chunk, d).swapaxes(0, 1)
        tc = targets.reshape(B, S // chunk, chunk).swapaxes(0, 1)

        from shockwave_tpu.models.small_models import token_xent_sum

        @jax.checkpoint
        def body(total, xt):
            xcb, tcb = xt
            # token_xent's sum form; the mean is taken once over all
            # chunks below.
            return total + token_xent_sum(logits_of(xcb), tcb), None

        total, _ = jax.lax.scan(body, jnp.float32(0.0), (xc, tc))
        return total / (B * S)


def moe_aux_loss(mutated_vars) -> jnp.ndarray:
    """Mean of the per-layer router balance losses sown into the
    "losses" collection (one scalar per MoE layer)."""
    sown = jax.tree_util.tree_leaves(mutated_vars.get("losses", {}))
    if not sown:
        return jnp.float32(0.0)
    return sum(sown) / len(sown)


def lm_loss(model, params, tokens, logit_chunk=None):
    """Next-token cross entropy over a [B, S+1] token batch, plus the
    router load-balancing auxiliary loss (weight
    ``config.moe_aux_weight``) when the model is MoE. With
    ``logit_chunk`` the head+loss run sequence-chunked (see
    TransformerLM.__call__) so full logits never materialize."""
    cfg = model.config
    with_aux = cfg.num_experts > 0 and cfg.moe_aux_weight > 0.0
    if logit_chunk is not None:
        if with_aux:
            loss, mutated = model.apply(
                params, tokens[:, :-1], tokens[:, 1:], logit_chunk,
                mutable=["losses"],
            )
            return loss + cfg.moe_aux_weight * moe_aux_loss(mutated)
        return model.apply(
            params, tokens[:, :-1], tokens[:, 1:], logit_chunk
        )
    from shockwave_tpu.models.small_models import token_xent

    if with_aux:
        logits, mutated = model.apply(
            params, tokens[:, :-1], mutable=["losses"]
        )
        return token_xent(logits, tokens[:, 1:]) + (
            cfg.moe_aux_weight * moe_aux_loss(mutated)
        )
    logits = model.apply(params, tokens[:, :-1])
    return token_xent(logits, tokens[:, 1:])
