"""The remaining reference workload families, TPU-native and compact:

  LM             — LSTM language model (reference: workloads/pytorch/
                   language_modeling/main.py; wikitext-2 scale)
  Recommendation — neural collaborative filtering MLP (reference:
                   workloads/pytorch/recommendation/)
  A3C            — actor-critic policy/value net (reference:
                   workloads/pytorch/rl/)
  CycleGAN       — resnet generator + patch discriminator (reference:
                   workloads/pytorch/cyclegan/)

Recurrence runs under nn.scan (compiler-friendly lax.scan, static
shapes); losses are defined next to the models so the unified trainer
(shockwave_tpu/models/train.py) treats every family identically.
"""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp


class LSTMLanguageModel(nn.Module):
    vocab_size: int = 10000
    d_embed: int = 128
    d_hidden: int = 256

    @nn.compact
    def __call__(self, tokens):
        x = nn.Embed(self.vocab_size, self.d_embed)(tokens)
        lstm = nn.RNN(nn.OptimizedLSTMCell(self.d_hidden), name="lstm")
        y = lstm(x)
        return nn.Dense(self.vocab_size)(y)


class NeuMF(nn.Module):
    """Neural collaborative filtering (GMF + MLP fusion)."""

    num_users: int = 2048
    num_items: int = 2048
    d_factor: int = 32

    @nn.compact
    def __call__(self, user_item):
        users, items = user_item[:, 0], user_item[:, 1]
        gmf_u = nn.Embed(self.num_users, self.d_factor, name="gmf_user")(users)
        gmf_i = nn.Embed(self.num_items, self.d_factor, name="gmf_item")(items)
        mlp_u = nn.Embed(self.num_users, self.d_factor, name="mlp_user")(users)
        mlp_i = nn.Embed(self.num_items, self.d_factor, name="mlp_item")(items)
        mlp = jnp.concatenate([mlp_u, mlp_i], axis=-1)
        for width in (64, 32, 16):
            mlp = nn.relu(nn.Dense(width)(mlp))
        fused = jnp.concatenate([gmf_u * gmf_i, mlp], axis=-1)
        return nn.Dense(1)(fused)[:, 0]


class ActorCritic(nn.Module):
    """A3C network over image observations."""

    num_actions: int = 6

    @nn.compact
    def __call__(self, obs):
        y = nn.relu(nn.Conv(16, (8, 8), (4, 4))(obs))
        y = nn.relu(nn.Conv(32, (4, 4), (2, 2))(y))
        y = y.reshape((y.shape[0], -1))
        y = nn.relu(nn.Dense(256)(y))
        return nn.Dense(self.num_actions)(y), nn.Dense(1)(y)[:, 0]


class CycleGANGenerator(nn.Module):
    features: int = 32
    num_res_blocks: int = 3

    @nn.compact
    def __call__(self, x):
        y = nn.relu(nn.Conv(self.features, (7, 7))(x))
        y = nn.relu(nn.Conv(self.features * 2, (3, 3), (2, 2))(y))
        for _ in range(self.num_res_blocks):
            r = nn.relu(nn.Conv(self.features * 2, (3, 3))(y))
            r = nn.Conv(self.features * 2, (3, 3))(r)
            y = y + r
        y = nn.relu(nn.ConvTranspose(self.features, (3, 3), (2, 2))(y))
        return nn.tanh(nn.Conv(x.shape[-1], (7, 7))(y))


class CycleGANDiscriminator(nn.Module):
    features: int = 32

    @nn.compact
    def __call__(self, x):
        y = nn.leaky_relu(nn.Conv(self.features, (4, 4), (2, 2))(x))
        y = nn.leaky_relu(nn.Conv(self.features * 2, (4, 4), (2, 2))(y))
        return nn.Conv(1, (4, 4))(y)


# -- losses -------------------------------------------------------------
def token_xent_sum(logits, targets):
    """Sum (not mean) form of :func:`token_xent` over a logits block —
    shared by the full-logits loss and TransformerLM's sequence-chunked
    head (which averages once over all chunks). Same CONTRACT: every
    target must lie in [0, vocab)."""
    lse = jax.scipy.special.logsumexp(logits, axis=-1)
    idx = jax.lax.broadcasted_iota(
        jnp.int32, logits.shape, logits.ndim - 1
    )
    picked = jnp.sum(
        jnp.where(idx == targets[..., None], logits, 0.0), axis=-1
    )
    return jnp.sum(lse - picked)


def token_xent(logits, targets):
    """Next-token cross entropy as logsumexp minus a select-reduce pick.

    take_along_axis over the [tokens, vocab] logits compiles to a
    gather whose backward is a scatter — measured 58 ms fwd+bwd on a
    v5e at [16384, 8192] f32 vs 4.3 ms for this formulation (iota
    compare + select + reduce fuses into the logsumexp passes; exact
    to float tolerance).

    CONTRACT: every target must lie in [0, vocab). Unlike
    take_along_axis (which clamps), an out-of-range target here selects
    nothing — the loss silently degrades to mean(lse) for that token.
    There is no -100-style ignore index; mask padding tokens out of the
    mean yourself before calling."""
    return token_xent_sum(logits, targets) / targets.size


def a3c_loss(policy_logits, values, actions, returns):
    """Policy-gradient surrogate + value loss + entropy bonus."""
    advantages = returns - values
    logp = jax.nn.log_softmax(policy_logits, axis=-1)
    chosen = jnp.take_along_axis(logp, actions[:, None], axis=-1)[:, 0]
    policy_loss = -jnp.mean(chosen * jax.lax.stop_gradient(advantages))
    value_loss = jnp.mean(advantages**2)
    entropy = -jnp.mean(jnp.sum(jnp.exp(logp) * logp, axis=-1))
    return policy_loss + 0.5 * value_loss - 0.01 * entropy


def lsgan_loss(real_scores, fake_scores):
    return jnp.mean((real_scores - 1.0) ** 2) + jnp.mean(fake_scores**2)
