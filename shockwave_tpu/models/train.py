"""Unified training program for every workload family.

This is the process the dispatcher launches:

  python -m shockwave_tpu.models.train --model ResNet-18 --batch_size 32 \
      -n <steps> --checkpoint_dir <dir> --enable_shockwave_iterator

One code path serves all seven families (reference ships a separate
PyTorch/TF program per family under workloads/). Synthetic data by
default — the scheduler's concern is steps/second, not accuracy — with
static shapes so each family compiles exactly once. Gang jobs receive
``--distributed_addr/--num_workers/--worker_rank`` from the scheduler and
initialize jax.distributed; the mesh factorizes the gang into
(data, model, seq) per the transformer flags.

Checkpoint/restore: full train state via flax.serialization, written on
preemption (lease expiry) and completion.
"""

from __future__ import annotations

import argparse
import os
import sys
import time

import numpy as np


def build_family(name, args, mesh, abstract=False):
    """Returns (params, step_fn(params, opt_state, batch), opt_state,
    batch_fn(rng) -> batch).

    With ``abstract=True`` the variables/opt_state come back as
    jax.ShapeDtypeStruct trees (no device compute): a resuming attempt
    only needs the tree as a restore template, and skipping the real
    init saves its whole compile (~11 s for ResNet-18 on the tunneled
    TPU, where compiled executables cannot persist across processes)."""
    import jax
    import jax.numpy as jnp
    import optax

    from shockwave_tpu.models import small_models as sm
    from shockwave_tpu.ops.fused_adamw import FusedAdamW
    from shockwave_tpu.models.resnet import ResNet18, ResNet50
    from shockwave_tpu.models.transformer import (
        TransformerConfig,
        TransformerLM,
        lm_loss,
    )

    rng = jax.random.PRNGKey(args.seed)
    bs = args.batch_size

    def jit_init(init_fn, *init_args):
        """Run a flax ``init`` under jit: one compiled program instead of
        one eager dispatch per parameter tensor. On a remote-tunneled
        accelerator (the axon TPU) the eager path pays a compile
        round-trip per op — measured 102 s for ResNet-18 against 12 s
        jitted; on local CPU/TPU it is merely tidier."""
        if abstract:
            return jax.eval_shape(init_fn, *init_args)
        return jax.jit(init_fn)(*init_args)
    # Fused single-pass AdamW (shockwave_tpu/ops/fused_adamw.py): same
    # math as optax.adamw, one parameter traversal per step instead of
    # updates-tree + apply; paired in-process A/B at the 110M tier says
    # full-step parity (see the module docstring's measurement story).
    tx = FusedAdamW(args.learning_rate)

    if name in ("ResNet-18", "ResNet-50"):
        model = (ResNet18 if name == "ResNet-18" else ResNet50)()
        example = jnp.zeros((bs, 32, 32, 3), jnp.float32)
        variables = jit_init(
            lambda r: model.init(r, example, train=True), rng
        )

        def loss_fn(variables, batch):
            images, labels = batch
            logits, updates = model.apply(
                variables, images, train=True, mutable=["batch_stats"]
            )
            loss = sm.token_xent(logits, labels)
            return loss, updates

        def batch_fn(np_rng):
            return (
                jnp.asarray(np_rng.normal(size=(bs, 32, 32, 3)), jnp.float32),
                jnp.asarray(np_rng.integers(0, 10, bs)),
            )

        def step_fn(variables, opt_state, batch):
            (loss, updates), grads = jax.value_and_grad(
                loss_fn, has_aux=True
            )(variables, batch)
            params, opt_state = tx.apply_gradients(
                grads["params"], opt_state, variables["params"]
            )
            variables = {
                "params": params,
                "batch_stats": updates["batch_stats"],
            }
            return variables, opt_state, loss

        opt_state = jit_init(tx.init, variables["params"])
        return variables, step_fn, opt_state, batch_fn

    if name == "Transformer":
        cfg = TransformerConfig(
            vocab_size=args.vocab_size,
            d_model=args.d_model,
            num_heads=args.num_heads,
            num_layers=args.num_layers,
            d_ff=4 * args.d_model,
            max_len=args.seq_len,
            dtype=getattr(args, "dtype", "float32"),
            attention=args.attention,
            attention_window=getattr(args, "attention_window", None),
            num_kv_heads=getattr(args, "num_kv_heads", None),
            positional=getattr(args, "positional", "learned"),
            num_experts=args.num_experts,
            moe_aux_weight=getattr(args, "moe_aux_weight", 1e-2),
            moe_capacity_factor=getattr(args, "moe_capacity_factor", 1.25),
            moe_dispatch=getattr(args, "moe_dispatch", "grouped"),
            remat=getattr(args, "remat", False),
        )
        model = TransformerLM(cfg, mesh=mesh)
        example = jnp.zeros((bs, args.seq_len), jnp.int32)
        variables = jit_init(model.init, rng, example)

        def loss_fn(variables, batch):
            return lm_loss(
                model, variables, batch,
                logit_chunk=getattr(args, "logit_chunk", None),
            )

        def batch_fn(np_rng):
            return jnp.asarray(
                np_rng.integers(0, cfg.vocab_size, (bs, args.seq_len + 1))
            )

    elif name == "LM":
        model = sm.LSTMLanguageModel()
        example = jnp.zeros((bs, args.seq_len), jnp.int32)
        variables = jit_init(model.init, rng, example)

        def loss_fn(variables, batch):
            logits = model.apply(variables, batch[:, :-1])
            return sm.token_xent(logits, batch[:, 1:])

        def batch_fn(np_rng):
            return jnp.asarray(
                np_rng.integers(0, 10000, (bs, args.seq_len + 1))
            )

    elif name == "Recommendation":
        model = sm.NeuMF()
        example = jnp.zeros((bs, 2), jnp.int32)
        variables = jit_init(model.init, rng, example)

        def loss_fn(variables, batch):
            pairs, labels = batch
            scores = model.apply(variables, pairs)
            return jnp.mean(optax.sigmoid_binary_cross_entropy(scores, labels))

        def batch_fn(np_rng):
            return (
                jnp.asarray(np_rng.integers(0, 2048, (bs, 2))),
                jnp.asarray(np_rng.integers(0, 2, bs), jnp.float32),
            )

    elif name == "A3C":
        model = sm.ActorCritic()
        example = jnp.zeros((bs, 84, 84, 4), jnp.float32)
        variables = jit_init(model.init, rng, example)

        def loss_fn(variables, batch):
            obs, actions, returns = batch
            logits, values = model.apply(variables, obs)
            return sm.a3c_loss(logits, values, actions, returns)

        def batch_fn(np_rng):
            return (
                jnp.asarray(np_rng.normal(size=(bs, 84, 84, 4)), jnp.float32),
                jnp.asarray(np_rng.integers(0, 6, bs)),
                jnp.asarray(np_rng.normal(size=bs), jnp.float32),
            )

    elif name == "CycleGAN":
        gen = sm.CycleGANGenerator()
        disc = sm.CycleGANDiscriminator()
        rng_g, rng_d = jax.random.split(rng)
        example = jnp.zeros((bs, 64, 64, 3), jnp.float32)
        variables = jit_init(
            lambda rg, rd: {
                "gen": gen.init(rg, example),
                "disc": disc.init(rd, example),
            },
            rng_g,
            rng_d,
        )

        def loss_fn(variables, batch):
            real_a, real_b = batch
            fake_b = gen.apply(variables["gen"], real_a)
            # Generator: fool the discriminator + cycle-style identity.
            fake_scores = disc.apply(variables["disc"], fake_b)
            gen_loss = jnp.mean((fake_scores - 1.0) ** 2) + jnp.mean(
                jnp.abs(fake_b - real_b)
            )
            # Discriminator: reject fakes (gradient must NOT flow back
            # into the generator, so stop on the IMAGE, not the score).
            real_scores = disc.apply(variables["disc"], real_b)
            fake_scores_d = disc.apply(
                variables["disc"], jax.lax.stop_gradient(fake_b)
            )
            disc_loss = sm.lsgan_loss(real_scores, fake_scores_d)
            return gen_loss + disc_loss

        def batch_fn(np_rng):
            return (
                jnp.asarray(np_rng.normal(size=(bs, 64, 64, 3)), jnp.float32),
                jnp.asarray(np_rng.normal(size=(bs, 64, 64, 3)), jnp.float32),
            )

    else:
        raise ValueError(f"Unknown model family {name!r}")

    def step_fn(variables, opt_state, batch):
        loss, grads = jax.value_and_grad(loss_fn)(variables, batch)
        variables, opt_state = tx.apply_gradients(
            grads, opt_state, variables
        )
        return variables, opt_state, loss

    opt_state = jit_init(tx.init, variables)
    return variables, step_fn, opt_state, batch_fn


def main(argv=None):
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--model", type=str, required=True)
    parser.add_argument("--batch_size", type=int, default=32)
    parser.add_argument("-n", "--num_steps", type=int, required=True)
    parser.add_argument("--checkpoint_dir", type=str, default=None)
    parser.add_argument("--ckpt_backend", type=str, default="msgpack",
                        choices=["msgpack", "orbax"],
                        help="checkpoint format: one msgpack file, or an "
                        "orbax directory (sharded/async-capable)")
    parser.add_argument("--enable_shockwave_iterator", action="store_true")
    parser.add_argument("--learning_rate", type=float, default=1e-3)
    parser.add_argument("--seed", type=int, default=0)
    # Transformer knobs.
    parser.add_argument("--vocab_size", type=int, default=1024)
    parser.add_argument("--d_model", type=int, default=128)
    parser.add_argument("--num_heads", type=int, default=4)
    parser.add_argument("--num_layers", type=int, default=2)
    parser.add_argument("--seq_len", type=int, default=128)
    parser.add_argument("--attention", type=str, default="dense",
                        choices=["dense", "ring", "ulysses", "flash"])
    parser.add_argument("--attention_window", type=int, default=None,
                        help="sliding window (flash): each token attends"
                             " its most recent N positions; O(S*N) cost")
    parser.add_argument("--num_kv_heads", type=int, default=None,
                        help="grouped-query attention KV head count "
                             "(flash/dense/ring; ulysses rejects it)")
    parser.add_argument("--logit_chunk", type=int, default=None,
                        help="sequence-chunk the LM head+loss (full "
                             "[S, vocab] logits never materialize)")
    parser.add_argument("--positional", type=str, default="learned",
                        choices=["learned", "rope"],
                        help="position encoding: learned table or "
                             "rotary (no table; the table is 134M "
                             "params at 131k context)")
    parser.add_argument("--dtype", type=str, default="float32",
                        choices=["float32", "bfloat16"],
                        help="activation dtype (params stay float32)")
    parser.add_argument("--remat", action="store_true",
                        help="rematerialize transformer blocks in the "
                        "backward pass (less HBM, ~1/3 more FLOPs)")
    parser.add_argument("--num_experts", type=int, default=0)
    parser.add_argument("--moe_aux_weight", type=float, default=1e-2,
                        help="router load-balancing auxiliary loss "
                        "weight (Switch-style; 0 disables)")
    parser.add_argument("--moe_capacity_factor", type=float, default=1.25,
                        help="per-expert token capacity factor for the "
                        "grouped dispatch path")
    parser.add_argument("--moe_dispatch", type=str, default="grouped",
                        choices=["grouped", "dense"],
                        help="grouped: capacity-bucketed expert "
                        "matmuls; dense: one-hot dispatch einsum "
                        "(every expert computed for every token)")
    parser.add_argument("--model_parallel", type=int, default=1)
    parser.add_argument("--seq_parallel", type=int, default=1)
    # Gang rendezvous (appended by the scheduler).
    parser.add_argument("--distributed_addr", type=str, default=None)
    parser.add_argument("--num_workers", type=int, default=1)
    parser.add_argument("--worker_rank", type=int, default=0)
    parser.add_argument(
        "--distributed_timeout", type=float, default=None,
        help="Gang rendezvous timeout in seconds: fail fast (nonzero "
        "exit -> a zero-progress Done report -> the scheduler's "
        "micro-task failure/retry path) instead of blocking on the "
        "coordinator when a peer host never arrives",
    )
    args = parser.parse_args(argv)

    # Opt-in phase breakdown (SHOCKWAVE_PHASE_TIMINGS=1): one PHASES
    # line on stdout splitting the attempt's wall clock into
    # rendezvous/build/restore/first_step_compile/train/save. The
    # physical drivers use it to
    # report per-preemption overhead (process relaunches dominate the
    # round budget on remote-tunneled chips, where executables cannot
    # be cached across processes).
    phase_timings = {}
    phase_start = time.time()

    def mark_phase(name):
        nonlocal phase_start
        if os.environ.get("SHOCKWAVE_PHASE_TIMINGS"):
            now = time.time()
            phase_timings[name] = (
                phase_timings.get(name, 0.0) + now - phase_start
            )
            phase_start = now

    import jax

    # Honor an explicit platform request reliably: on hosts with a
    # plugin backend (axon TPU) the JAX_PLATFORMS env var alone can be
    # overridden during init; pinning jax.config is the robust form
    # (same recipe as tests/conftest.py).
    if os.environ.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

    if args.distributed_addr and args.num_workers > 1:
        import math

        init_kwargs = {}
        if args.distributed_timeout is not None:
            # Round UP to whole seconds: int() would turn a sub-second
            # request into timeout=0 (fail-on-arrival).
            init_kwargs["initialization_timeout"] = max(
                1, math.ceil(args.distributed_timeout)
            )
        jax.distributed.initialize(
            coordinator_address=args.distributed_addr,
            num_processes=args.num_workers,
            process_id=args.worker_rank,
            **init_kwargs,
        )
        mark_phase("rendezvous")

    from shockwave_tpu.parallel.mesh import factorize_gang, make_mesh

    shape = factorize_gang(
        len(jax.devices()), args.seq_parallel, args.model_parallel
    )
    mesh = make_mesh(shape)

    # Resolve the resume source before building the family: a resuming
    # attempt builds only the abstract state template (see build_family)
    # and fills it from the checkpoint, skipping the init compile.
    if getattr(args, "ckpt_backend", "msgpack") == "orbax":
        resume_from = (
            os.path.join(os.path.abspath(args.checkpoint_dir), "orbax_state")
            if args.checkpoint_dir
            else None
        )
    else:
        resume_from = (
            os.path.join(args.checkpoint_dir, "train_state.msgpack")
            if args.checkpoint_dir
            else None
        )
    resuming = bool(resume_from and os.path.exists(resume_from))

    variables, step_fn, opt_state, batch_fn = build_family(
        args.model, args, mesh, abstract=resuming
    )
    if resuming:
        # Host-side zero template with the right tree/shapes/dtypes:
        # flax.serialization and orbax both restore into it leaf by
        # leaf, and the first jit_step call uploads the restored state.
        variables, opt_state = jax.tree.map(
            lambda s: np.zeros(s.shape, s.dtype), (variables, opt_state)
        )
    jax.block_until_ready((variables, opt_state))
    mark_phase("build")

    def restore_legacy_optax_state(restore_fn):
        """Migrate a checkpoint written when the optimizer was
        optax.adamw: restore against the optax state template, then
        repack (count, mu, nu) into FusedAdamWState. Jobs preempted
        before the fused-AdamW switch resume losslessly instead of
        failing every retry on a template mismatch.

        The legacy template is built over the fused state's own moment
        tree — NOT over ``variables`` — because families differ in what
        they hand the optimizer (ResNet inits it over
        variables["params"] only); opt_state.m always has exactly that
        structure."""
        import optax

        from shockwave_tpu.ops.fused_adamw import FusedAdamWState

        legacy_template = optax.adamw(args.learning_rate).init(opt_state.m)
        restored_vars, legacy = restore_fn(legacy_template)
        adam = legacy[0]  # ScaleByAdamState(count, mu, nu)
        return restored_vars, FusedAdamWState(
            count=adam.count, m=adam.mu, v=adam.nu
        )

    # Restore from a previous round's checkpoint. Two backends:
    # msgpack (flax.serialization, one file, host-memory bound) and
    # orbax (directory tree, sharded/async-capable — the idiomatic TPU
    # checkpointer once states outgrow one host buffer).
    restored = False
    if getattr(args, "ckpt_backend", "msgpack") == "orbax":
        import orbax.checkpoint as ocp

        orbax_dir = resume_from
        checkpointer = ocp.StandardCheckpointer()
        if resuming:
            try:
                restored = checkpointer.restore(
                    orbax_dir, {"variables": variables, "opt": opt_state}
                )
                variables, opt_state = restored["variables"], restored["opt"]
            except Exception as template_err:

                def _restore(template):
                    r = checkpointer.restore(
                        orbax_dir, {"variables": variables, "opt": template}
                    )
                    return r["variables"], r["opt"]

                try:
                    variables, opt_state = restore_legacy_optax_state(
                        _restore
                    )
                except Exception:
                    # Not a legacy-format checkpoint either (e.g. a
                    # truncated save): surface the ORIGINAL error, not
                    # a bogus template-mismatch from the fallback.
                    raise template_err
            restored = True

        def save_checkpoint():
            if not orbax_dir:
                return
            checkpointer.save(
                orbax_dir,
                {"variables": variables, "opt": opt_state},
                force=True,
            )
            checkpointer.wait_until_finished()

    else:
        from flax import serialization

        ckpt_path = resume_from
        if resuming:
            try:
                with open(ckpt_path, "rb") as f:
                    blob = f.read()
            except FileNotFoundError:
                # Same race the post-restore check below guards: the
                # scheduler's cleanup (or a competing attempt) removed
                # the checkpoint between resume detection and restore.
                # Route it into the identical loud RuntimeError so both
                # backends report the race the same way.
                raise RuntimeError(
                    f"checkpoint at {resume_from} disappeared between "
                    "resume detection and restore"
                ) from None
            try:
                variables, opt_state = serialization.from_bytes(
                    (variables, opt_state), blob
                )
            except ValueError as template_err:

                def _restore(template):
                    return serialization.from_bytes(
                        (variables, template), blob
                    )

                try:
                    variables, opt_state = restore_legacy_optax_state(
                        _restore
                    )
                except Exception:
                    raise template_err
            restored = True

        def save_checkpoint():
            if not ckpt_path:
                return
            # Fetch the whole state in one batched transfer before
            # serializing: to_bytes pulls leaves one np.asarray at a
            # time, and on a remote-tunneled device that is
            # latency-bound (measured 24 s vs 5-8 s batched for the
            # 134 MB ResNet-18 state).
            host_state = jax.device_get((variables, opt_state))
            # Atomic replace: a preemption kill (SIGTERM past the
            # completion buffer) can land mid-save, and a torn write at
            # the final path would poison EVERY subsequent retry with
            # an unreadable checkpoint (observed live: msgpack
            # "incomplete input" on the packed-pair chip demo). Writing
            # beside and renaming keeps the previous good checkpoint
            # until the new one is fully on disk.
            tmp_path = ckpt_path + ".tmp"
            with open(tmp_path, "wb") as f:
                f.write(serialization.to_bytes(host_state))
            os.replace(tmp_path, ckpt_path)

    if resuming and not restored:
        # build_family returned the zero template on the promise that a
        # checkpoint would fill it; training from zeros would silently
        # produce garbage and then overwrite the checkpoint with it.
        raise RuntimeError(
            f"checkpoint at {resume_from} disappeared between resume "
            "detection and restore"
        )
    if restored and jax.process_count() == 1:
        # from_bytes / orbax restore leave host-side numpy leaves;
        # donated host buffers are unusable, so the first jit_step —
        # the largest (compile-inclusive) step — would copy the whole
        # state and warn "donated buffers were not usable" into the
        # phase-timing scrape. Upload once here instead, charged to the
        # restore phase where the transfer belongs. (Multi-process runs
        # go through host_local_array_to_global_array below, which does
        # its own placement.)
        variables, opt_state = jax.device_put((variables, opt_state))
    mark_phase("restore")
    jit_step = jax.jit(step_fn, donate_argnums=(0, 1))
    # SHOCKWAVE_SANITIZE=jax: every step runs under the device-to-host
    # transfer guard and a recompile after warmup (the loop is
    # shape-stable by construction) fails the run; a no-op otherwise.
    from shockwave_tpu.analysis import sanitize

    jit_step = sanitize.watch_jit("train.jit_step", jit_step)
    # Each gang member generates ITS OWN data shard (distinct rng per
    # rank); single-process runs keep the plain seed.
    np_rng = np.random.default_rng(args.seed + jax.process_index())

    if jax.process_count() > 1:
        # Multi-host data parallelism over the gang: the train state is
        # replicated as a global array across every process's devices and
        # each process's local batch becomes one shard of the global
        # batch along the mesh's "data" axis — XLA then inserts the
        # cross-process gradient allreduce (Gloo on CPU hosts, ICI/DCN
        # on TPU fleets). This is the TPU-native counterpart of the
        # reference's DDP/NCCL data plane (reference:
        # scheduler/scheduler.py:1943-1950 rendezvous + torch DDP inside
        # workloads).
        from jax.experimental import multihost_utils
        from jax.sharding import PartitionSpec as P

        variables = multihost_utils.host_local_array_to_global_array(
            variables, mesh, P()
        )
        opt_state = multihost_utils.host_local_array_to_global_array(
            opt_state, mesh, P()
        )

        def globalize(batch):
            return multihost_utils.host_local_array_to_global_array(
                batch, mesh, P("data")
            )

    else:

        def globalize(batch):
            return batch

    class Batches:
        def __iter__(self):
            while True:
                yield globalize(batch_fn(np_rng))

    use_iterator = args.enable_shockwave_iterator and "SHOCKWAVE_JOB_ID" in os.environ
    if use_iterator:
        from shockwave_tpu.runtime.iterator import ShockwaveIterator

        loader = ShockwaveIterator(
            Batches(), args.checkpoint_dir or "/tmp",
            save_checkpoint_func=save_checkpoint,
        )
    else:
        loader = Batches()

    steps = 0
    start = time.time()
    loss = None
    for batch in loader:
        variables, opt_state, loss = jit_step(variables, opt_state, batch)
        steps += 1
        if steps == 1 and os.environ.get("SHOCKWAVE_PHASE_TIMINGS"):
            # Deliberate one-time sync: fences the compile-inclusive
            # first step so the phase scrape attributes it to compile,
            # not to steady-state train; gated off in production runs.
            # shockwave-lint: disable=host-sync-in-hot-loop
            loss.block_until_ready()
            mark_phase("first_step_compile")
        if steps >= args.num_steps:
            if use_iterator:
                loader.complete()
            break
    if loss is not None:
        loss.block_until_ready()
    elapsed = time.time() - start
    mark_phase("train")
    save_checkpoint()
    mark_phase("save")
    if phase_timings:
        print(
            "PHASES "
            + " ".join(f"{k}={v:.1f}s" for k, v in phase_timings.items())
        )
    loss_str = f"{float(loss):.4f}" if loss is not None else "n/a"
    print(
        f"[{args.model}] steps={steps} loss={loss_str} "
        f"throughput={steps / max(elapsed, 1e-9):.2f} steps/s"
    )
    if sanitize.active_kinds():
        # One machine-readable line so the launching harness (the
        # sanitize smoke gate, a dispatcher scraping worker stdout) can
        # collect the sanitizer verdict without a side channel.
        import json as _json

        print("SANITIZE " + _json.dumps(sanitize.report()))


if __name__ == "__main__":
    main()
