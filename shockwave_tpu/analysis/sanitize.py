"""Runtime sanitizers: the dynamic oracle behind the static rules.

``SHOCKWAVE_SANITIZE=locks,jax`` (comma-separated kinds) switches on:

* **locks** — every lock the production classes create through
  :func:`make_lock` / :func:`make_rlock` / :func:`make_condition`
  becomes an instrumented wrapper that records the per-thread
  acquisition order into a process-global lockdep-style graph and
  RAISES on:

  - an **order inversion**: thread acquires B while holding A after
    any thread has acquired A while holding B (the dynamic counterpart
    of the static ``lock-order-cycle`` rule — the static rule proves
    the graph cycle can exist, the sanitizer proves a run actually
    walked both sides);
  - a **self-deadlock**: blocking re-acquisition of a non-reentrant
    lock the same thread already holds (raised instead of hanging);
  - a **hold-time ceiling** breach: a critical section held longer
    than ``SHOCKWAVE_SANITIZE_HOLD_S`` seconds (default 10) — the
    precursor of every "scheduler round stalls behind a metrics
    flush" incident.

* **jax** — hot JAX entry points opt in via :func:`watch_jit` (the
  train step) and :func:`jax_entry` / :func:`check_recompiles` (the
  solver): calls run under ``jax.transfer_guard_device_to_host
  ("disallow")`` so an implicit device→host transfer raises at the
  offending line, and a compilation counter fails the run when a
  shape-stable loop recompiles (cache size exceeding the distinct
  signatures/budget seen — the silent 20-40 s stall the watchdog's
  solver-time rule can only flag after the fact).

* **threads** — the dynamic counterpart of the static
  ``shared-state-race`` rule. :func:`instrument_for_threads` patches
  ``__setattr__`` on the classes the static pass identifies (the
  lock-owning families of the race scope) to record, per (instance,
  field), which thread wrote it and the intersection of sanitized
  locks that thread held across its writes. A write from a second
  thread whose lock set is disjoint from another writer's RAISES
  :class:`ThreadRaceViolation` at the offending line. Two deliberate
  deltas from the static model: tracking is at ATTRIBUTE-WRITE
  granularity (an in-place container mutation never passes through
  ``__setattr__`` — that hazard is the static rule's domain; the
  dynamic half observes the rebind/RMW side), and it is STRICTER
  about cross-thread lock-free rebinds (the static GIL model blesses
  fresh-value publication; an observed lock-free cross-thread write
  pair raises here, because at runtime the tracker cannot tell a
  blessed publication from a torn read-modify-write). The first
  writer per (instance, field) owns an exclusive construction/setup
  phase that never pairs. Enabling ``threads`` also instruments the
  lock factories — the held stack is what the recorder reads, and
  the full lock sanitizer (order-inversion, self-deadlock,
  hold-ceiling checks, recorded under kind ``"locks"``) is active
  with it.

Disabled (the default), every factory returns the raw
``threading`` primitive and every wrapper returns its argument —
zero overhead, bit-identical behavior.

Violations raise immediately AND are recorded; :func:`report` returns
a JSON-ready summary (the committed smoke artifact) and
:func:`violations_as_findings` renders them as
:class:`~shockwave_tpu.analysis.core.Finding` records so runtime
evidence flows through the same fingerprint/baseline machinery as the
static rules.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Dict, List, Optional, Set, Tuple

from shockwave_tpu.analysis.core import Finding

__all__ = [
    "SanitizerError",
    "LockOrderViolation",
    "LockHoldViolation",
    "RecompileViolation",
    "ThreadRaceViolation",
    "configure",
    "enabled",
    "make_lock",
    "make_rlock",
    "make_condition",
    "watch_jit",
    "jax_entry",
    "check_recompiles",
    "instrument_class",
    "instrument_for_threads",
    "report",
    "reset",
    "violations_as_findings",
]


class SanitizerError(RuntimeError):
    """Base class for sanitizer-detected violations."""


class LockOrderViolation(SanitizerError):
    pass


class LockHoldViolation(SanitizerError):
    pass


class RecompileViolation(SanitizerError):
    pass


class ThreadRaceViolation(SanitizerError):
    pass


# -- configuration ------------------------------------------------------

_DEFAULT_HOLD_S = 10.0

# Explicit override (tests / drivers); None means "read the env".
_configured: Optional[frozenset] = None


def configure(kinds=None) -> None:
    """Explicitly enable sanitizer kinds (an iterable of ``"locks"`` /
    ``"jax"``), overriding ``SHOCKWAVE_SANITIZE``; ``configure(None)``
    returns control to the environment variable."""
    global _configured
    _configured = None if kinds is None else frozenset(kinds)


def active_kinds() -> frozenset:
    if _configured is not None:
        return _configured
    raw = os.environ.get("SHOCKWAVE_SANITIZE", "")
    return frozenset(k.strip() for k in raw.split(",") if k.strip())


def enabled(kind: str) -> bool:
    return kind in active_kinds()


def hold_ceiling_s() -> float:
    try:
        return float(os.environ.get("SHOCKWAVE_SANITIZE_HOLD_S", ""))
    except ValueError:
        return _DEFAULT_HOLD_S


# -- shared violation ledger -------------------------------------------

_state_lock = threading.Lock()
_violations: List[dict] = []


def _caller_site() -> Tuple[str, int, str]:
    """(relpath, line, source text) of the first stack frame outside
    this module — the production line that committed the violation."""
    import linecache
    import sys

    frame = sys._getframe(1)
    here = os.path.abspath(__file__)

    def _internal(f) -> bool:
        filename = f.f_code.co_filename
        # Condition routes acquisitions through threading.py
        # (__enter__/wait/_acquire_restore); the witness the operator
        # needs is the production `with self._cv:` line, not stdlib.
        return filename == here or filename.endswith(
            os.sep + "threading.py"
        )

    while frame is not None and _internal(frame):
        frame = frame.f_back
    if frame is None:  # pragma: no cover - defensive
        return "<unknown>", 0, ""
    filename = frame.f_code.co_filename
    line = frame.f_lineno
    text = linecache.getline(filename, line).strip()
    from shockwave_tpu.analysis.core import repo_root

    root = repo_root()
    try:
        rel = os.path.relpath(filename, root)
    except ValueError:  # pragma: no cover - different drive (windows)
        rel = filename
    if rel.startswith(".."):
        rel = filename
    return rel.replace(os.sep, "/"), line, text


def _record_violation(kind: str, rule: str, message: str) -> dict:
    path, line, text = _caller_site()
    entry = {
        "kind": kind,
        "rule": rule,
        "path": path,
        "line": line,
        "line_text": text,
        "message": message,
        "thread": threading.current_thread().name,
    }
    with _state_lock:
        _violations.append(entry)
    return entry


def violations() -> List[dict]:
    with _state_lock:
        return list(_violations)


def violations_as_findings() -> List[Finding]:
    """Runtime violations as lint findings, so a CI harness can merge
    them into the same fingerprint/baseline ratchet as the static
    rules."""
    return [
        Finding(
            rule=v["rule"],
            path=v["path"],
            line=v["line"],
            col=0,
            message=v["message"],
            line_text=v["line_text"],
        )
        for v in violations()
    ]


# -- lock sanitizer -----------------------------------------------------

# (held_name, acquired_name) -> first witness {thread, site}
_lock_edges: Dict[Tuple[str, str], dict] = {}
_tls = threading.local()


def _held_stack() -> List["_Held"]:
    stack = getattr(_tls, "held", None)
    if stack is None:
        stack = []
        _tls.held = stack
    return stack


class _Held:
    __slots__ = ("lock", "t_acquired", "count")

    def __init__(self, lock):
        self.lock = lock
        self.t_acquired = time.monotonic()
        self.count = 1


class SanitizedLock:
    """Instrumented wrapper around ``threading.Lock``/``RLock`` that
    maintains the per-thread held stack and the global acquisition-order
    graph. Exposes the ``Condition`` integration surface
    (``_release_save``/``_acquire_restore``/``_is_owned``) so
    ``threading.Condition(sanitized_lock)`` works unchanged — a
    ``wait()`` correctly pops the lock from the held stack for its
    duration."""

    def __init__(self, name: str, inner, reentrant: bool):
        self.name = name
        self._inner = inner
        self._reentrant = reentrant

    # -- core protocol ---------------------------------------------------
    def acquire(self, blocking: bool = True, timeout: float = -1):
        stack = _held_stack()
        mine = next((h for h in stack if h.lock is self), None)
        if mine is not None and not self._reentrant and blocking:
            entry = _record_violation(
                "locks",
                "sanitize-self-deadlock",
                f"blocking re-acquisition of non-reentrant lock "
                f"{self.name} already held by this thread — this would "
                "deadlock; raised instead",
            )
            raise LockOrderViolation(entry["message"])
        if blocking and mine is None:
            # Inversion check BEFORE the blocking acquire: when the
            # other side of an AB/BA pair is live (the other thread
            # holds what we want and wants what we hold), checking
            # after acquire() returns would never run — the deadlock
            # the sanitizer exists to catch would hang undiagnosed.
            self._precheck_inversion(stack)
        ok = (
            self._inner.acquire(blocking, timeout)
            if timeout != -1 or not blocking
            else self._inner.acquire()
        )
        if not ok:
            return ok
        if mine is not None and self._reentrant:
            mine.count += 1
            return ok
        self._note_acquired(stack)
        stack.append(_Held(self))
        return ok

    def _precheck_inversion(self, stack: List[_Held]) -> None:
        held_names = {h.lock.name for h in stack if h.lock is not self}
        if not held_names:
            return
        with _state_lock:
            inverted = sorted(
                held
                for held in held_names
                if (self.name, held) in _lock_edges
            )
        if inverted:
            witness = _lock_edges[(self.name, inverted[0])]
            entry = _record_violation(
                "locks",
                "sanitize-lock-order",
                f"lock-order inversion: acquiring {self.name} while "
                f"holding {inverted[0]}, but {witness['thread']} "
                f"previously acquired {inverted[0]} while holding "
                f"{self.name} (at {witness['site']}) — AB/BA deadlock "
                "hazard; raised before blocking",
            )
            raise LockOrderViolation(entry["message"])

    def _note_acquired(self, stack: List[_Held]) -> None:
        held_names = {h.lock.name for h in stack if h.lock is not self}
        if not held_names:
            return
        path, line, _ = _caller_site()
        site = f"{path}:{line}"
        with _state_lock:
            for held in held_names:
                _lock_edges.setdefault(
                    (held, self.name),
                    {
                        "thread": threading.current_thread().name,
                        "site": site,
                    },
                )
            inverted = sorted(
                held
                for held in held_names
                if (self.name, held) in _lock_edges
            )
        if inverted:
            witness = _lock_edges[(self.name, inverted[0])]
            entry = _record_violation(
                "locks",
                "sanitize-lock-order",
                f"lock-order inversion: acquiring {self.name} while "
                f"holding {inverted[0]}, but {witness['thread']} "
                f"previously acquired {inverted[0]} while holding "
                f"{self.name} (at {witness['site']}) — AB/BA deadlock "
                "hazard",
            )
            self._inner.release()
            raise LockOrderViolation(entry["message"])

    def release(self):
        stack = _held_stack()
        for i in range(len(stack) - 1, -1, -1):
            if stack[i].lock is self:
                held = stack[i]
                if self._reentrant and held.count > 1:
                    held.count -= 1
                    self._inner.release()
                    return
                del stack[i]
                self._inner.release()
                dt = time.monotonic() - held.t_acquired
                ceiling = hold_ceiling_s()
                if dt > ceiling:
                    import sys

                    entry = _record_violation(
                        "locks",
                        "sanitize-lock-hold",
                        f"lock {self.name} held for {dt:.3f}s, over the "
                        f"{ceiling:.3f}s ceiling — long critical "
                        "sections stall every contending thread",
                    )
                    # If the with-body is already unwinding a real
                    # error, record only: replacing it with the hold
                    # violation would misattribute the run's failure
                    # to a slow critical section.
                    if sys.exc_info()[0] is None:
                        raise LockHoldViolation(entry["message"])
                return
        # Not held by this thread (foreign release) — delegate and let
        # threading raise its own error.
        self._inner.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def locked(self):
        return self._inner.locked()

    # -- Condition integration ------------------------------------------
    def _release_save(self):
        stack = _held_stack()
        saved_entry = None
        for i in range(len(stack) - 1, -1, -1):
            if stack[i].lock is self:
                saved_entry = stack.pop(i)
                break
        if hasattr(self._inner, "_release_save"):
            inner_state = self._inner._release_save()
        else:
            self._inner.release()
            inner_state = None
        return (inner_state, saved_entry)

    def _acquire_restore(self, state):
        inner_state, saved_entry = state
        if hasattr(self._inner, "_acquire_restore"):
            self._inner._acquire_restore(inner_state)
        else:
            self._inner.acquire()
        stack = _held_stack()
        if saved_entry is not None:
            saved_entry.t_acquired = time.monotonic()
            stack.append(saved_entry)
        else:  # pragma: no cover - defensive
            stack.append(_Held(self))

    def _is_owned(self):
        if hasattr(self._inner, "_is_owned"):
            return self._inner._is_owned()
        return any(h.lock is self for h in _held_stack())

    def __repr__(self):  # pragma: no cover - debugging aid
        return f"<SanitizedLock {self.name} {self._inner!r}>"


def _locks_instrumented() -> bool:
    """The thread sanitizer reads the held-lock stack, so enabling
    ``threads`` instruments the lock factories too."""
    return enabled("locks") or enabled("threads")


def make_lock(name: str):
    """A ``threading.Lock`` — instrumented when the lock (or thread)
    sanitizer is active. ``name`` is the project-wide lock identity,
    conventionally matching the static analyzer's node names
    (``"obs.metrics.MetricsRegistry._lock"``)."""
    if _locks_instrumented():
        return SanitizedLock(name, threading.Lock(), reentrant=False)
    return threading.Lock()


def make_rlock(name: str):
    if _locks_instrumented():
        return SanitizedLock(name, threading.RLock(), reentrant=True)
    return threading.RLock()


def make_condition(lock=None, name: str = "condition"):
    """``threading.Condition`` over ``lock`` (itself typically from
    :func:`make_lock`/:func:`make_rlock`); creates a sanitized RLock
    when none is given."""
    return threading.Condition(lock if lock is not None else make_rlock(name))


def observed_lock_graph() -> dict:
    """The dynamically observed acquisition-order edges — diff against
    ``python -m shockwave_tpu.analysis --lock-graph`` (the static
    prediction) when triaging a deadlock."""
    with _state_lock:
        return {
            "edges": [
                {"held": a, "acquired": b, **w}
                for (a, b), w in sorted(_lock_edges.items())
            ]
        }


# -- thread-race sanitizer ----------------------------------------------

# Classes patched by instrument_class (qname -> class), for report()
# and idempotency across repeated instrument_for_threads() calls.
_instrumented: Dict[str, type] = {}
_tracked_write_count = 0

# Attribute names never tracked: the sanitizer's own bookkeeping slot
# plus lock objects (their wrappers maintain the held stack already).
_TRACK_SKIP_PREFIX = "_san_"


def _held_lock_names() -> frozenset:
    return frozenset(h.lock.name for h in _held_stack())


_OWNER_KEY = "\x00owner"


def _note_field_write(owner: str, obj, attr: str) -> None:
    """Record one field write on an instrumented instance and raise on
    an observed unsynchronized cross-thread write pair.

    Eraser-style lockset states per (instance, field): writes stay in
    an EXCLUSIVE phase while a single thread owns the field
    (construction and pre-publication setup — a driver configuring the
    scheduler before starting its round-loop thread — are lock-free by
    design and never pair). The first write from a SECOND thread moves
    the field to the shared phase: from then on each writer thread's
    entry is the INTERSECTION of the sanitized locks it held across
    its writes, and two threads whose entries are disjoint raced."""
    global _tracked_write_count
    inst = getattr(obj, "__dict__", None)
    if inst is None:  # __slots__ instance: nowhere to hang the table
        return
    thread = threading.current_thread().name
    held = _held_lock_names()
    track = inst.setdefault("_san_writes", {})
    with _state_lock:
        _tracked_write_count += 1
        seen = track.get(attr)
        if seen is None:
            track[attr] = {_OWNER_KEY: thread}  # exclusive phase
            return
        if _OWNER_KEY in seen:
            if seen[_OWNER_KEY] == thread:
                return  # still exclusive: setup writes are free
            # Second thread arrived: the field is shared from HERE.
            # The exclusive owner's setup history is forgiven (it
            # happened-before this thread could exist).
            del seen[_OWNER_KEY]
        prev = seen.get(thread)
        seen[thread] = held if prev is None else (prev & held)
        mine = seen[thread]
        conflict = next(
            (
                (other, locks)
                for other, locks in seen.items()
                if other != thread and not (locks & mine)
            ),
            None,
        )
    if conflict is not None:
        other, locks = conflict
        entry = _record_violation(
            "threads",
            "sanitize-thread-race",
            f"unsynchronized cross-thread write to {owner}.{attr}: "
            f"{thread} wrote holding "
            f"{{{', '.join(sorted(mine)) or 'no locks'}}} but {other} "
            f"wrote holding "
            f"{{{', '.join(sorted(locks)) or 'no locks'}}} — the "
            "guaranteed lock sets are disjoint, so these writes "
            "interleave",
        )
        raise ThreadRaceViolation(entry["message"])


def instrument_class(cls: type, owner: Optional[str] = None) -> type:
    """Patch ``cls.__setattr__`` to track per-(instance, field) writes
    while the thread sanitizer is active. Idempotent per CLASS (the
    marker lives in ``cls.__dict__``, not inherited, so a subclass can
    still be instrumented independently while the same class is never
    double-wrapped under two owner labels); returns ``cls``. The
    underlying write always happens BEFORE the race check raises, so
    state is not corrupted by the diagnostic."""
    owner = owner or f"{cls.__module__}.{cls.__qualname__}"
    if cls.__dict__.get("_san_instrumented"):
        return cls
    orig = cls.__setattr__

    def __setattr__(self, name, value, _orig=orig, _owner=owner):
        _orig(self, name, value)
        # Gate per write, not just at patch time: the patch is
        # irreversible, so a process that instrumented under
        # ``threads`` and later turned it off (test suites) must stop
        # tracking — locks made AFTER the switch-off are raw and
        # invisible to the held stack, and pairing their correctly
        # guarded writes as "lock-free" would raise spuriously.
        if not name.startswith(_TRACK_SKIP_PREFIX) and enabled(
            "threads"
        ):
            _note_field_write(_owner, self, name)

    cls.__setattr__ = __setattr__
    cls._san_instrumented = True
    _instrumented[owner] = cls
    return cls


def instrument_for_threads() -> List[str]:
    """Instrument the classes the STATIC pass identifies as shared
    (the lock-owning class families in the shared-state-race scope of
    :mod:`shockwave_tpu.analysis.rules.races`): every member class gets
    write tracking. No-op unless ``threads`` is active. Returns the
    instrumented class qnames."""
    if not enabled("threads"):
        return []
    import importlib

    from shockwave_tpu.analysis.project import Project

    project = Project.build()
    targets: List[str] = []
    for qn in sorted(project.classes):
        family = project.class_family(qn)
        if not project.family_owns_lock(family):
            continue
        if qn.startswith(f"{project.package}.analysis."):
            continue  # never instrument the sanitizer's own machinery
        if qn != family:
            # Patch only the family ROOT: subclasses inherit the
            # instrumented __setattr__, and patching both would track
            # every write twice (mis-counting construction writes).
            continue
        targets.append(qn)
    done: List[str] = []
    for qn in targets:
        modname, _, clsname = qn.rpartition(".")
        try:
            cls = getattr(importlib.import_module(modname), clsname)
        except (ImportError, AttributeError):  # pragma: no cover
            # A class gated behind an optional dep loses write
            # tracking: say so, or the coverage gap is invisible.
            import logging

            logging.getLogger("analysis.sanitize").warning(
                "thread sanitizer could not import %s; its fields "
                "are NOT write-tracked this run", qn, exc_info=True,
            )
            continue
        instrument_class(cls, owner=project.short(qn))
        done.append(qn)
    return done


# -- jax sanitizer ------------------------------------------------------

_jax_entries: Dict[str, dict] = {}
_jit_watches: Dict[str, "_JitWatch"] = {}
_recompile_checks: Dict[str, dict] = {}


def _d2h_guard():
    import jax

    return jax.transfer_guard_device_to_host("disallow")


class _JitWatch:
    """Wraps a jitted callable: every call runs under the
    device-to-host transfer guard, and cache growth beyond
    ``max_compiles`` raises — a shape-stable loop must compile once."""

    def __init__(self, name: str, fn, max_compiles: int):
        self.name = name
        self._fn = fn
        self.max_compiles = max_compiles
        self.calls = 0

    def compiles(self) -> int:
        cache_size = getattr(self._fn, "_cache_size", None)
        return int(cache_size()) if callable(cache_size) else -1

    def __call__(self, *args, **kwargs):
        with _d2h_guard():
            out = self._fn(*args, **kwargs)
        self.calls += 1
        size = self.compiles()
        if size > self.max_compiles:
            entry = _record_violation(
                "jax",
                "sanitize-recompile",
                f"{self.name} recompiled: jit cache holds {size} "
                f"entries after call {self.calls}, budget "
                f"{self.max_compiles} — a shape-stable loop is "
                "recompiling (shape/dtype/static-arg churn)",
            )
            raise RecompileViolation(entry["message"])
        return out

    def __getattr__(self, attr):
        return getattr(self._fn, attr)


def watch_jit(name: str, fn, max_compiles: int = 1):
    """Instrument a jitted callable when the jax sanitizer is active;
    returns ``fn`` unchanged otherwise."""
    if not enabled("jax"):
        return fn
    watch = _JitWatch(name, fn, max_compiles)
    with _state_lock:
        _jit_watches[name] = watch
    return watch


class _JaxEntry:
    def __init__(self, name):
        self._name = name
        self._guard = _d2h_guard()

    def __enter__(self):
        with _state_lock:
            _jax_entries.setdefault(self._name, {"calls": 0})["calls"] += 1
        self._guard.__enter__()
        return self

    def __exit__(self, *exc):
        return self._guard.__exit__(*exc)


class _NullEntry:
    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_ENTRY = _NullEntry()


def jax_entry(name: str):
    """Context manager for a hot device entry point (the solver's
    device head): device-to-host transfers inside raise while the jax
    sanitizer is active. The host tail (explicit ``jax.device_get`` /
    ``np.asarray`` on the returned arrays) belongs OUTSIDE the block."""
    if not enabled("jax"):
        return _NULL_ENTRY
    return _JaxEntry(name)


def check_recompiles(name: str, fn, signature) -> None:
    """Record one call of jitted ``fn`` under the distinct-shape
    ``signature`` and fail when its compile cache outgrew the number of
    distinct signatures seen — i.e. a recompile happened with no shape
    change to justify it."""
    if not enabled("jax"):
        return
    cache_size = getattr(fn, "_cache_size", None)
    size = int(cache_size()) if callable(cache_size) else -1
    with _state_lock:
        st = _recompile_checks.get(name)
        if st is None:
            # The jit cache is process-global and may hold entries from
            # callers that predate sanitizing (or aren't checked at
            # all); charge everything before the first checked call —
            # which itself may have compiled one entry — to a baseline
            # so only growth past the tracked signatures counts.
            st = _recompile_checks[name] = {
                "signatures": set(),
                "calls": 0,
                "compiles": 0,
                "baseline": max(0, size - 1),
            }
        st["signatures"].add(signature)
        st["calls"] += 1
        st["compiles"] = size
        budget = st["baseline"] + len(st["signatures"])
    if size > budget:
        entry = _record_violation(
            "jax",
            "sanitize-recompile",
            f"{name} recompiled: jit cache holds {size} entries against "
            f"a budget of {budget} ({st['baseline']} pre-existing + "
            f"{len(st['signatures'])} distinct checked signature(s)) — "
            "a shape-stable call path is recompiling",
        )
        raise RecompileViolation(entry["message"])


# -- reporting ----------------------------------------------------------

def report() -> dict:
    """JSON-ready summary of everything the active sanitizers saw —
    the committed smoke artifact's payload."""
    with _state_lock:
        return {
            "active": sorted(active_kinds()),
            "violations": list(_violations),
            "locks": {
                "edges": [
                    {"held": a, "acquired": b, **w}
                    for (a, b), w in sorted(_lock_edges.items())
                ],
            },
            "threads": {
                "instrumented": sorted(_instrumented),
                "tracked_writes": _tracked_write_count,
            },
            "jax": {
                "entries": {
                    name: dict(st) for name, st in sorted(_jax_entries.items())
                },
                "watches": {
                    name: {"calls": w.calls, "compiles": w.compiles()}
                    for name, w in sorted(_jit_watches.items())
                },
                "recompile_checks": {
                    name: {
                        "calls": st["calls"],
                        "distinct_signatures": len(st["signatures"]),
                        "compiles": st["compiles"],
                        "baseline": st["baseline"],
                    }
                    for name, st in sorted(_recompile_checks.items())
                },
            },
        }


def reset() -> None:
    """Tests only: drop all recorded sanitizer state. Instrumented
    classes stay patched (their tracking is per-instance, and dead
    instances take their write tables with them)."""
    global _violations, _tracked_write_count
    with _state_lock:
        _violations = []
        _lock_edges.clear()
        _jax_entries.clear()
        _jit_watches.clear()
        _recompile_checks.clear()
        _tracked_write_count = 0
    _tls.held = []
