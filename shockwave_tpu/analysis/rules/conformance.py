"""Rule: solver-backend-conformance.

Every EG solver backend consumes the same :class:`EGProblem`; the PR-1
switching-cost term had to be hand-ported to level/greedy/relaxed/
sharded/native/MILP because nothing checks that a backend implements
every objective term. This rule makes the interface mechanical: a
backend module that defines a ``solve*`` entry point must (a) take the
shared ``EGProblem`` as its first parameter on public ``solve_eg_*``
entries, and (b) reference the switching-cost term
(``switch_bonus``, or the raw ``switch_cost``+``incumbent`` pair) so a
new backend cannot silently optimize the pre-PR-1 objective. The
planner facade (``policies/shockwave.py``) must keep threading
``switch_cost=``/``incumbent=`` into the EGProblem it builds, keep a
dispatch branch for every registered backend, and the JAX cold-start
entry must stay wired to the warm-start cache.
"""

from __future__ import annotations

import ast
import fnmatch
import re
from typing import Iterator, List, Set

from shockwave_tpu.analysis.core import FileContext, Finding, Rule, dotted_name

_BACKEND_GLOBS = (
    "shockwave_tpu/solver/eg_*.py",
    "shockwave_tpu/native/__init__.py",
    # The what-if fleet solves the same EG objective in batch: a
    # scenario kernel that silently dropped the switching-cost term
    # would price counterfactuals against a different market than the
    # planner runs.
    "shockwave_tpu/whatif/*.py",
)
_NON_BACKEND_FILES = {"shockwave_tpu/solver/eg_problem.py"}
_PLANNER_FILE = "shockwave_tpu/policies/shockwave.py"
_WARM_START_FILE = "shockwave_tpu/solver/eg_jax.py"
_CELLS_FILE = "shockwave_tpu/cells/planner.py"
_CELLS_COORD_FILE = "shockwave_tpu/cells/coordinator.py"

# Dispatch branches the planner must keep: one per registered backend
# ("cells" routes to the partitioned-market CellPlanner federation).
REQUIRED_BACKENDS = (
    "reference", "native", "level", "sharded", "relaxed", "pdhg",
    "cells",
)

# Fallback rungs the planner's degradation ladder must register (the
# first-order PDHG rung sits between the primary backend and the PGD
# relaxed solve; native is the mandatory host-only final rung).
REQUIRED_LADDER_RUNGS = ("pdhg", "relaxed", "native")

_SOLVE_ENTRY_RE = re.compile(r"^solve(_|$)")


def _is_backend_module(relpath: str) -> bool:
    if relpath in _NON_BACKEND_FILES:
        return False
    return any(fnmatch.fnmatch(relpath, g) for g in _BACKEND_GLOBS)


class SolverBackendConformance(Rule):
    name = "solver-backend-conformance"
    description = (
        "solver backend or planner solve path missing a required "
        "objective term / kwarg (switching cost, warm start) or a "
        "registered dispatch branch"
    )
    rationale = (
        "interface conformance across solver backends is where "
        "correctness quietly dies (MPAX): a backend that drops one "
        "objective term still returns plausible schedules"
    )

    def applies_to(self, relpath: str) -> bool:
        return _is_backend_module(relpath) or relpath in (
            _PLANNER_FILE,
            _WARM_START_FILE,
            _CELLS_FILE,
            _CELLS_COORD_FILE,
        )

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        if _is_backend_module(ctx.relpath):
            yield from self._check_backend(ctx)
        if ctx.relpath == _WARM_START_FILE:
            yield from self._check_warm_start(ctx)
        if ctx.relpath == _PLANNER_FILE:
            yield from self._check_planner(ctx)
        if ctx.relpath == _CELLS_FILE:
            yield from self._check_cells(ctx)
        if ctx.relpath == _CELLS_COORD_FILE:
            yield from self._check_cells_coordinator(ctx)

    # -- backend modules ------------------------------------------------

    def _solve_defs(self, ctx: FileContext) -> List[ast.FunctionDef]:
        return [
            n
            for n in ctx.tree.body
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
            and _SOLVE_ENTRY_RE.match(n.name.lstrip("_"))
        ]

    def _references(self, ctx: FileContext, name: str) -> bool:
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Attribute) and node.attr == name:
                return True
            if isinstance(node, ast.Name) and node.id == name:
                return True
            if isinstance(node, ast.Constant) and node.value == name:
                return True
        return False

    def _check_backend(self, ctx: FileContext):
        solves = self._solve_defs(ctx)
        if not solves:
            return
        has_switch_term = self._references(ctx, "switch_bonus") or (
            self._references(ctx, "switch_cost")
            and self._references(ctx, "incumbent")
        )
        if not has_switch_term:
            yield self.finding(
                ctx,
                solves[0],
                f"backend module defines {solves[0].name}() but never "
                "references the switching-cost term (switch_bonus, or "
                "switch_cost+incumbent) — a plan from this backend "
                "silently drops incumbents for free",
            )
        for fn in solves:
            if not fn.name.startswith("solve_eg_"):
                continue
            params = [a.arg for a in fn.args.args]
            if not params or params[0] != "problem":
                yield self.finding(
                    ctx,
                    fn,
                    f"public backend entry {fn.name}() must take the "
                    "shared EGProblem as its first parameter "
                    "('problem'), the interface every caller and the "
                    "bench harness rely on",
                )

    # -- warm start -----------------------------------------------------

    def _check_warm_start(self, ctx: FileContext):
        if not self._references(ctx, "warm_start"):
            yield self.finding(
                ctx,
                1,
                "solver/eg_jax.py no longer references the warm_start "
                "cache — the sub-2s cold-start contract "
                "(solve_level_counts) is broken",
            )

    # -- cell federation ------------------------------------------------

    def _check_cells(self, ctx: FileContext):
        """The cell-decomposed coordinator's own contract: it must keep
        a coordinated ``_replan`` (the flight-recorder replay entry
        point), price migrations through the switching-cost term, and
        route per-cell solves through the child planner's solve path so
        each cell keeps the degradation ladder (a cell-solver timeout
        degrades that cell only)."""
        has_replan = any(
            isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
            and n.name == "_replan"
            for n in ast.walk(ctx.tree)
        )
        if not has_replan:
            yield self.finding(
                ctx,
                1,
                "cells/planner.py no longer defines _replan() — the "
                "coordinated replan is the flight-recorder replay "
                "contract for cell-decomposed runs",
            )
        if not (
            self._references(ctx, "_solve")
            and self._references(ctx, "_solve_backend")
        ):
            yield self.finding(
                ctx,
                1,
                "cells/planner.py no longer routes per-cell solves "
                "through the child planner's _solve/_solve_backend "
                "path — cells would lose the degradation ladder (and "
                "replay could not re-enter a degraded cell's backend)",
            )

    def _check_cells_coordinator(self, ctx: FileContext):
        """Migration pricing: the coordinator must keep weighing the
        switching-cost term when it plans cross-cell moves."""
        has_switch_term = self._references(ctx, "switch_bonus") or (
            self._references(ctx, "switch_cost")
            and self._references(ctx, "incumbent")
        )
        if not has_switch_term:
            yield self.finding(
                ctx,
                1,
                "cells/coordinator.py never references the "
                "switching-cost term — cross-cell migrations would be "
                "free, thrashing incumbents the objective exists to "
                "protect",
            )

    # -- planner facade -------------------------------------------------

    def _check_planner(self, ctx: FileContext):
        # (a) The EGProblem the planner builds must thread the
        # preemption-awareness kwargs.
        eg_calls = [
            n
            for n in ast.walk(ctx.tree)
            if isinstance(n, ast.Call)
            and dotted_name(n.func).split(".")[-1] == "EGProblem"
        ]
        for call in eg_calls:
            kwargs = {kw.arg for kw in call.keywords}
            for required in ("switch_cost", "incumbent"):
                if required not in kwargs:
                    yield self.finding(
                        ctx,
                        call,
                        f"EGProblem(...) built without {required}= — the "
                        "planner would solve the zero-overhead objective "
                        "and thrash incumbents",
                    )
        # (b) Every registered backend keeps a dispatch branch.
        compared: Set[str] = set()
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Compare):
                continue
            operands = [node.left] + list(node.comparators)
            names = {dotted_name(o) for o in operands}
            if not any(n.endswith("backend") for n in names if n):
                continue
            for o in operands:
                if isinstance(o, ast.Constant) and isinstance(o.value, str):
                    compared.add(o.value)
        missing = [b for b in REQUIRED_BACKENDS if b not in compared]
        for backend in missing:
            yield self.finding(
                ctx,
                1,
                f"planner dispatch no longer handles backend "
                f"{backend!r} — removing a backend branch must be "
                "deliberate (update REQUIRED_BACKENDS in "
                "analysis/rules/conformance.py alongside)",
            )
        # (c) The degradation ladder keeps every registered fallback
        # rung: a solver timeout must still have the cheap first-order
        # and host-greedy recovery paths.
        ladder_fn = None
        for node in ast.walk(ctx.tree):
            if (
                isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
                and node.name == "_ladder_rungs"
            ):
                ladder_fn = node
                break
        if ladder_fn is None:
            yield self.finding(
                ctx,
                1,
                "planner no longer defines _ladder_rungs() — the solver "
                "degradation ladder (plan_deadline_s / fault-injection "
                "recovery) has lost its fallback contract",
            )
        else:
            rung_names = {
                n.value
                for n in ast.walk(ladder_fn)
                if isinstance(n, ast.Constant) and isinstance(n.value, str)
            }
            for rung in REQUIRED_LADDER_RUNGS:
                if rung not in rung_names:
                    yield self.finding(
                        ctx,
                        ladder_fn,
                        f"degradation ladder no longer registers the "
                        f"{rung!r} fallback rung — a deadline-blown or "
                        "faulted solve must be able to degrade through "
                        "every registered rung (update "
                        "REQUIRED_LADDER_RUNGS alongside a deliberate "
                        "removal)",
                    )
