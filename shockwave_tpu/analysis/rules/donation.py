"""Rule: donation-after-use.

``jax.jit(..., donate_argnums=...)`` invalidates the donated argument
buffers at every call — the caller's reference still points at freed
device memory, and reading it "works" on CPU test runs while silently
corrupting state on TPU (the exact hazard the donated ``jit_step`` in
``models/train.py`` documents). This rule tracks callables built with
``donate_argnums`` and flags any read of a donated argument name after
the call site without an interposing rebind.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Set, Tuple

from shockwave_tpu.analysis.core import (
    FileContext,
    Finding,
    Rule,
    dotted_name,
    iter_scopes,
    node_pos,
    walk_scope,
)


def _donate_argnums_literal(call: ast.Call) -> Optional[Tuple[int, ...]]:
    """The donate_argnums keyword as a tuple of ints, or None when the
    call has no such keyword. Non-literal values -> empty tuple meaning
    "donates, indices unknown" (treat every positional arg as donated).
    """
    for kw in call.keywords:
        if kw.arg != "donate_argnums":
            continue
        value = kw.value
        if isinstance(value, ast.Constant) and isinstance(value.value, int):
            return (value.value,)
        if isinstance(value, (ast.Tuple, ast.List)):
            nums = []
            for elt in value.elts:
                if isinstance(elt, ast.Constant) and isinstance(elt.value, int):
                    nums.append(elt.value)
                else:
                    return ()
            return tuple(nums)
        return ()
    return None


def _is_jit_call(call: ast.Call) -> bool:
    name = dotted_name(call.func)
    return name.split(".")[-1] == "jit"


def collect_donated_callables(scope: ast.AST) -> Dict[str, Tuple[int, ...]]:
    """name -> donated positional indices for callables bound in scope.

    Two binding forms: ``f = jax.jit(fn, donate_argnums=...)`` and a
    function decorated ``@functools.partial(jax.jit, donate_argnums=...)``
    or ``@jax.jit(donate_argnums=...)`` (decorator position shifts the
    visible signature by zero, so indices carry over unchanged).
    """
    donated: Dict[str, Tuple[int, ...]] = {}
    for node in walk_scope(scope):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            call = node.value
            if _is_jit_call(call):
                nums = _donate_argnums_literal(call)
                if nums is not None:
                    for target in node.targets:
                        if isinstance(target, ast.Name):
                            donated[target.id] = nums
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                if not isinstance(dec, ast.Call):
                    continue
                inner_names = [dotted_name(a) for a in dec.args]
                is_partial_jit = dotted_name(dec.func).split(".")[
                    -1
                ] == "partial" and any(
                    n.split(".")[-1] == "jit" for n in inner_names
                )
                if is_partial_jit or _is_jit_call(dec):
                    nums = _donate_argnums_literal(dec)
                    if nums is not None:
                        donated[node.name] = nums
    return donated


def _rebound_names(stmt: ast.AST) -> Set[str]:
    """Names the statement itself rebinds (assignment targets)."""
    names: Set[str] = set()
    targets: List[ast.AST] = []
    if isinstance(stmt, ast.Assign):
        targets = list(stmt.targets)
    elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
        targets = [stmt.target]
    for target in targets:
        for node in ast.walk(target):
            if isinstance(node, ast.Name):
                names.add(node.id)
    return names


def _enclosing_stmt(ctx: FileContext, node: ast.AST) -> ast.AST:
    cur = node
    for anc in ctx.ancestors(node):
        if isinstance(anc, (ast.stmt,)):
            return anc
        cur = anc
    return cur


def _enclosing_loop(ctx: FileContext, node: ast.AST, scope) -> Optional[ast.AST]:
    for anc in ctx.ancestors(node):
        if anc is scope:
            return None
        if isinstance(anc, (ast.For, ast.While, ast.AsyncFor)):
            return anc
    return None


class DonationAfterUse(Rule):
    name = "donation-after-use"
    description = (
        "argument buffer donated to a jit-compiled call is read after "
        "the call site without being rebound"
    )
    rationale = (
        "donated device buffers are freed by XLA at the call; a later "
        "read aliases dead memory and corrupts training state silently "
        "on TPU"
    )

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for scope in iter_scopes(ctx.tree):
            donated = collect_donated_callables(scope)
            if not donated:
                continue
            # All Name events in this scope, ordered by position.
            events = [
                n
                for n in walk_scope(scope)
                if isinstance(n, ast.Name)
            ]
            events.sort(key=node_pos)
            for node in walk_scope(scope):
                if not (
                    isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Name)
                    and node.func.id in donated
                ):
                    continue
                nums = donated[node.func.id]
                if nums == ():
                    nums = tuple(range(len(node.args)))
                stmt = _enclosing_stmt(ctx, node)
                rebound = _rebound_names(stmt)
                call_pos = node_pos(node)
                loop = _enclosing_loop(ctx, node, scope)
                for idx in nums:
                    if idx >= len(node.args):
                        continue
                    arg = node.args[idx]
                    if not isinstance(arg, ast.Name):
                        continue
                    if arg.id in rebound:
                        # `v, o, loss = jit_step(v, o, batch)` — the
                        # call's own targets replace the donated
                        # binding, the canonical safe idiom.
                        continue
                    hit = self._first_bad_use(
                        ctx, events, arg.id, call_pos, node, loop
                    )
                    if hit is not None:
                        yield self.finding(
                            ctx,
                            hit,
                            f"'{arg.id}' is donated to '{node.func.id}' "
                            f"(donate_argnums includes {idx}) at line "
                            f"{node.lineno} and read afterwards; the "
                            "donated buffer is invalid after the call "
                            "— rebind it from the call's results or "
                            "copy before donating",
                        )

    def _first_bad_use(
        self,
        ctx: FileContext,
        events: List[ast.Name],
        name: str,
        call_pos,
        call_node: ast.Call,
        loop: Optional[ast.AST],
    ) -> Optional[ast.Name]:
        """Earliest Load of ``name`` after the call (before any Store).

        When the call sits in a loop and the loop body never rebinds the
        name, loads lexically before the call are reads of the dead
        buffer on iteration 2+ and count as well.
        """
        # The call's own argument occurrences sit positionally after the
        # Call node itself — they are the donation, not a use-after.
        in_call = {id(n) for n in ast.walk(call_node)}
        after = [
            e
            for e in events
            if node_pos(e) > call_pos
            and e.id == name
            and id(e) not in in_call
        ]
        for event in after:
            if isinstance(event.ctx, ast.Store):
                return None
            if isinstance(event.ctx, ast.Load):
                return event
        if loop is not None:
            loop_events = [
                e
                for e in ast.walk(loop)
                if isinstance(e, ast.Name) and e.id == name
            ]
            if any(isinstance(e.ctx, ast.Store) for e in loop_events):
                return None
            loads = [
                e
                for e in loop_events
                if isinstance(e.ctx, ast.Load) and e is not None
            ]
            # Exclude the donated argument occurrence itself.
            loads = [
                e
                for e in loads
                if node_pos(e) < call_pos
            ]
            if loads:
                return loads[0]
        return None
