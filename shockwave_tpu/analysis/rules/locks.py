"""Rule: lock-discipline.

The metrics registry, tracer, recorder, watchdog, and dispatcher are
mutated from gRPC handler threads, the round loop, and worker monitor
threads at once; every one of them guards shared state with a
``self._lock``. A mutation added outside the ``with self._lock:`` block
is a data race that only manifests under production thread
interleavings. Scoped to ``obs/`` and ``runtime/``, the two packages
with threaded callers.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterator, List, Set

from shockwave_tpu.analysis.core import (
    FileContext,
    Finding,
    Rule,
    dotted_name,
)

_SCOPE_PREFIXES = (
    "shockwave_tpu/obs/",
    "shockwave_tpu/runtime/",
    # The HA control plane: journal appends, lease renewals, and
    # front-door servers run on RPC handler threads, the renewal
    # daemon, and the round loop at once.
    "shockwave_tpu/ha/",
)

# Individual modules outside the threaded packages that the ExplainJob
# RPC path reads from handler threads while the round loop writes.
_SCOPE_FILES = ("shockwave_tpu/solver/duals.py",)

_MUTATING_METHODS = {
    "append",
    "extend",
    "insert",
    "add",
    "discard",
    "remove",
    "pop",
    "popleft",
    "popitem",
    "appendleft",
    "update",
    "setdefault",
    "clear",
    "sort",
}

# Methods that establish state rather than mutate shared state.
_EXEMPT_METHODS = {"__init__", "__new__", "__init_subclass__"}

# A helper invoked only while the public entry point already holds the
# lock declares the contract in its docstring (the repo's existing
# convention, e.g. EventTracer._track) or via a `_locked` name suffix;
# the declaration keeps the contract greppable and review-visible.
_CALLER_HOLDS_LOCK_RE = re.compile(
    r"caller[s]?\s+(must\s+)?(hold[s]?|holding)\b[^.]*\block", re.IGNORECASE
)


def _declares_caller_holds_lock(method: ast.AST) -> bool:
    if method.name.endswith("_locked"):
        return True
    doc = ast.get_docstring(method) or ""
    return bool(_CALLER_HOLDS_LOCK_RE.search(doc))


def _lock_attrs_of_class(cls: ast.ClassDef) -> Set[str]:
    """Attributes assigned ``threading.Lock()``/``RLock()`` — or the
    sanitizer factories ``make_lock``/``make_rlock``/``make_condition``
    (:mod:`shockwave_tpu.analysis.sanitize`), which production classes
    use so ``SHOCKWAVE_SANITIZE=locks`` can instrument them — anywhere
    in the class (typically __init__)."""
    locks: Set[str] = set()
    for node in ast.walk(cls):
        if not isinstance(node, ast.Assign):
            continue
        if not isinstance(node.value, ast.Call):
            continue
        leaf = dotted_name(node.value.func).split(".")[-1]
        if leaf not in (
            "Lock", "RLock", "Condition",
            "make_lock", "make_rlock", "make_condition",
        ):
            continue
        for target in node.targets:
            if (
                isinstance(target, ast.Attribute)
                and isinstance(target.value, ast.Name)
                and target.value.id == "self"
            ):
                locks.add(target.attr)
    return locks


def _with_holds_lock(stmt: ast.With, lock_attrs: Set[str]) -> bool:
    """True when any context manager expression references a lock attr
    (``self._lock`` or another object's ``._lock`` — cross-object
    locking like ``with registry._lock:`` in the metric handles is the
    documented idiom)."""
    for item in stmt.items:
        for node in ast.walk(item.context_expr):
            if isinstance(node, ast.Attribute) and (
                node.attr in lock_attrs or "lock" in node.attr.lower()
            ):
                return True
    return False


class LockDiscipline(Rule):
    name = "lock-discipline"
    description = (
        "mutation of self.<attr> shared state in a lock-owning class "
        "outside a `with self._lock` block"
    )
    rationale = (
        "obs/ and runtime/ objects are mutated concurrently from RPC "
        "handler threads and the round loop; an unguarded write is a "
        "race that only fails under production interleavings"
    )

    def applies_to(self, relpath: str) -> bool:
        return (
            relpath.startswith(_SCOPE_PREFIXES)
            or relpath in _SCOPE_FILES
        )

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for cls in ast.walk(ctx.tree):
            if not isinstance(cls, ast.ClassDef):
                continue
            lock_attrs = _lock_attrs_of_class(cls)
            if not lock_attrs:
                continue
            shared = self._shared_attrs(cls, lock_attrs)
            for method in cls.body:
                if not isinstance(
                    method, (ast.FunctionDef, ast.AsyncFunctionDef)
                ):
                    continue
                if method.name in _EXEMPT_METHODS:
                    continue
                if _declares_caller_holds_lock(method):
                    continue
                yield from self._check_method(
                    ctx, cls, method, lock_attrs, shared
                )

    def _shared_attrs(
        self, cls: ast.ClassDef, lock_attrs: Set[str]
    ) -> Set[str]:
        """self attributes initialized in __init__ — the state the lock
        exists to guard. Attributes only ever set elsewhere are treated
        as method-local caches and left to review."""
        shared: Set[str] = set()
        for method in cls.body:
            if (
                isinstance(method, (ast.FunctionDef, ast.AsyncFunctionDef))
                and method.name == "__init__"
            ):
                for node in ast.walk(method):
                    if isinstance(node, (ast.Assign, ast.AnnAssign)):
                        targets = (
                            node.targets
                            if isinstance(node, ast.Assign)
                            else [node.target]
                        )
                        for target in targets:
                            if (
                                isinstance(target, ast.Attribute)
                                and isinstance(target.value, ast.Name)
                                and target.value.id == "self"
                            ):
                                shared.add(target.attr)
        return shared - lock_attrs

    def _check_method(
        self,
        ctx: FileContext,
        cls: ast.ClassDef,
        method: ast.AST,
        lock_attrs: Set[str],
        shared: Set[str],
    ):
        # DFS carrying the "lock held" flag through with-blocks.
        def visit(node: ast.AST, locked: bool):
            if isinstance(node, ast.With):
                locked = locked or _with_holds_lock(node, lock_attrs)
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if node is not method:
                    # Nested defs run when called; their lock context is
                    # the caller's, which we cannot see — skip.
                    return []
            out = []
            if not locked:
                out.extend(self._mutations(node, shared))
            for child in ast.iter_child_nodes(node):
                out.extend(visit(child, locked))
            return out

        for mut_node, attr, how in visit(method, False):
            yield self.finding(
                ctx,
                mut_node,
                f"{cls.name}.{method.name} {how} 'self.{attr}' outside "
                f"`with self.{sorted(lock_attrs)[0]}` — shared state in "
                "a lock-owning class must be mutated under the lock",
            )

    def _mutations(self, node: ast.AST, shared: Set[str]):
        """Mutations *directly at* this node (children are handled by
        the recursive visit so the locked flag stays accurate)."""
        out = []
        if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            targets = (
                node.targets
                if isinstance(node, ast.Assign)
                else [node.target]
            )
            for target in targets:
                attr = self._self_attr_target(target)
                if attr and attr in shared:
                    verb = (
                        "augments"
                        if isinstance(node, ast.AugAssign)
                        else "assigns"
                    )
                    out.append((node, attr, verb))
        elif isinstance(node, ast.Call):
            func = node.func
            if (
                isinstance(func, ast.Attribute)
                and func.attr in _MUTATING_METHODS
                and isinstance(func.value, ast.Attribute)
                and isinstance(func.value.value, ast.Name)
                and func.value.value.id == "self"
                and func.value.attr in shared
            ):
                out.append(
                    (node, func.value.attr, f"calls .{func.attr}() on")
                )
        return out

    def _self_attr_target(self, target: ast.AST):
        """'attr' when target writes self.attr or self.attr[...]"""
        if isinstance(target, ast.Subscript):
            target = target.value
        if (
            isinstance(target, ast.Attribute)
            and isinstance(target.value, ast.Name)
            and target.value.id == "self"
        ):
            return target.attr
        return None
