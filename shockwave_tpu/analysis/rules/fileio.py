"""Rule: non-atomic-artifact-write.

A run killed mid-write (preemption, ctrl-C between rounds, OOM) leaves
a truncated JSON/JSONL artifact that poisons downstream analysis
silently — the exact failure ``utils/fileio`` exists to prevent with
temp-file+rename. Every text-mode truncating ``open(..., "w")`` outside
that module is either an artifact write that must go through
``atomic_write_text``/``atomic_write_json``/``atomic_append_text``, or
a justified exception (a live subprocess stdout sink) that carries an
inline suppression explaining itself.
"""

from __future__ import annotations

import ast
from typing import Iterator

from shockwave_tpu.analysis.core import FileContext, Finding, Rule

_EXEMPT_FILES = (
    "shockwave_tpu/utils/fileio.py",
)

_TRUNCATING_TEXT_MODES = {"w", "wt", "tw", "w+", "wt+"}


class NonAtomicArtifactWrite(Rule):
    name = "non-atomic-artifact-write"
    description = (
        'raw truncating open(..., "w") instead of the atomic '
        "utils/fileio helpers"
    )
    rationale = (
        "a crash mid-write leaves a truncated artifact that every "
        "downstream reader mis-parses silently; temp+rename is atomic"
    )

    def applies_to(self, relpath: str) -> bool:
        return relpath not in _EXEMPT_FILES and not relpath.startswith(
            "tests/"
        )

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            if not (
                isinstance(node.func, ast.Name) and node.func.id == "open"
            ):
                continue
            mode = self._mode_of(node)
            if mode in _TRUNCATING_TEXT_MODES:
                yield self.finding(
                    ctx,
                    node,
                    f'open(..., "{mode}") truncates in place; use '
                    "shockwave_tpu.utils.fileio.atomic_write_text / "
                    "atomic_write_json (or atomic_append_text for "
                    "grow-only logs) so a crash cannot leave a torn "
                    "artifact",
                )

    def _mode_of(self, call: ast.Call):
        mode_node = None
        if len(call.args) >= 2:
            mode_node = call.args[1]
        for kw in call.keywords:
            if kw.arg == "mode":
                mode_node = kw.value
        if isinstance(mode_node, ast.Constant) and isinstance(
            mode_node.value, str
        ):
            return mode_node.value
        return None
