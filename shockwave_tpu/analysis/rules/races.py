"""Thread-topology race rules: shared-state races and snapshot escapes.

PR 11 made the control plane genuinely concurrent: a daemon speculation
thread clones and solves against planner state while the round loop,
the heartbeat reaper, the gRPC handlers (scheduler- and worker-side
servicers), the watchdog tick, and the admission drain all mutate
overlapping structures. These two rules turn the thread-safety story
from convention into proof, on top of the thread-root discovery and
per-function effect summaries in :mod:`shockwave_tpu.analysis.project`.

* **shared-state-race** — for every (object family, field) pair
  reachable from two thread roots (or from one root that can race
  itself — a per-event daemon thread, a gRPC handler on a thread
  pool), with at least one WRITE, where the lock sets *guaranteed*
  held (the meet over all call paths from each root) are disjoint:
  flag it, printing the two witness call chains. The write model is
  GIL-aware: a plain attribute load and a plain rebind of a fresh
  value are atomic in CPython and pair benignly; what races is an
  in-place container mutation (``self._m[k] = v``, ``.append``,
  ``del``) against any access, and a read-modify-write
  (``self.n += 1``, ``self.f = g(self.f)``) against anything.
  Scope: classes that own a lock (declaring, by construction, that
  they are touched from multiple threads) and module globals in
  modules that own a module-level lock. A class with no lock is
  single-thread-confined by convention — its cross-thread story is
  the snapshot-escape contract below, not lock discipline.

* **snapshot-escape** — verifies ``clone_planner``'s deep-copy
  contract. The speculation clone shares the process with the live
  planner; ``state_dict()`` is deliberately shallow where it can
  afford to be, and ``_MUTABLE_MD_FIELDS`` names exactly the per-job
  metadata structures both sides mutate in place. The rule computes,
  from the effect summaries, every field of the metadata classes (and
  every planner field passed by bare reference through
  ``state_dict``/``from_state``) that is mutated IN PLACE anywhere in
  the project, and flags any such field the copy contract does not
  cover — aliased mutable state that the live planner and the
  speculative clone would both write. Guarded until this PR only by a
  code comment.

Dynamic counterpart: ``SHOCKWAVE_SANITIZE=threads``
(:mod:`shockwave_tpu.analysis.sanitize`) instruments the same
lock-owning classes at runtime and raises on an observed
unsynchronized cross-thread write pair.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Set, Tuple

from shockwave_tpu.analysis.core import Finding, ProjectRule, dotted_name
from shockwave_tpu.analysis.project import (
    MUTATE,
    Project,
    READ,
    WRITE_KINDS,
)
from shockwave_tpu.analysis.rules.interproc import _project_finding


def _site(project: Project, access) -> str:
    fn = project.functions[access.fn]
    return f"{fn.module.relpath}:{getattr(access.node, 'lineno', 0)}"


def _chain_str(project: Project, root, access, guaranteed) -> str:
    chain = project.call_chain(root.qname, access.fn)
    if not chain:
        chain = [root.qname, "...", access.fn]
    held = sorted(guaranteed) or ["no locks"]
    return (
        f"[{root.kind}] "
        + " -> ".join(project.short(q) for q in chain)
        + f" {access.kind}s at {_site(project, access)}"
        + f" holding {{{', '.join(held)}}}"
    )


class SharedStateRace(ProjectRule):
    name = "shared-state-race"
    description = (
        "a field reachable from two thread roots with at least one "
        "write where the guaranteed-held lock sets are disjoint"
    )
    rationale = (
        "the speculation thread, round loop, heartbeat reaper, RPC "
        "handlers, watchdog, and admission drain overlap on the same "
        "objects; an unlocked write pair only corrupts state under "
        "production interleavings, never in single-threaded tests"
    )

    def analyze(self, project: Project) -> List[dict]:
        """The raw race table (also behind the CLI's ``--thread-roots``
        evidence dump): one entry per racy (owner, field) pair with
        both witnesses. Memoized on the project so the rule run and the
        evidence dump share one analysis."""
        return project.cached(
            "race_table", lambda: self._analyze(project)
        )

    def _analyze(self, project: Project) -> List[dict]:
        roots = project.thread_roots()
        if not roots:
            return []
        effects = project.function_effects()

        # Owners in scope: lock-owning class families + module globals
        # of modules owning a module-level lock.
        allowed: Set[str] = set()
        for qn in project.classes:
            family = project.class_family(qn)
            if project.family_owns_lock(family):
                allowed.add(project.short(family))
        for mod in project.modules.values():
            if mod.module_locks:
                allowed.add(project.short(mod.modname))

        # (owner, attr) -> [(root, access, guaranteed-held)]
        table: Dict[Tuple[str, str], list] = {}
        for root in roots:
            held = project.guaranteed_held(root)
            for qn, entry_locks in held.items():
                eff = effects.get(qn)
                if eff is None:
                    continue
                for access in eff.accesses:
                    if access.in_ctor or access.owner not in allowed:
                        continue
                    guaranteed = entry_locks | access.locks
                    table.setdefault(
                        (access.owner, access.attr), []
                    ).append((root, access, guaranteed))

        races: List[dict] = []
        for (owner, attr), entries in sorted(table.items()):
            pair = self._find_race_pair(entries)
            if pair is None:
                continue
            (root_w, acc_w, held_w), (root_o, acc_o, held_o) = pair
            write_fn = project.functions[acc_w.fn]
            races.append(
                {
                    "owner": owner,
                    "field": attr,
                    # An inline-justified suppression at the write site
                    # keeps the pair in this evidence table but out of
                    # the findings (the comment is the review trail).
                    "suppressed": project.is_suppressed(
                        write_fn.module.relpath,
                        getattr(acc_w.node, "lineno", 0),
                        SharedStateRace.name,
                    ),
                    "write": {
                        "root": root_w.qname,
                        "kind": acc_w.kind,
                        "site": _site(project, acc_w),
                        "locks": sorted(held_w),
                        "witness": _chain_str(
                            project, root_w, acc_w, held_w
                        ),
                    },
                    "other": {
                        "root": root_o.qname,
                        "kind": acc_o.kind,
                        "site": _site(project, acc_o),
                        "locks": sorted(held_o),
                        "witness": _chain_str(
                            project, root_o, acc_o, held_o
                        ),
                    },
                    "_access": acc_w,
                }
            )
        return races

    @staticmethod
    def _find_race_pair(entries) -> Optional[tuple]:
        """The most severe racing pair among one field's accesses, or
        None. Severity order: write/write beats write/read; distinct
        roots beat a multi root racing itself."""
        best = None
        best_rank = -1
        for i, (r1, a1, g1) in enumerate(entries):
            if a1.kind not in WRITE_KINDS:
                continue
            for j, (r2, a2, g2) in enumerate(entries):
                if i == j and not r1.multi:
                    continue
                if r1.qname == r2.qname and not r1.multi:
                    continue
                if a2.kind == READ and a2.fn == a1.fn:
                    # A read in the same function as the write is the
                    # write's own operand scan, not a second thread's
                    # view — require the read elsewhere (the write
                    # itself still pairs with writes anywhere).
                    continue
                if g1 & g2:
                    continue
                rank = (2 if a2.kind in WRITE_KINDS else 1) * 2 + (
                    1 if r1.qname != r2.qname else 0
                )
                if rank > best_rank:
                    best_rank = rank
                    best = ((r1, a1, g1), (r2, a2, g2))
        return best

    def check_project(self, project: Project) -> Iterator[Finding]:
        for race in self.analyze(project):
            access = race["_access"]
            fn = project.functions[access.fn]
            yield _project_finding(
                self, project, fn, access.node,
                f"unsynchronized cross-thread access to "
                f"{race['owner']}.{race['field']}: "
                f"{race['write']['witness']}; but "
                f"{race['other']['witness']} — guaranteed-held lock "
                "sets are disjoint, so these interleave",
            )


# -- snapshot-escape ----------------------------------------------------


class SnapshotEscape(ProjectRule):
    name = "snapshot-escape"
    description = (
        "a structure mutated in place by the live planner or the "
        "speculative clone that clone_planner's deep-copy contract "
        "does not cover (aliased mutable state)"
    )
    rationale = (
        "the speculation clone shares the process with the live "
        "planner; state_dict() is shallow by design and "
        "_MUTABLE_MD_FIELDS names exactly what both sides mutate — a "
        "field that joins the mutating set without joining the copied "
        "set corrupts the live planner from the clone's thread"
    )

    def check_project(self, project: Project) -> Iterator[Finding]:
        clone_fn = next(
            (
                fn
                for qn, fn in sorted(project.functions.items())
                if fn.name == "clone_planner" and fn.cls is None
            ),
            None,
        )
        if clone_fn is None:
            return
        copied = self._copied_fields(clone_fn.module)
        effects = project.function_effects()

        # In-place mutated fields per class FAMILY (effect owners are
        # family-rooted): family short name -> attr -> first witness.
        mutated: Dict[str, Dict[str, object]] = {}
        for qn, eff in effects.items():
            for access in eff.accesses:
                if access.kind != MUTATE or access.in_ctor:
                    continue
                mutated.setdefault(access.owner, {}).setdefault(
                    access.attr, access
                )

        spec_entry = next(
            (
                qn
                for qn, fn in sorted(project.functions.items())
                if fn.name == "run_speculation" and fn.cls is None
            ),
            clone_fn.qname,
        )

        # (a) Metadata classes: everything stored into a planner's
        # job_metadata mapping is snapshotted via the shallow
        # dict(self.__dict__) path; every in-place-mutated field must
        # be in the copied set.
        for cls_qname in self._metadata_classes(project):
            family = project.short(project.class_family(cls_qname))
            for attr, access in sorted(
                mutated.get(family, {}).items()
            ):
                if attr in copied:
                    continue
                yield self._escape_finding(
                    project, access, spec_entry,
                    f"{family}.{attr} is mutated in place here but "
                    f"clone_planner's copied set (_MUTABLE_MD_FIELDS = "
                    f"{sorted(copied)}) does not deep-copy it — the "
                    "live planner and the speculative clone alias it, "
                    "so a post-snapshot mutation on either side "
                    "corrupts the other",
                )

        # (b) Planner classes: a state_dict entry that passes a field
        # by bare reference (no copying wrapper) aliases it into the
        # clone; if that field is mutated in place and from_state does
        # not re-copy it, it escapes.
        for cls_qname in self._planner_classes(project):
            cls = project.classes[cls_qname]
            state_fn = cls.methods.get("state_dict")
            if state_fn is None:
                continue
            family = project.short(project.class_family(cls_qname))
            bare = self._bare_state_fields(state_fn)
            if "*" in bare:
                # dict(self.__dict__): every in-place-mutated field of
                # the family passes through the snapshot by reference.
                bare = (bare - {"*"}) | set(mutated.get(family, {}))
            recopied = self._from_state_copies(cls)
            for attr in sorted(bare):
                access = mutated.get(family, {}).get(attr)
                if access is None or attr in recopied or attr in copied:
                    continue
                yield self._escape_finding(
                    project, access, spec_entry,
                    f"{family}.{attr} passes through state_dict by "
                    "bare reference and is mutated in place here — "
                    "the snapshot aliases it between the live planner "
                    "and the speculative clone",
                )

    def _escape_finding(self, project, access, spec_entry, message):
        fn = project.functions[access.fn]
        chain = project.call_chain(spec_entry, access.fn)
        if chain:
            message += (
                "; clone-side witness: "
                + " -> ".join(project.short(q) for q in chain)
            )
        return _project_finding(self, project, fn, access.node, message)

    @staticmethod
    def _copied_fields(mod) -> Set[str]:
        for stmt in mod.tree.body:
            if (
                isinstance(stmt, ast.Assign)
                and len(stmt.targets) == 1
                and isinstance(stmt.targets[0], ast.Name)
                and stmt.targets[0].id == "_MUTABLE_MD_FIELDS"
            ):
                try:
                    value = ast.literal_eval(stmt.value)
                except ValueError:
                    return set()
                return {str(v) for v in value}
        return set()

    def _metadata_classes(self, project: Project) -> List[str]:
        """Classes whose instances are stored into a ``job_metadata``
        mapping (``self.job_metadata[job_id] = md``) — the values the
        snapshot copies via their shallow ``state_dict``."""
        out: Set[str] = set()
        for fn in project.functions.values():
            if fn.cls is None:
                continue
            local_types = project._local_types(fn)
            for node in ast.walk(fn.node):
                if not isinstance(node, ast.Assign):
                    continue
                for target in node.targets:
                    if not (
                        isinstance(target, ast.Subscript)
                        and project._self_attr(fn, target.value)
                        == "job_metadata"
                    ):
                        continue
                    value = node.value
                    if isinstance(value, ast.Name):
                        if value.id in local_types:
                            out.add(local_types[value.id])
                    elif isinstance(value, ast.Call):
                        resolved = project._resolve_class_name(
                            fn.module, dotted_name(value.func)
                        )
                        if resolved:
                            out.add(resolved)
        return sorted(out)

    @staticmethod
    def _planner_classes(project: Project) -> List[str]:
        """The speculation-wired planner kinds: classes defining the
        ``_spec_solve_base`` reconcile hook."""
        return sorted(
            qn
            for qn, cls in project.classes.items()
            if "_spec_solve_base" in cls.methods
        )

    @staticmethod
    def _bare_state_fields(state_fn) -> Set[str]:
        """Fields returned from state_dict as bare ``self.attr`` values
        (no copying wrapper). ``return dict(self.__dict__)`` — the
        JobMetadata idiom, a shallow copy of EVERY field — yields the
        ``"*"`` sentinel, which the caller expands to all in-place-
        mutated fields of the class family."""
        bare: Set[str] = set()
        for node in ast.walk(state_fn.node):
            if not isinstance(node, ast.Return) or node.value is None:
                continue
            if isinstance(node.value, ast.Dict):
                for value in node.value.values:
                    if (
                        isinstance(value, ast.Attribute)
                        and isinstance(value.value, ast.Name)
                        and value.value.id == "self"
                    ):
                        bare.add(value.attr)
            elif isinstance(node.value, ast.Call):
                call = node.value
                if (
                    dotted_name(call.func) == "dict"
                    and call.args
                    and dotted_name(call.args[0]) == "self.__dict__"
                ):
                    bare.add("*")
        return bare

    @staticmethod
    def _from_state_copies(cls) -> Set[str]:
        """Attrs that ``from_state`` re-wraps in a fresh container
        (``planner.x = dict(state[...])``) — copied at restore time, so
        a bare state_dict reference does not alias."""
        from_fn = cls.methods.get("from_state")
        if from_fn is None:
            return set()
        out: Set[str] = set()
        for node in ast.walk(from_fn.node):
            if not isinstance(node, ast.Assign):
                continue
            for target in node.targets:
                if isinstance(target, ast.Attribute) and isinstance(
                    node.value, ast.Call
                ):
                    out.add(target.attr)
        return out


def thread_roots_dict(project: Optional[Project] = None) -> dict:
    """JSON-ready dump of the discovered thread topology and the race
    table — ``python -m shockwave_tpu.analysis --thread-roots`` and the
    committed sweep evidence."""
    project = project or Project.build()
    return {
        "roots": [r.to_dict() for r in project.thread_roots()],
        # Copies, minus the witness AST handle: the table is memoized
        # on the Project and check_project still needs "_access".
        "races": [
            {k: v for k, v in race.items() if k != "_access"}
            for race in SharedStateRace().analyze(project)
        ],
    }
