"""Interprocedural rules: lock-order cycles, transitive host syncs,
swallowed exceptions.

These are the hazards PR 5's per-file rules structurally cannot see:
every one of them needs the project symbol table / call graph
(:mod:`shockwave_tpu.analysis.project`) because the two halves of the
bug live in different functions — usually different files.

* **lock-order-cycle** — the dispatcher, scheduler, and every obs plane
  guard state with their own lock and call into each other (metrics
  increments under the dispatcher lock, registry snapshots under the
  watchdog lock). Each "acquires lock B while holding lock A" pair —
  observed directly as nested ``with`` blocks or transitively through
  any resolvable call chain — is an edge in a global lock graph; a
  cycle means two production threads can deadlock. Reacquiring a
  non-reentrant ``Lock`` through a call chain is reported too: that one
  deadlocks a single thread, deterministically.

* **transitive-host-sync** — the per-file host-sync rule only sees a
  ``.item()`` lexically inside the hot loop. This rule follows calls
  *out of* the hot region (lax-traced bodies, jit-step driving loops)
  across files and flags any reachable ``.item()`` /
  ``block_until_ready`` / ``device_get`` / ``np.asarray`` — the silent
  per-iteration device round-trips that ROADMAP's replanning-under-
  churn and plan-ahead pipelining items cannot afford.

* **swallowed-exception** — the gRPC/retry paths must never eat an
  error invisibly: a handler that neither re-raises, logs through the
  project logger, nor increments an error counter turns a dead worker
  into an infinite hang. Helpers the handler delegates to are followed
  through the call graph before flagging.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterator, List, Optional, Set, Tuple

from shockwave_tpu.analysis.core import (
    Finding,
    ProjectRule,
    dotted_name,
)
from shockwave_tpu.analysis.project import (
    FunctionInfo,
    Project,
    unwrap_call,
)


def _project_finding(
    rule, project: Project, fn: FunctionInfo, node, message: str
) -> Finding:
    line = getattr(node, "lineno", 1)
    col = getattr(node, "col_offset", 0)
    mod = fn.module
    text = ""
    if 1 <= line <= len(mod.lines):
        text = mod.lines[line - 1].strip()
    return Finding(
        rule=rule.name,
        path=mod.relpath,
        line=line,
        col=col,
        message=message,
        line_text=text,
        suppressed=project.is_suppressed(mod.relpath, line, rule.name),
    )


# -- lock-order-cycle ---------------------------------------------------

_REENTRANT_FACTORIES = {"RLock", "make_rlock"}


class LockOrderCycle(ProjectRule):
    name = "lock-order-cycle"
    description = (
        "two locks acquired in opposite orders on different call paths "
        "(potential deadlock), or a non-reentrant lock reacquired "
        "through a call chain"
    )
    rationale = (
        "obs/ and runtime/ objects lock independently and call into "
        "each other from RPC handler threads, the round loop, and "
        "monitor threads; an AB/BA inversion only deadlocks under "
        "production interleavings, never in single-threaded tests"
    )

    def graph(self, project: Project):
        """The full held-before graph: ``(edges, self_deadlocks)`` where
        ``edges`` maps ``(held, acquired)`` lock pairs to the first
        witness ``(fn, site, chain)``. The CLI's ``--lock-graph`` dump
        and the committed sweep evidence both come from here. Memoized
        on the project so the rule run and the dump share one build."""
        return project.cached(
            "lock_order_graph", lambda: self._graph(project)
        )

    def _graph(self, project: Project):
        reach = project.transitive_acquires()
        reentrant = self._reentrant_locks(project)
        edges: Dict[Tuple[str, str], tuple] = {}
        self_deadlocks: List[tuple] = []
        for fn in project.functions.values():
            self._walk(
                project, fn, fn.node, (), reach, reentrant, edges,
                self_deadlocks,
            )
        return edges, self_deadlocks

    def check_project(self, project: Project) -> Iterator[Finding]:
        edges, self_deadlocks = self.graph(project)

        for fn, site, lock, chain in self_deadlocks:
            yield _project_finding(
                self, project, fn, site,
                f"non-reentrant lock {lock} reacquired while already "
                f"held (self-deadlock): {' -> '.join(chain)}",
            )

        # Cycle detection over the held-before graph.
        graph: Dict[str, Set[str]] = {}
        for a, b in edges:
            graph.setdefault(a, set()).add(b)
        reported: Set[frozenset] = set()
        for (a, b), (fn, site, chain) in sorted(
            edges.items(), key=lambda kv: (kv[1][0].module.relpath,
                                           getattr(kv[1][1], "lineno", 0))
        ):
            if a == b:
                continue
            path_back = self._path(graph, b, a)
            if path_back is None:
                continue
            cycle = frozenset([a, b])
            if cycle in reported:
                continue
            reported.add(cycle)
            back = " -> ".join(path_back)
            yield _project_finding(
                self, project, fn, site,
                f"lock-order cycle: {a} held while acquiring {b} here "
                f"(via {' -> '.join(chain)}), but elsewhere {back} — "
                "opposite acquisition orders can deadlock",
            )

    # -- helpers ---------------------------------------------------------
    def _reentrant_locks(self, project: Project) -> Set[str]:
        """Lock nodes backed by RLock (reacquisition is legal)."""
        short = lambda qn: (
            qn[len(project.package) + 1:]
            if qn.startswith(project.package + ".")
            else qn
        )
        out: Set[str] = set()
        for cls in project.classes.values():
            for sub in ast.walk(cls.node):
                if not isinstance(sub, ast.Assign) or not isinstance(
                    sub.value, ast.Call
                ):
                    continue
                leaf = dotted_name(sub.value.func).split(".")[-1]
                if leaf not in _REENTRANT_FACTORIES:
                    continue
                for target in sub.targets:
                    if (
                        isinstance(target, ast.Attribute)
                        and isinstance(target.value, ast.Name)
                        and target.value.id == "self"
                    ):
                        out.add(f"{short(cls.qname)}.{target.attr}")
        # Condition() with no explicit lock wraps an RLock.
        for cls in project.classes.values():
            for attr, alias_of in cls.lock_aliases.items():
                lock = f"{short(cls.qname)}.{alias_of}"
                if lock in out:
                    out.add(f"{short(cls.qname)}.{attr}")
        return out

    def _walk(
        self, project, fn, node, held, reach, reentrant, edges,
        self_deadlocks,
    ):
        if isinstance(node, (ast.With, ast.AsyncWith)):
            acquired = []
            for item in node.items:
                lock = project.lock_node(fn, item.context_expr)
                if lock:
                    acquired.append(lock)
            for lock in acquired:
                for holder in held:
                    if holder == lock:
                        if lock not in reentrant:
                            self_deadlocks.append(
                                (fn, node, lock, (fn.qname,))
                            )
                        continue
                    edges.setdefault(
                        (holder, lock), (fn, node, (fn.qname,))
                    )
                held = held + (lock,)
        elif isinstance(node, ast.Call):
            callee_qn = None
            for call_node, qn in fn.calls:
                if call_node is node:
                    callee_qn = qn
                    break
            if callee_qn is not None and held:
                for target in reach.get(callee_qn, set()):
                    chain = tuple(
                        project.witness_chain(
                            callee_qn,
                            lambda q: target
                            in {
                                lock
                                for _, lock in project.direct_acquisitions(
                                    project.functions[q]
                                )
                            }
                            if q in project.functions
                            else False,
                            reach,
                            target,
                        )
                    )
                    for holder in held:
                        if holder == target:
                            if target not in reentrant:
                                self_deadlocks.append(
                                    (fn, node, target,
                                     (fn.qname,) + chain)
                                )
                            continue
                        edges.setdefault(
                            (holder, target),
                            (fn, node, (fn.qname,) + chain),
                        )
        elif isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
        ):
            if node is not fn.node:
                return  # nested defs execute under their caller's locks
        for child in ast.iter_child_nodes(node):
            self._walk(
                project, fn, child, held, reach, reentrant, edges,
                self_deadlocks,
            )

    @staticmethod
    def _path(graph, start, goal) -> Optional[List[str]]:
        from collections import deque

        queue = deque([[start]])
        seen = {start}
        while queue:
            path = queue.popleft()
            if path[-1] == goal:
                return path
            for nxt in graph.get(path[-1], ()):
                if nxt not in seen:
                    seen.add(nxt)
                    queue.append(path + [nxt])
        return None


# -- transitive-host-sync -----------------------------------------------

_TRACED_LOOP_CALLS = {
    "jax.lax.scan",
    "lax.scan",
    "jax.lax.fori_loop",
    "lax.fori_loop",
    "jax.lax.while_loop",
    "lax.while_loop",
    "jax.lax.map",
    "lax.map",
}

_HOT_CALLEE_RE = re.compile(
    r"(jit_step|step_fn|train_step|update_step|solve_step)$"
)

_NUMPY_MODULES = {"np", "numpy", "onp"}

# A callee that IS the host boundary on purpose says so in its
# docstring; the declaration is the contract (same convention as the
# lock rule's "caller holds the lock").
_HOST_BOUNDARY_RE = re.compile(
    r"host[- ](tail|side|boundary|fetch)", re.IGNORECASE
)


def _sync_sites(fn_node: ast.AST) -> List[Tuple[ast.AST, str]]:
    """Direct host-sync markers in one function body (not descending
    into nested defs)."""
    out: List[Tuple[ast.AST, str]] = []
    stack = list(ast.iter_child_nodes(fn_node))
    while stack:
        node = stack.pop()
        if isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
        ):
            continue
        stack.extend(ast.iter_child_nodes(node))
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        if isinstance(func, ast.Attribute):
            base = dotted_name(func.value)
            if func.attr == "item" and not node.args:
                out.append((node, ".item()"))
            elif func.attr == "block_until_ready":
                out.append((node, ".block_until_ready()"))
            elif (
                base.split(".")[0] in _NUMPY_MODULES
                and func.attr in ("asarray", "array")
            ):
                out.append((node, f"{base}.{func.attr}()"))
            elif base == "jax" and func.attr == "device_get":
                out.append((node, "jax.device_get()"))
        elif isinstance(func, ast.Name) and func.id == "device_get":
            out.append((node, "device_get()"))
    return out


class TransitiveHostSync(ProjectRule):
    name = "transitive-host-sync"
    description = (
        "a call chain from a hot loop (lax body / jit-step loop) "
        "reaches .item()/block_until_ready/device_get/np.asarray in "
        "another function"
    )
    rationale = (
        "the per-file rule only sees syncs lexically inside the loop; "
        "a helper two calls down stalls the dispatch pipeline just the "
        "same, every iteration, invisibly"
    )

    @staticmethod
    def _sync_reach(project: Project):
        """(syncs, reach): direct host-sync sites per function, and the
        transitive closure of which sync-containing functions each
        function reaches. Memoized on the project."""
        syncs: Dict[str, List[Tuple[ast.AST, str]]] = {}
        for qn, fn in project.functions.items():
            doc = ast.get_docstring(fn.node) or ""
            if _HOST_BOUNDARY_RE.search(doc):
                continue
            sites = _sync_sites(fn.node)
            if sites:
                syncs[qn] = sites

        reach: Dict[str, Set[str]] = {
            qn: ({qn} if qn in syncs else set())
            for qn in project.functions
        }
        changed = True
        while changed:
            changed = False
            for qn, fn in project.functions.items():
                acc = reach[qn]
                before = len(acc)
                for _, callee in fn.calls:
                    acc |= reach.get(callee, set())
                if len(acc) != before:
                    changed = True
        return syncs, reach

    def check_project(self, project: Project) -> Iterator[Finding]:
        syncs, reach = project.cached(
            "host_sync_reach", lambda: self._sync_reach(project)
        )

        seen: Set[Tuple[str, int, str]] = set()
        for fn in project.functions.values():
            for region_call, callee_qn in self._hot_region_calls(
                project, fn
            ):
                targets = reach.get(callee_qn, set())
                # Syncs in the SAME function as the hot region are the
                # per-file rule's findings; only cross-function ones here.
                targets = {t for t in targets if t != fn.qname}
                for target in sorted(targets):
                    site, what = syncs[target][0]
                    key = (
                        fn.module.relpath,
                        getattr(region_call, "lineno", 0),
                        target,
                    )
                    if key in seen:
                        continue
                    seen.add(key)
                    chain = project.witness_chain(
                        callee_qn, lambda q: q == target, reach, target
                    )
                    tmod = project.functions[target].module
                    yield _project_finding(
                        self, project, fn, region_call,
                        f"hot-loop call reaches {what} at "
                        f"{tmod.relpath}:{getattr(site, 'lineno', '?')} "
                        f"via {' -> '.join([fn.qname] + list(chain))} — "
                        "a host sync every iteration; hoist it out of "
                        "the loop or keep the value on device",
                    )

    def _hot_region_calls(
        self, project: Project, fn: FunctionInfo
    ) -> Iterator[Tuple[ast.Call, str]]:
        """(call node, resolved callee qname) for calls inside hot
        regions of ``fn``: lax-traced bodies handed to scan/fori/while,
        jitted function bodies, and python loops driving a jit step."""
        resolved = {id(c): qn for c, qn in fn.calls}
        regions: List[ast.AST] = []

        # (a) the whole body when fn itself is jitted (traced code).
        if self._is_jitted(project, fn):
            regions.append(fn.node)

        # (b) local defs handed to lax.scan/fori/while in this fn are
        # covered when those defs are themselves walked (their calls are
        # their own FunctionInfo's); here we mark python loops only.
        donated_or_jit = self._local_jit_names(fn)
        for node in Project._walk_own(fn.node):
            if isinstance(node, (ast.For, ast.While, ast.AsyncFor)):
                if self._is_hot_loop(node, donated_or_jit):
                    regions.append(node)

        emitted: Set[int] = set()
        for region in regions:
            walk = (
                Project._walk_own(region)
                if region is fn.node
                else ast.walk(region)
            )
            for node in walk:
                if (
                    isinstance(node, ast.Call)
                    and id(node) in resolved
                    and id(node) not in emitted
                ):
                    emitted.add(id(node))
                    yield node, resolved[id(node)]

    def _is_jitted(self, project: Project, fn: FunctionInfo) -> bool:
        for dec in fn.node.decorator_list:
            target = dec.func if isinstance(dec, ast.Call) else dec
            leaf = dotted_name(target).split(".")[-1]
            if leaf == "jit":
                return True
            if leaf == "partial" and isinstance(dec, ast.Call) and dec.args:
                if dotted_name(dec.args[0]).split(".")[-1] == "jit":
                    return True
        # Module-level alias g = jax.jit(f) marks f as traced; a plain
        # `public = _impl` alias or lru_cache wrapper does not.
        mod = fn.module
        if fn.name in mod.traced_defs:
            return True
        # Handed to lax.scan / fori_loop / while_loop anywhere in the
        # module: the body is traced per iteration.
        for other in mod.functions.values():
            for node in ast.walk(other.node):
                if not isinstance(node, ast.Call):
                    continue
                if dotted_name(node.func) not in _TRACED_LOOP_CALLS:
                    continue
                for arg in node.args:
                    if isinstance(arg, ast.Name) and arg.id == fn.name:
                        return True
        return False

    def _local_jit_names(self, fn: FunctionInfo) -> Set[str]:
        names: Set[str] = set()
        for node in ast.walk(fn.node):
            if (
                isinstance(node, ast.Assign)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and isinstance(node.value, ast.Call)
            ):
                call = node.value
                leaf = dotted_name(call.func).split(".")[-1]
                has_donate = any(
                    kw.arg in ("donate_argnums", "donate_argnames")
                    for kw in call.keywords
                )
                if leaf == "jit" or (
                    isinstance(unwrap_call(call), ast.Name) and has_donate
                ):
                    names.add(node.targets[0].id)
        return names

    def _is_hot_loop(self, loop: ast.AST, jit_names: Set[str]) -> bool:
        for node in ast.walk(loop):
            if not isinstance(node, ast.Call):
                continue
            callee = dotted_name(node.func)
            if not callee and isinstance(node.func, ast.Name):
                callee = node.func.id
            leaf = callee.split(".")[-1] if callee else ""
            if leaf in jit_names or _HOT_CALLEE_RE.search(leaf or ""):
                return True
        return False


# -- swallowed-exception ------------------------------------------------

_SCOPE_PREFIXES = ("shockwave_tpu/runtime/", "shockwave_tpu/ha/")
# physical.py hosts the RPC callbacks; explain.py and duals.py feed the
# ExplainJob handler — a swallowed error in any of them turns a live
# narrative request into a silent found=false.
_SCOPE_FILES = (
    "shockwave_tpu/core/physical.py",
    "shockwave_tpu/obs/explain.py",
    "shockwave_tpu/solver/duals.py",
)

_LOG_METHODS = {
    "debug", "info", "warning", "error", "exception", "critical", "log",
}


def _handler_is_broad(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True
    names = []
    if isinstance(t, ast.Tuple):
        names = [dotted_name(e) for e in t.elts]
    else:
        names = [dotted_name(t)]
    return any(
        n.split(".")[-1] in ("Exception", "BaseException") for n in names
    )


def _node_reports(node: ast.AST) -> bool:
    """Does this single statement/expression visibly report the error?"""
    if isinstance(node, ast.Raise):
        return True
    if isinstance(node, ast.Call):
        func = node.func
        if isinstance(func, ast.Attribute):
            if func.attr in _LOG_METHODS:
                base = dotted_name(func.value).split(".")[0].lower()
                if "log" in base or base in ("self", "cls"):
                    return True
            if func.attr == "print_exc":
                return True
            if func.attr == "inc":
                # obs.counter(...).inc() / self._errors.inc() — an error
                # counter increment is a visible report.
                return True
    return False


class SwallowedException(ProjectRule):
    name = "swallowed-exception"
    description = (
        "bare `except`/`except Exception` on the gRPC/retry paths that "
        "neither re-raises, logs via the project logger, nor "
        "increments an error counter"
    )
    rationale = (
        "a swallowed RPC/retry failure turns a dead worker or a "
        "failed dispatch into an invisible hang: the scheduler waits "
        "on a Done that can never arrive"
    )

    def check_project(self, project: Project) -> Iterator[Finding]:
        # Fixpoint: which functions visibly report (log/inc/raise) on
        # some path — used to credit helpers the handler delegates to.
        reports: Dict[str, bool] = {}
        for qn, fn in project.functions.items():
            reports[qn] = any(
                _node_reports(n) for n in ast.walk(fn.node)
            )
        changed = True
        while changed:
            changed = False
            for qn, fn in project.functions.items():
                if reports[qn]:
                    continue
                if any(reports.get(callee, False) for _, callee in fn.calls):
                    reports[qn] = True
                    changed = True

        for fn in project.functions.values():
            relpath = fn.module.relpath
            if not (
                relpath.startswith(_SCOPE_PREFIXES)
                or relpath in _SCOPE_FILES
            ):
                continue
            resolved = {id(c): qn for c, qn in fn.calls}
            for node in ast.walk(fn.node):
                if not isinstance(node, ast.Try):
                    continue
                for handler in node.handlers:
                    if not _handler_is_broad(handler):
                        continue
                    if self._handler_reports(handler, resolved, reports):
                        continue
                    yield _project_finding(
                        self, project, fn, handler,
                        f"{fn.qname} swallows "
                        f"{self._handler_label(handler)} without "
                        "re-raising, logging, or incrementing an error "
                        "counter — on the gRPC/retry paths this turns "
                        "failures into silent hangs",
                    )

    @staticmethod
    def _handler_label(handler: ast.ExceptHandler) -> str:
        if handler.type is None:
            return "a bare except"
        return f"`except {dotted_name(handler.type) or 'Exception'}`"

    def _handler_reports(
        self, handler: ast.ExceptHandler, resolved, reports
    ) -> bool:
        for node in ast.walk(handler):
            if _node_reports(node):
                return True
            if isinstance(node, ast.Call) and id(node) in resolved:
                if reports.get(resolved[id(node)], False):
                    return True
        return False


def lock_graph_dict(project: Optional[Project] = None) -> dict:
    """JSON-ready dump of the project's lock acquisition-order graph —
    what ``python -m shockwave_tpu.analysis --lock-graph`` prints and
    the committed sweep evidence records. An operator triaging a
    deadlock diffs this against the sanitizer's observed order."""
    project = project or Project.build()
    edges, self_deadlocks = LockOrderCycle().graph(project)
    return {
        "edges": [
            {
                "held": a,
                "acquired": b,
                "site": f"{fn.module.relpath}:{getattr(site, 'lineno', 0)}",
                "via": list(chain),
            }
            for (a, b), (fn, site, chain) in sorted(edges.items())
        ],
        "self_deadlocks": [
            {
                "lock": lock,
                "site": f"{fn.module.relpath}:{getattr(site, 'lineno', 0)}",
                "via": list(chain),
            }
            for fn, site, lock, chain in self_deadlocks
        ],
    }
