"""Rule: rng-key-reuse.

JAX PRNG keys are pure values: feeding the same key to two consumers
produces *identical* randomness (correlated init and dropout masks, a
bug that shows up as mysteriously degenerate training, never as an
error). A key may be consumed once; every further consumer must get a
fresh key from ``jax.random.split`` / ``fold_in``. This rule tracks
key-typed names inside each scope and flags a second consumption
without an interposing rebind.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Set, Tuple

from shockwave_tpu.analysis.core import (
    FileContext,
    Finding,
    Rule,
    dotted_name,
    iter_scopes,
    node_pos,
    walk_scope,
)

_KEY_SOURCES = {"PRNGKey", "key", "fold_in"}
_DERIVE_LEAVES = {"split", "fold_in"}


def _is_key_source(call: ast.Call) -> bool:
    name = dotted_name(call.func)
    if not name:
        return False
    parts = name.split(".")
    leaf = parts[-1]
    if leaf == "PRNGKey":
        return True
    # jax.random.key / random.key — require the random module prefix so
    # a generic dict .key() helper is not mistaken for a PRNG source.
    if leaf in ("key", "fold_in") and len(parts) >= 2 and parts[-2] == "random":
        return True
    if leaf == "fold_in" and parts[0] in ("jax", "jrandom", "jr"):
        return True
    return False


def _is_derive_call(call: ast.Call) -> bool:
    """A jax.random.split / fold_in call. Requires a random-module
    prefix so e.g. ``line.split("\\t")`` is never mistaken for a PRNG
    derivation."""
    name = dotted_name(call.func)
    if not name:
        return False
    parts = name.split(".")
    if parts[-1] not in _DERIVE_LEAVES:
        return False
    return len(parts) >= 2 and (
        parts[-2] == "random" or parts[0] in ("jax", "jrandom", "jr")
    )


class RngKeyReuse(Rule):
    name = "rng-key-reuse"
    description = (
        "the same PRNG key is passed to two consumers without an "
        "interposing split/fold_in"
    )
    rationale = (
        "identical keys produce identical samples — correlated "
        "initializations and dropout masks that silently degrade "
        "training instead of failing"
    )

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for scope in iter_scopes(ctx.tree):
            yield from self._check_scope(ctx, scope)

    def _check_scope(self, ctx: FileContext, scope: ast.AST):
        key_vars = self._collect_key_vars(scope)
        if not key_vars:
            return
        # Ordered (pos, kind, node) events per key var.
        events: Dict[str, List[Tuple[tuple, str, ast.AST]]] = {
            k: [] for k in key_vars
        }
        for node in walk_scope(scope):
            if isinstance(node, ast.Name) and node.id in events:
                if isinstance(node.ctx, ast.Store):
                    events[node.id].append((node_pos(node), "rebind", node))
            elif isinstance(node, ast.Call):
                consumed = self._consumed_keys(node, key_vars)
                kind = "derive" if _is_derive_call(node) else "consume"
                for name, arg_node in consumed:
                    events[name].append((node_pos(arg_node), kind, node))
        for name, evs in events.items():
            evs.sort(key=lambda e: e[0])
            last_use: Optional[ast.AST] = None
            for pos, kind, node in evs:
                if kind == "rebind":
                    last_use = None
                    continue
                if last_use is not None:
                    if self._exclusive_branches(ctx, last_use, node):
                        continue
                    if self._terminating_branch_separates(
                        ctx, last_use, node
                    ):
                        continue
                    yield self.finding(
                        ctx,
                        node,
                        f"PRNG key '{name}' already consumed at line "
                        f"{last_use.lineno} is used again here without "
                        "split/fold_in — both consumers see identical "
                        "randomness",
                    )
                    last_use = node
                else:
                    last_use = node

    def _collect_key_vars(self, scope: ast.AST) -> Set[str]:
        keys: Set[str] = set()
        for node in walk_scope(scope):
            if not isinstance(node, ast.Assign):
                continue
            value = node.value
            if not isinstance(value, ast.Call):
                continue
            if _is_key_source(value):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        keys.add(t.id)
            elif _is_derive_call(value):
                # k1, k2 = jax.random.split(key): each target a key.
                for t in node.targets:
                    if isinstance(t, (ast.Tuple, ast.List)):
                        for elt in t.elts:
                            if isinstance(elt, ast.Name):
                                keys.add(elt.id)
                    elif isinstance(t, ast.Name):
                        keys.add(t.id)
        return keys

    def _consumed_keys(self, call: ast.Call, key_vars: Set[str]):
        """(name, node) for key vars appearing whole as call arguments.

        A subscripted key array (``keys[i]``) selects distinct keys per
        use and is not tracked; the whole-array name passed bare is.
        """
        out = []
        for arg in list(call.args) + [kw.value for kw in call.keywords]:
            if isinstance(arg, ast.Name) and arg.id in key_vars:
                out.append((arg.id, arg))
        return out

    def _terminating_branch_separates(
        self, ctx: FileContext, a: ast.AST, b: ast.AST
    ) -> bool:
        """True when ``a`` sits in an If body that ends in
        return/raise/continue/break and ``b`` comes after that whole If
        — control flow that reaches ``b`` never executed ``a`` (the
        ``if name == ...: ...; return`` dispatch idiom in
        models/train.py's build_family)."""
        b_pos = node_pos(b)
        for anc in ctx.ancestors(a):
            if not isinstance(anc, ast.If):
                continue
            for branch in (anc.body, anc.orelse):
                if not branch:
                    continue
                if not any(a in ast.walk(s) for s in branch):
                    continue
                last = branch[-1]
                if isinstance(
                    last, (ast.Return, ast.Raise, ast.Continue, ast.Break)
                ):
                    end = (
                        getattr(anc, "end_lineno", anc.lineno),
                        getattr(anc, "end_col_offset", 0),
                    )
                    if b_pos > end:
                        return True
        return False

    def _exclusive_branches(
        self, ctx: FileContext, a: ast.AST, b: ast.AST
    ) -> bool:
        """True when a and b sit in mutually exclusive branches of the
        same If (or a Try body vs handler) — only one runs, no reuse."""
        for anc in ctx.ancestors(a):
            if isinstance(anc, ast.If):
                in_body = any(a in ast.walk(s) for s in anc.body)
                other_body = any(b in ast.walk(s) for s in anc.body)
                in_else = any(a in ast.walk(s) for s in anc.orelse)
                other_else = any(b in ast.walk(s) for s in anc.orelse)
                if (in_body and other_else) or (in_else and other_body):
                    return True
            if isinstance(anc, ast.Try):
                in_body = any(a in ast.walk(s) for s in anc.body)
                other_h = any(
                    b in ast.walk(h) for h in anc.handlers
                )
                in_h = any(a in ast.walk(h) for h in anc.handlers)
                other_body = any(b in ast.walk(s) for s in anc.body)
                if (in_body and other_h) or (in_h and other_body):
                    return True
        return False
