"""The shockwave-lint rule catalog.

One class per hazard class this repo has been bitten by (or explicitly
guards by convention); see each module's docstring for the rationale
and ``docs/USAGE.md`` for the operator-facing catalog.
"""

from __future__ import annotations

from typing import List

from shockwave_tpu.analysis.core import Rule
from shockwave_tpu.analysis.rules.conformance import SolverBackendConformance
from shockwave_tpu.analysis.rules.donation import DonationAfterUse
from shockwave_tpu.analysis.rules.fileio import NonAtomicArtifactWrite
from shockwave_tpu.analysis.rules.hotloop import HostSyncInHotLoop
from shockwave_tpu.analysis.rules.interproc import (
    LockOrderCycle,
    SwallowedException,
    TransitiveHostSync,
)
from shockwave_tpu.analysis.rules.locks import LockDiscipline
from shockwave_tpu.analysis.rules.races import SharedStateRace, SnapshotEscape
from shockwave_tpu.analysis.rules.rng import RngKeyReuse
from shockwave_tpu.analysis.rules.wirecheck import (
    CanonicalDefaultOmission,
    DecoderUnknownFieldTolerance,
    FieldNumberCollision,
    ProtoCodecDrift,
)

RULE_CLASSES = (
    DonationAfterUse,
    HostSyncInHotLoop,
    RngKeyReuse,
    LockDiscipline,
    NonAtomicArtifactWrite,
    SolverBackendConformance,
    LockOrderCycle,
    TransitiveHostSync,
    SwallowedException,
    SharedStateRace,
    SnapshotEscape,
    ProtoCodecDrift,
    FieldNumberCollision,
    CanonicalDefaultOmission,
    DecoderUnknownFieldTolerance,
)


def default_rules() -> List[Rule]:
    return [cls() for cls in RULE_CLASSES]


def rule_by_name(name: str) -> Rule:
    for cls in RULE_CLASSES:
        if cls.name == name:
            return cls()
    raise KeyError(name)


__all__ = [
    "RULE_CLASSES",
    "default_rules",
    "rule_by_name",
    "DonationAfterUse",
    "HostSyncInHotLoop",
    "RngKeyReuse",
    "LockDiscipline",
    "NonAtomicArtifactWrite",
    "SolverBackendConformance",
    "LockOrderCycle",
    "TransitiveHostSync",
    "SwallowedException",
    "SharedStateRace",
    "SnapshotEscape",
    "ProtoCodecDrift",
    "FieldNumberCollision",
    "CanonicalDefaultOmission",
    "DecoderUnknownFieldTolerance",
]
