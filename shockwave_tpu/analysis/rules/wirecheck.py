"""Rules: wire-contract conformance for the hand-rolled codecs.

Every RPC rides hand-rolled wire-compatible codecs
(``runtime/protobuf/*_pb2.py`` plus the fastwire columnar frames) whose
field numbers, wire types, and canonical default-omission are
maintained by hand against ``.proto`` files that are documentation,
not source. These rules close that loop: :mod:`..protospec` parses the
protos into a schema model and each codec module is AST-checked
against it.

* **proto-codec-drift** — every ``put_*`` serializer call and every
  ``scan_fields`` decoder branch must agree with the ``.proto`` on
  field number, wire type, and packedness; proto fields absent from
  the encoder or decoder, and codec fields (or whole codec classes)
  absent from the proto, are findings. The fastwire columnar path is
  held to the same contract: the ``STR_FIELDS`` /
  ``columns_from_jobspec_spans`` mapping must cover every ``JobSpec``
  field (a new JobSpec field that skips the columnar frame is a silent
  decode divergence, not a lint-free change) and the
  ``encode_columnar_block``/``decode_columnar_block`` pair must agree
  with ``ColumnarJobBlock``.
* **field-number-collision** — duplicate field numbers inside a
  message, reserved-range/name violations (declared ``reserved``
  statements plus proto's own 19000–19999 range), duplicate enum
  values.
* **canonical-default-omission** — ``put_msg`` is the one helper in
  :mod:`shockwave_tpu.runtime.protobuf.wire` that does NOT self-guard,
  so every call must sit under an ``if``/loop guard; an unguarded call
  emits a zero-length field for default values and breaks the
  all-default-message-serializes-to-zero-bytes contract byte-identity
  (and capability negotiation) rely on.
* **decoder-unknown-field-tolerance** — scan-based decoders must skip
  unknown tags, never raise on them: any ``raise`` inside a
  ``for ... in scan_fields(...)`` loop, or a field-dispatch chain
  whose terminal ``else`` raises, would turn a widened peer schema
  into a hard parse failure (the forward-compat flag-day these codecs
  exist to avoid).

Findings anchor on the ``*_pb2.py`` module (the proto file is named in
the message) so project-scoped runs and the baseline treat them like
any other Python finding.
"""

from __future__ import annotations

import ast
import fnmatch
import posixpath
from typing import Dict, Iterator, List, Optional, Sequence, Set, Tuple

from shockwave_tpu.analysis.core import FileContext, Finding, Rule

_PB2_GLOB = "shockwave_tpu/runtime/protobuf/*_pb2.py"
_LEGACY_PREFIX = "shockwave_tpu/runtime/protobuf/legacy/"
_FASTWIRE_PATH = "shockwave_tpu/runtime/protobuf/fastwire.py"

_PUT_HELPERS = frozenset(
    {
        "put_str",
        "put_varint",
        "put_double",
        "put_msg",
        "put_packed_varints",
        "put_packed_doubles",
    }
)

#: messages whose codec deliberately lives outside <proto>_pb2.py
#: (the columnar frame is fastwire's encode/decode_columnar_block).
_EXTERNAL_CODECS = frozenset({"ColumnarJobBlock"})


def _is_pb2_module(relpath: str) -> bool:
    return fnmatch.fnmatch(relpath, _PB2_GLOB) and not relpath.startswith(
        _LEGACY_PREFIX
    )


def _module_proto_name(relpath: str) -> str:
    base = posixpath.basename(relpath)
    return base[: -len("_pb2.py")] + ".proto"


def _is_protoc_generated(tree: ast.Module) -> bool:
    """protoc output assigns the serialized FileDescriptorProto to a
    module-level ``DESCRIPTOR`` — the runtime descriptor itself is the
    conformance authority there (checked by scripts/ci/wire_smoke.py),
    so the AST rules skip those modules."""
    for node in tree.body:
        if isinstance(node, ast.Assign):
            for target in node.targets:
                if isinstance(target, ast.Name) and target.id == "DESCRIPTOR":
                    return True
    return False


def _call_name(node: ast.Call) -> str:
    func = node.func
    if isinstance(func, ast.Name):
        return func.id
    if isinstance(func, ast.Attribute):
        return func.attr
    return ""


def _literal_int(node: ast.AST) -> Optional[int]:
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return node.value
    return None


def _self_attrs(node: ast.AST) -> Set[str]:
    """Attribute names read off ``self`` anywhere inside ``node``."""
    attrs: Set[str] = set()
    for sub in ast.walk(node):
        if (
            isinstance(sub, ast.Attribute)
            and isinstance(sub.value, ast.Name)
            and sub.value.id == "self"
        ):
            attrs.add(sub.attr)
    return attrs


def _method(cls: ast.ClassDef, name: str) -> Optional[ast.FunctionDef]:
    for node in cls.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if node.name == name:
                return node
    return None


def _function_calls(fn: ast.AST) -> List[ast.Call]:
    calls = [n for n in ast.walk(fn) if isinstance(n, ast.Call)]
    calls.sort(key=lambda c: (c.lineno, c.col_offset))
    return calls


class _WireRule(Rule):
    """Shared schema plumbing: rules accept an injected schema for
    fixture tests and lazily parse the repo's protos otherwise."""

    def __init__(self, schema=None):
        self._schema = schema

    def _get_schema(self):
        if self._schema is None:
            from shockwave_tpu.analysis import protospec

            self._schema = protospec.load_repo_schema()
        return self._schema


# ---------------------------------------------------------------------------
# proto-codec-drift
# ---------------------------------------------------------------------------

class ProtoCodecDrift(_WireRule):
    name = "proto-codec-drift"
    description = (
        "hand-rolled codec disagrees with its .proto on field number, "
        "wire type, packedness, field coverage, or documents a message "
        "no .proto declares"
    )
    rationale = (
        "the .proto files are the wire contract but nothing generates "
        "code from them — a codec edit that drifts (or a codec with no "
        ".proto at all, like explain_pb2 pre-fix) silently breaks "
        "byte-identity with every protoc peer"
    )

    def applies_to(self, relpath: str) -> bool:
        return _is_pb2_module(relpath) or relpath.endswith("fastwire.py")

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        if ctx.relpath.endswith("fastwire.py"):
            yield from self._check_fastwire(ctx)
            return
        if _is_protoc_generated(ctx.tree):
            return
        schema = self._get_schema()
        proto_name = _module_proto_name(ctx.relpath)
        proto_file = schema.files.get(proto_name)
        codec_classes = [
            node
            for node in ctx.tree.body
            if isinstance(node, ast.ClassDef)
            and (
                _method(node, "SerializeToString") is not None
                or _method(node, "FromString") is not None
            )
        ]
        implemented = {cls.name for cls in codec_classes}
        if proto_file is not None:
            for msg in proto_file.messages:
                if msg.name in implemented or msg.name in _EXTERNAL_CODECS:
                    continue
                yield self.finding(
                    ctx,
                    1,
                    f"message {msg.name} (declared in {proto_name}:"
                    f"{msg.line}) has no codec class in this module — "
                    "a peer encoding it gets silently dropped",
                )
        for cls in codec_classes:
            spec = schema.message(cls.name)
            if spec is None:
                yield self.finding(
                    ctx,
                    cls,
                    f"codec class {cls.name} is not declared by any "
                    f".proto — author {proto_name} so the wire contract "
                    "is documented, registered, and fuzzable",
                )
                continue
            yield from self._check_encoder(ctx, cls, spec)
            yield from self._check_decoder(ctx, cls, spec)

    # -- encoder --------------------------------------------------------

    def _helper_ok(self, helper: str, fld) -> bool:
        if helper == "put_str":
            return not fld.repeated and fld.kind == "string"
        if helper == "put_varint":
            return not fld.repeated and fld.kind in ("varint", "enum")
        if helper == "put_double":
            return not fld.repeated and fld.kind == "fixed64"
        if helper == "put_packed_varints":
            return fld.packed and fld.element_wire_type == 0
        if helper == "put_packed_doubles":
            return fld.packed and fld.element_wire_type == 1
        if helper == "put_msg":
            # Any length-delimited payload the caller pre-built: an
            # embedded message, a bytes field, one element of a
            # repeated string, or a pre-packed column. Singular strings
            # must go through the self-guarding put_str.
            if fld.wire_type != 2:
                return False
            return fld.repeated or fld.kind != "string"
        return False

    def _expected_helper(self, fld) -> str:
        if fld.packed:
            return (
                "put_packed_varints"
                if fld.element_wire_type == 0
                else "put_packed_doubles"
            )
        if fld.repeated or fld.kind in ("message", "bytes"):
            return "put_msg"
        if fld.kind == "string":
            return "put_str"
        if fld.kind == "fixed64":
            return "put_double"
        return "put_varint"

    def _encoder_attr(self, ctx: FileContext, call: ast.Call) -> Optional[str]:
        """The self attribute a put_* call serializes, when it is
        unambiguous: either exactly one ``self.x`` in the value
        expression, or the ``self.x`` a wrapping ``for`` iterates."""
        if len(call.args) < 3:
            return None
        attrs = _self_attrs(call.args[2])
        if len(attrs) == 1:
            return next(iter(attrs))
        if attrs:
            return None
        for ancestor in ctx.ancestors(call):
            if isinstance(ancestor, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return None
            if isinstance(ancestor, (ast.For, ast.AsyncFor)):
                iter_attrs = _self_attrs(ancestor.iter)
                if len(iter_attrs) == 1:
                    return next(iter(iter_attrs))
                return None
        return None

    def _check_encoder(self, ctx: FileContext, cls: ast.ClassDef, spec):
        fn = _method(cls, "SerializeToString")
        if fn is None:
            yield self.finding(
                ctx, cls, f"codec class {cls.name} has no SerializeToString()"
            )
            return
        written: Set[int] = set()
        ordered: List[int] = []
        for call in _function_calls(fn):
            helper = _call_name(call)
            if helper not in _PUT_HELPERS or len(call.args) < 2:
                continue
            number = _literal_int(call.args[1])
            if number is None:
                yield self.finding(
                    ctx,
                    call,
                    f"{cls.name}: {helper}() field number must be a "
                    "literal int so the contract is statically checkable",
                )
                continue
            ordered.append(number)
            fld = spec.by_number.get(number)
            if fld is None:
                yield self.finding(
                    ctx,
                    call,
                    f"{cls.name} encoder writes field {number}, which "
                    f"{spec.proto} does not declare for message "
                    f"{spec.name}",
                )
                continue
            written.add(number)
            if not self._helper_ok(helper, fld):
                yield self.finding(
                    ctx,
                    call,
                    f"{cls.name} encoder writes field {number} "
                    f"({fld.name}: "
                    f"{'repeated ' if fld.repeated else ''}{fld.type}) "
                    f"with {helper}() — wrong wire type/packedness; "
                    f"expected {self._expected_helper(fld)}()",
                )
            attr = self._encoder_attr(ctx, call)
            if attr is not None and attr != fld.name:
                yield self.finding(
                    ctx,
                    call,
                    f"{cls.name} encoder writes self.{attr} into field "
                    f"{number}, which {spec.proto} names {fld.name!r} — "
                    "swapped or renumbered field",
                )
        for prev, cur in zip(ordered, ordered[1:]):
            if cur < prev:
                yield self.finding(
                    ctx,
                    fn,
                    f"{cls.name} encoder emits field {cur} after "
                    f"{prev} — canonical proto3 writes fields in "
                    "number order (byte-identity with protoc)",
                )
                break
        for fld in spec.fields:
            if fld.number not in written:
                yield self.finding(
                    ctx,
                    fn,
                    f"{cls.name} encoder never writes field "
                    f"{fld.number} ({fld.name}), declared in "
                    f"{spec.proto} — the field silently drops on send",
                )

    # -- decoder --------------------------------------------------------

    def _scan_loops(self, fn: ast.AST) -> List[Tuple[ast.For, str, str]]:
        loops = []
        for node in ast.walk(fn):
            if not isinstance(node, ast.For):
                continue
            if not (
                isinstance(node.iter, ast.Call)
                and _call_name(node.iter) == "scan_fields"
            ):
                continue
            field_var, wt_var = "field", "wire_type"
            if isinstance(node.target, ast.Tuple) and len(node.target.elts) >= 2:
                first, second = node.target.elts[0], node.target.elts[1]
                if isinstance(first, ast.Name):
                    field_var = first.id
                if isinstance(second, ast.Name):
                    wt_var = second.id
            loops.append((node, field_var, wt_var))
        return loops

    def _branch_tests(
        self, loop: ast.For, field_var: str, wt_var: str
    ) -> List[Tuple[ast.If, int, Optional[int]]]:
        """(if-node, field number, wire type or None) per dispatch branch."""
        branches = []
        for node in ast.walk(loop):
            if not isinstance(node, ast.If):
                continue
            field_num, wt = self._parse_test(node.test, field_var, wt_var)
            if field_num is not None:
                branches.append((node, field_num, wt))
        return branches

    def _parse_test(
        self, test: ast.AST, field_var: str, wt_var: str
    ) -> Tuple[Optional[int], Optional[int]]:
        field_num: Optional[int] = None
        wt: Optional[int] = None
        comparisons: List[ast.Compare] = []
        if isinstance(test, ast.BoolOp) and isinstance(test.op, ast.And):
            comparisons = [v for v in test.values if isinstance(v, ast.Compare)]
        elif isinstance(test, ast.Compare):
            comparisons = [test]
        for cmp_node in comparisons:
            if len(cmp_node.ops) != 1 or not isinstance(cmp_node.ops[0], ast.Eq):
                continue
            left, right = cmp_node.left, cmp_node.comparators[0]
            value = _literal_int(right)
            if not isinstance(left, ast.Name) or value is None:
                continue
            if left.id == field_var:
                field_num = value
            elif left.id == wt_var:
                wt = value
        return field_num, wt

    def _branch_attr(self, branch: ast.If) -> Optional[str]:
        """The instance attribute one dispatch branch assigns/appends —
        unambiguous single-attr branches only."""
        attrs: Set[str] = set()
        for node in branch.body:
            for sub in ast.walk(node):
                if isinstance(sub, ast.Assign):
                    for target in sub.targets:
                        if isinstance(target, ast.Attribute) and isinstance(
                            target.value, ast.Name
                        ):
                            attrs.add(target.attr)
                elif isinstance(sub, ast.Call):
                    func = sub.func
                    if (
                        isinstance(func, ast.Attribute)
                        and func.attr in ("append", "extend")
                        and isinstance(func.value, ast.Attribute)
                        and isinstance(func.value.value, ast.Name)
                    ):
                        attrs.add(func.value.attr)
        if len(attrs) == 1:
            return next(iter(attrs))
        return None

    def _check_decoder(self, ctx: FileContext, cls: ast.ClassDef, spec):
        fn = _method(cls, "FromString")
        if fn is None:
            yield self.finding(
                ctx, cls, f"codec class {cls.name} has no FromString()"
            )
            return
        loops = self._scan_loops(fn)
        if not loops:
            # A decoder not built on scan_fields (fastwire-style manual
            # scan) is outside this rule's per-branch model.
            return
        handled: Set[int] = set()
        for loop, field_var, wt_var in loops:
            for branch, number, wt in self._branch_tests(loop, field_var, wt_var):
                fld = spec.by_number.get(number)
                if fld is None:
                    yield self.finding(
                        ctx,
                        branch,
                        f"{cls.name} decoder handles field {number}, "
                        f"which {spec.proto} does not declare for "
                        f"message {spec.name}",
                    )
                    continue
                handled.add(number)
                allowed = {fld.wire_type}
                if fld.packed:
                    # protoc parsers accept the unpacked encoding of a
                    # packed field; these decoders keep that fallback.
                    allowed.add(fld.element_wire_type)
                if wt is not None and wt not in allowed:
                    yield self.finding(
                        ctx,
                        branch,
                        f"{cls.name} decoder reads field {number} "
                        f"({fld.name}) at wire type {wt}; {spec.proto} "
                        f"implies {sorted(allowed)}",
                    )
                attr = self._branch_attr(branch)
                if attr is not None and attr != fld.name:
                    yield self.finding(
                        ctx,
                        branch,
                        f"{cls.name} decoder stores field {number} into "
                        f".{attr}, which {spec.proto} names "
                        f"{fld.name!r} — swapped or renumbered field",
                    )
        for fld in spec.fields:
            if fld.number not in handled:
                yield self.finding(
                    ctx,
                    fn,
                    f"{cls.name} decoder never reads field "
                    f"{fld.number} ({fld.name}), declared in "
                    f"{spec.proto} — the field silently drops on receive",
                )

    # -- fastwire columnar path ----------------------------------------

    def _check_fastwire(self, ctx: FileContext) -> Iterator[Finding]:
        schema = self._get_schema()
        jobspec = schema.message("JobSpec")
        block = schema.message("ColumnarJobBlock")
        if jobspec is not None:
            yield from self._check_fastwire_jobspec(ctx, jobspec)
        if block is not None:
            yield from self._check_fastwire_block(ctx, block)

    def _str_fields_assign(self, ctx: FileContext):
        for node in ctx.tree.body:
            if isinstance(node, ast.Assign):
                for target in node.targets:
                    if isinstance(target, ast.Name) and target.id == "STR_FIELDS":
                        return node
        return None

    def _check_fastwire_jobspec(self, ctx: FileContext, jobspec):
        """STR_FIELDS + the numeric dispatch in
        columns_from_jobspec_spans must jointly cover JobSpec."""
        str_map: Dict[int, str] = {}
        assign = self._str_fields_assign(ctx)
        if assign is None:
            yield self.finding(
                ctx,
                1,
                "fastwire no longer defines STR_FIELDS — the columnar "
                "string-column mapping for JobSpec is gone",
            )
            return
        if isinstance(assign.value, (ast.Tuple, ast.List)):
            for elt in assign.value.elts:
                if isinstance(elt, (ast.Tuple, ast.List)) and len(elt.elts) == 2:
                    number = _literal_int(elt.elts[0])
                    name_node = elt.elts[1]
                    if number is not None and isinstance(name_node, ast.Constant):
                        str_map[number] = str(name_node.value)
        for number, name in sorted(str_map.items()):
            fld = jobspec.by_number.get(number)
            if fld is None or fld.name != name or fld.kind != "string":
                yield self.finding(
                    ctx,
                    assign,
                    f"STR_FIELDS maps column ({number}, {name!r}) but "
                    f"JobSpec declares "
                    f"{'no field ' + str(number) if fld is None else f'{number} as {fld.name} ({fld.type})'}",
                )
        numeric = self._jobspec_numeric_dispatch(ctx)
        for number, wt in sorted(numeric.items()):
            fld = jobspec.by_number.get(number)
            if fld is None:
                yield self.finding(
                    ctx,
                    1,
                    f"columns_from_jobspec_spans dispatches JobSpec "
                    f"field {number}, which admission.proto does not "
                    "declare",
                )
            elif fld.wire_type != wt:
                yield self.finding(
                    ctx,
                    1,
                    f"columns_from_jobspec_spans reads JobSpec field "
                    f"{number} ({fld.name}) at wire type {wt}; "
                    f"admission.proto implies {fld.wire_type}",
                )
        covered = set(str_map) | set(numeric)
        for fld in jobspec.fields:
            if fld.number not in covered:
                yield self.finding(
                    ctx,
                    1,
                    f"JobSpec field {fld.number} ({fld.name}) is not "
                    "mapped by the fastwire columnar decoder "
                    "(STR_FIELDS / columns_from_jobspec_spans) — the "
                    "field silently diverges between the scalar and "
                    "columnar decode paths",
                )

    def _jobspec_numeric_dispatch(self, ctx: FileContext) -> Dict[int, int]:
        """field number -> wire type for the numeric branches of
        columns_from_jobspec_spans (``if wt == 0: ... if field == 5``)."""
        fn = None
        for node in ctx.tree.body:
            if (
                isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
                and node.name == "columns_from_jobspec_spans"
            ):
                fn = node
                break
        if fn is None:
            return {}
        dispatch: Dict[int, int] = {}
        for node in ast.walk(fn):
            if not isinstance(node, ast.If):
                continue
            field_num, _ = self._parse_test(node.test, "field", "wt")
            if field_num is None:
                continue
            wt = self._enclosing_wt(ctx, node)
            if wt in (0, 1):
                dispatch[field_num] = wt
        return dispatch

    def _enclosing_wt(self, ctx: FileContext, node: ast.If) -> Optional[int]:
        for ancestor in ctx.ancestors(node):
            if isinstance(ancestor, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return None
            if isinstance(ancestor, ast.If):
                _, wt = self._parse_test(ancestor.test, "field", "wt")
                if wt is None and isinstance(ancestor.test, ast.Compare):
                    # `if wt == 0:` parses as the wt side only when the
                    # name matches; _parse_test already handled it.
                    pass
                if wt is not None:
                    return wt
        return None

    def _check_fastwire_block(self, ctx: FileContext, block):
        """encode/decode_columnar_block field numbers must cover and
        agree with ColumnarJobBlock."""
        encode_fn = decode_fn = None
        for node in ctx.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if node.name == "encode_columnar_block":
                    encode_fn = node
                elif node.name == "decode_columnar_block":
                    decode_fn = node
        if encode_fn is None or decode_fn is None:
            yield self.finding(
                ctx,
                1,
                "fastwire no longer defines encode_columnar_block/"
                "decode_columnar_block — the ColumnarJobBlock contract "
                "has no codec",
            )
            return
        written: Set[int] = set()
        for call in _function_calls(encode_fn):
            helper = _call_name(call)
            if helper not in _PUT_HELPERS or len(call.args) < 2:
                continue
            number = _literal_int(call.args[1])
            if number is None:
                continue
            fld = block.by_number.get(number)
            if fld is None:
                yield self.finding(
                    ctx,
                    call,
                    f"encode_columnar_block writes field {number}, "
                    "which ColumnarJobBlock does not declare",
                )
                continue
            written.add(number)
            expected_wt = 0 if helper == "put_varint" else 2
            if fld.wire_type != expected_wt:
                yield self.finding(
                    ctx,
                    call,
                    f"encode_columnar_block writes field {number} "
                    f"({fld.name}) with {helper}() (wire type "
                    f"{expected_wt}); ColumnarJobBlock implies "
                    f"{fld.wire_type}",
                )
        for fld in block.fields:
            if fld.number not in written:
                yield self.finding(
                    ctx,
                    encode_fn,
                    f"encode_columnar_block never writes field "
                    f"{fld.number} ({fld.name}) of ColumnarJobBlock",
                )
        read = self._block_decode_fields(decode_fn)
        for number in sorted(read):
            if number not in block.by_number:
                yield self.finding(
                    ctx,
                    decode_fn,
                    f"decode_columnar_block reads field {number}, "
                    "which ColumnarJobBlock does not declare",
                )
        for fld in block.fields:
            if fld.number not in read:
                yield self.finding(
                    ctx,
                    decode_fn,
                    f"decode_columnar_block never reads field "
                    f"{fld.number} ({fld.name}) of ColumnarJobBlock",
                )

    def _block_decode_fields(self, fn: ast.AST) -> Set[int]:
        numbers: Set[int] = set()
        for node in ast.walk(fn):
            if not isinstance(node, ast.Compare) or len(node.ops) != 1:
                continue
            if not (
                isinstance(node.left, ast.Name) and node.left.id == "field"
            ):
                continue
            comparator = node.comparators[0]
            if isinstance(node.ops[0], ast.Eq):
                value = _literal_int(comparator)
                if value is not None:
                    numbers.add(value)
            elif isinstance(node.ops[0], ast.In) and isinstance(
                comparator, (ast.Tuple, ast.List, ast.Set)
            ):
                for elt in comparator.elts:
                    value = _literal_int(elt)
                    if value is not None:
                        numbers.add(value)
        return numbers


# ---------------------------------------------------------------------------
# field-number-collision
# ---------------------------------------------------------------------------

class FieldNumberCollision(_WireRule):
    name = "field-number-collision"
    description = (
        ".proto message reuses a field number, violates a reserved "
        "range/name, or an enum aliases a value"
    )
    rationale = (
        "a reused or reserved field number decodes old peers' bytes "
        "into the wrong field with no error anywhere — the one wire "
        "bug no amount of runtime testing against the same build "
        "catches"
    )

    def applies_to(self, relpath: str) -> bool:
        return _is_pb2_module(relpath)

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        schema = self._get_schema()
        proto_file = schema.files.get(_module_proto_name(ctx.relpath))
        if proto_file is None:
            return
        for msg in proto_file.messages:
            seen: Dict[int, str] = {}
            for fld in msg.fields:
                if fld.number in seen:
                    yield self.finding(
                        ctx,
                        1,
                        f"{proto_file.name}:{fld.line}: message "
                        f"{msg.name} declares field number "
                        f"{fld.number} twice ({seen[fld.number]} and "
                        f"{fld.name})",
                    )
                seen[fld.number] = fld.name
                hit = msg.reserved_hit(fld.number)
                if hit is not None:
                    yield self.finding(
                        ctx,
                        1,
                        f"{proto_file.name}:{fld.line}: message "
                        f"{msg.name} field {fld.name} = {fld.number} "
                        f"falls in reserved range {hit[0]}-{hit[1]}",
                    )
                if fld.name in msg.reserved_names:
                    yield self.finding(
                        ctx,
                        1,
                        f"{proto_file.name}:{fld.line}: message "
                        f"{msg.name} reuses reserved field name "
                        f"{fld.name!r}",
                    )
        for enum in proto_file.enums:
            seen_values: Dict[int, str] = {}
            for value in enum.values:
                if value.number in seen_values:
                    yield self.finding(
                        ctx,
                        1,
                        f"{proto_file.name}:{value.line}: enum "
                        f"{enum.name} declares value {value.number} "
                        f"twice ({seen_values[value.number]} and "
                        f"{value.name})",
                    )
                seen_values[value.number] = value.name


# ---------------------------------------------------------------------------
# canonical-default-omission
# ---------------------------------------------------------------------------

class CanonicalDefaultOmission(Rule):
    name = "canonical-default-omission"
    description = (
        "unguarded put_msg() call — a default-valued field would emit "
        "a zero-length entry instead of being omitted"
    )
    rationale = (
        "canonical proto3 omits default fields, which is what makes an "
        "all-default message zero bytes and keeps hand-rolled output "
        "byte-identical to protoc; put_msg is the one wire.py helper "
        "that does not self-guard, so every call site needs an "
        "if/for guard (early-return guards do not count: the guard "
        "must be on the emptiness of THIS payload)"
    )

    def applies_to(self, relpath: str) -> bool:
        return _is_pb2_module(relpath) or relpath.endswith("fastwire.py")

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        if _is_protoc_generated(ctx.tree):
            return
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call) or _call_name(node) != "put_msg":
                continue
            if self._guarded(ctx, node):
                continue
            yield self.finding(
                ctx,
                node,
                "put_msg() without an if/for guard — an empty payload "
                "emits a zero-length field, breaking canonical "
                "default omission (and byte-identity with protoc)",
            )

    def _guarded(self, ctx: FileContext, node: ast.Call) -> bool:
        for ancestor in ctx.ancestors(node):
            if isinstance(
                ancestor, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
            ):
                return False
            if isinstance(
                ancestor, (ast.If, ast.IfExp, ast.For, ast.AsyncFor, ast.While)
            ):
                return True
        return False


# ---------------------------------------------------------------------------
# decoder-unknown-field-tolerance
# ---------------------------------------------------------------------------

class DecoderUnknownFieldTolerance(Rule):
    name = "decoder-unknown-field-tolerance"
    description = (
        "scan-based decoder raises inside its field loop or on an "
        "unmatched field number — unknown tags must be skipped"
    )
    rationale = (
        "proto3 forward compatibility IS unknown-field tolerance: a "
        "decoder that raises on an unrecognized tag turns every "
        "schema widening into a flag-day (the legacy-peer "
        "interop every capability negotiation here depends on)"
    )

    def applies_to(self, relpath: str) -> bool:
        return _is_pb2_module(relpath) or relpath.endswith("fastwire.py")

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        if _is_protoc_generated(ctx.tree):
            return
        reported: Set[ast.Raise] = set()
        # (a) any raise inside a `for ... in scan_fields(...)` body —
        # scan_fields already rejects malformed wire data before the
        # loop body runs, so a raise here can only be value/field
        # intolerance.
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.For):
                continue
            if not (
                isinstance(node.iter, ast.Call)
                and _call_name(node.iter) == "scan_fields"
            ):
                continue
            for sub in ast.walk(node):
                if isinstance(sub, ast.Raise) and sub not in reported:
                    reported.add(sub)
                    yield self.finding(
                        ctx,
                        sub,
                        "raise inside a scan_fields() loop — unknown or "
                        "unexpected fields must be skipped, not "
                        "rejected (proto3 forward compatibility)",
                    )
        # (b) a field-dispatch chain whose terminal else raises (manual
        # while-scanners dispatch on wire type too; only the FIELD
        # chain must be tolerant — unknown wire types 3/4/6/7 are
        # malformed data and may raise).
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.If):
                continue
            if not self._tests_field(node.test):
                continue
            for raise_node in self._terminal_else_raises(node):
                if raise_node in reported:
                    continue
                reported.add(raise_node)
                yield self.finding(
                    ctx,
                    raise_node,
                    "field-dispatch chain raises on an unmatched field "
                    "number — unknown fields must be skipped "
                    "(proto3 forward compatibility)",
                )

    def _tests_field(self, test: ast.AST) -> bool:
        for sub in ast.walk(test):
            if (
                isinstance(sub, ast.Compare)
                and isinstance(sub.left, ast.Name)
                and sub.left.id == "field"
            ):
                return True
        return False

    def _terminal_else_raises(self, node: ast.If) -> List[ast.Raise]:
        while node.orelse:
            if len(node.orelse) == 1 and isinstance(node.orelse[0], ast.If):
                nxt = node.orelse[0]
                if not self._tests_field(nxt.test):
                    # The chain switches dispatch variable (e.g. back
                    # to wire type) — stop at the field chain's end.
                    return []
                node = nxt
                continue
            return [
                sub
                for stmt in node.orelse
                for sub in ast.walk(stmt)
                if isinstance(sub, ast.Raise)
            ]
        return []
