"""Rule: host-sync-in-hot-loop.

A single ``.item()`` / ``float()`` / ``np.asarray`` /
``block_until_ready`` on a JAX value inside the train-step loop or a
``lax.scan`` body forces a device->host transfer every iteration,
serializing the dispatch pipeline that makes JAX fast (and inside a
traced scan body it is an outright tracer leak). Scoped to the code
that owns hot loops: ``models/``, ``parallel/``, the what-if fleet's batched solve path
``whatif/``, and the solver's JAX hot paths ``solver/eg_jax.py`` /
``solver/eg_pdhg.py``.
"""

from __future__ import annotations

import ast
import re
from typing import Iterator, List, Optional, Set

from shockwave_tpu.analysis.core import (
    FileContext,
    Finding,
    Rule,
    dotted_name,
    iter_scopes,
    walk_scope,
)
from shockwave_tpu.analysis.rules.donation import collect_donated_callables

_SCOPE_PREFIXES = (
    "shockwave_tpu/models/",
    "shockwave_tpu/parallel/",
    # The what-if fleet's batched counterfactual path: a host sync
    # inside its vmapped solve would serialize a thousand lanes at
    # once.
    "shockwave_tpu/whatif/",
)
_SCOPE_FILES = (
    "shockwave_tpu/solver/eg_jax.py",
    "shockwave_tpu/solver/eg_pdhg.py",
)

# lax control-flow primitives whose callable operand is traced per step.
_TRACED_LOOP_CALLS = {
    "jax.lax.scan",
    "lax.scan",
    "jax.lax.fori_loop",
    "lax.fori_loop",
    "jax.lax.while_loop",
    "lax.while_loop",
    "jax.lax.map",
    "lax.map",
}

# Callee-name shapes that mark a python for/while loop as a train/round
# hot loop even when the step callable is not jit-bound in this scope.
_HOT_CALLEE_RE = re.compile(
    r"(jit_step|step_fn|train_step|update_step|solve_step)$"
)

_NUMPY_MODULES = {"np", "numpy", "onp"}
_NUMPY_SYNC_ATTRS = {"asarray", "array"}


def _in_scope(relpath: str) -> bool:
    return relpath.startswith(_SCOPE_PREFIXES) or relpath in _SCOPE_FILES


class HostSyncInHotLoop(Rule):
    name = "host-sync-in-hot-loop"
    description = (
        ".item()/float()/np.asarray/block_until_ready/device_get on a "
        "JAX value inside a train-step loop or lax.scan/fori/while body"
    )
    rationale = (
        "each host sync in the hot loop stalls async dispatch for a "
        "full device round-trip (or leaks a tracer inside scan), "
        "erasing the latency the fast path exists to deliver"
    )

    def applies_to(self, relpath: str) -> bool:
        return _in_scope(relpath)

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        hot_regions: List[ast.AST] = []
        hot_kinds: List[str] = []

        # (a) callables handed to lax.scan / fori_loop / while_loop.
        local_defs = {
            n.name: n
            for n in ast.walk(ctx.tree)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        }
        traced_names: Set[str] = set()
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            if dotted_name(node.func) not in _TRACED_LOOP_CALLS:
                continue
            for arg in node.args:
                if isinstance(arg, ast.Lambda):
                    hot_regions.append(arg)
                    hot_kinds.append("lax traced body")
                elif isinstance(arg, ast.Name) and arg.id in local_defs:
                    traced_names.add(arg.id)
        for name in traced_names:
            hot_regions.append(local_defs[name])
            hot_kinds.append("lax traced body")

        # (b) python for/while loops that drive a jit step.
        donated: Set[str] = set()
        jit_bound: Set[str] = set()
        for scope in iter_scopes(ctx.tree):
            donated.update(collect_donated_callables(scope))
            for node in walk_scope(scope):
                if isinstance(node, ast.Assign) and isinstance(
                    node.value, ast.Call
                ):
                    if dotted_name(node.value.func).split(".")[-1] == "jit":
                        for t in node.targets:
                            if isinstance(t, ast.Name):
                                jit_bound.add(t.id)
        step_callables = donated | jit_bound
        for node in ast.walk(ctx.tree):
            if not isinstance(node, (ast.For, ast.While, ast.AsyncFor)):
                continue
            if self._is_hot_loop(node, step_callables):
                hot_regions.append(node)
                hot_kinds.append("train-step loop")

        seen: Set[int] = set()
        for region, kind in zip(hot_regions, hot_kinds):
            for sync, what in self._sync_sites(region):
                key = id(sync)
                if key in seen:
                    continue
                seen.add(key)
                yield self.finding(
                    ctx,
                    sync,
                    f"{what} inside a {kind} forces a host sync every "
                    "iteration; hoist it out of the loop or keep the "
                    "value on device",
                )

    def _is_hot_loop(self, loop: ast.AST, step_callables: Set[str]) -> bool:
        for node in ast.walk(loop):
            if not isinstance(node, ast.Call):
                continue
            callee = dotted_name(node.func)
            if not callee and isinstance(node.func, ast.Name):
                callee = node.func.id
            leaf = callee.split(".")[-1] if callee else ""
            if leaf in step_callables or _HOT_CALLEE_RE.search(leaf or ""):
                return True
        return False

    def _sync_sites(self, region: ast.AST):
        """(node, description) for every host-sync marker in region,
        not descending into nested defs for python loops (a helper
        defined inside the loop runs when called, not per iteration) —
        but a lax body IS the nested def, so walk it fully."""
        if isinstance(region, (ast.For, ast.While, ast.AsyncFor)):
            nodes = self._walk_no_defs(region)
        else:
            nodes = ast.walk(region)
        for node in nodes:
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            if isinstance(func, ast.Attribute):
                base = dotted_name(func.value)
                if func.attr == "item" and not node.args:
                    yield node, ".item()"
                elif func.attr == "block_until_ready":
                    yield node, ".block_until_ready()"
                elif (
                    base.split(".")[0] in _NUMPY_MODULES
                    and func.attr in _NUMPY_SYNC_ATTRS
                ):
                    yield node, f"{base}.{func.attr}()"
                elif base == "jax" and func.attr in (
                    "device_get",
                    "block_until_ready",
                ):
                    yield node, f"jax.{func.attr}()"
            elif isinstance(func, ast.Name):
                if func.id == "float" and node.args:
                    arg = node.args[0]
                    if not isinstance(arg, ast.Constant):
                        yield node, "float() on a computed value"
                elif func.id in ("device_get", "block_until_ready"):
                    yield node, f"{func.id}()"

    def _walk_no_defs(self, region: ast.AST):
        stack = list(ast.iter_child_nodes(region))
        while stack:
            node = stack.pop()
            yield node
            if isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
            ):
                continue
            stack.extend(ast.iter_child_nodes(node))
