"""Autofixes for mechanically-rewritable findings.

Currently one fixer: the ``non-atomic-artifact-write`` rule's two
dominant shapes rewrite to the :mod:`shockwave_tpu.utils.fileio`
helpers losslessly::

    with open(path, "w") as f:          ->  atomic_write_json(path, obj,
        json.dump(obj, f, indent=2)                           indent=2)

    with open(path, "w") as f:          ->  atomic_write_text(path, text)
        f.write(text)

Anything fancier (multiple statements in the with body, extra
``json.dump`` kwargs the helper has no slot for, writes in a loop) is
left for a human — a wrong autofix is worse than a finding.

The fixer inserts a function-local
``from shockwave_tpu.utils.fileio import ...`` immediately above the
rewritten statement unless the module already imports the helper at
top level: scripts in this repo do a ``sys.path.insert`` dance before
their project imports, and a local import is immune to that ordering.

``python -m shockwave_tpu.analysis --fix`` applies fixes in place;
``--fix --dry-run`` prints the unified diff and writes nothing.
"""

from __future__ import annotations

import ast
import difflib
from typing import List, Optional, Tuple

from shockwave_tpu.analysis.core import FileContext

_TRUNCATING_TEXT_MODES = {"w", "wt", "tw", "w+", "wt+"}

# json.dump keywords atomic_write_json can represent.
_DUMP_KW_OK = {"indent"}


class Fix:
    """One planned rewrite: replace source lines [start, end] (1-based,
    inclusive) with ``replacement`` (a list of full lines)."""

    __slots__ = ("start", "end", "replacement", "description")

    def __init__(self, start, end, replacement, description):
        self.start = start
        self.end = end
        self.replacement = replacement
        self.description = description


def _open_mode(call: ast.Call) -> Optional[str]:
    mode_node = None
    if len(call.args) >= 2:
        mode_node = call.args[1]
    for kw in call.keywords:
        if kw.arg == "mode":
            mode_node = kw.value
    if isinstance(mode_node, ast.Constant) and isinstance(
        mode_node.value, str
    ):
        return mode_node.value
    return None


def _match_open_with(stmt: ast.With):
    """(open_call, bound_name) when stmt is `with open(..., "w") as f:`
    with NOTHING beyond path and mode — an encoding/newline/buffering
    argument has no slot on the atomic helpers, and dropping it would
    silently change the written bytes."""
    if len(stmt.items) != 1:
        return None
    item = stmt.items[0]
    call = item.context_expr
    if not (
        isinstance(call, ast.Call)
        and isinstance(call.func, ast.Name)
        and call.func.id == "open"
        and call.args
    ):
        return None
    if len(call.args) > 2:
        return None
    if any(kw.arg != "mode" for kw in call.keywords):
        return None
    if _open_mode(call) not in _TRUNCATING_TEXT_MODES:
        return None
    if not isinstance(item.optional_vars, ast.Name):
        return None
    return call, item.optional_vars.id


def _module_imports_helper(tree: ast.Module, name: str) -> bool:
    for stmt in tree.body:
        if isinstance(stmt, ast.ImportFrom):
            if stmt.module and stmt.module.endswith("utils.fileio"):
                if any(a.name == name for a in stmt.names):
                    return True
    return False


def plan_fixes(source: str, relpath: str) -> List[Fix]:
    """Every non-atomic-artifact-write rewrite this fixer can do safely
    in ``source``. Suppressed lines are respected (a justified
    suppression documents a deliberate exception — don't "fix" it)."""
    try:
        ctx = FileContext(relpath, source)
    except SyntaxError:
        return []
    fixes: List[Fix] = []
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.With):
            continue
        if ctx.is_suppressed(node.lineno, "non-atomic-artifact-write"):
            continue
        matched = _match_open_with(node)
        if matched is None:
            continue
        open_call, fname = matched
        if len(node.body) != 1 or not isinstance(node.body[0], ast.Expr):
            continue
        inner = node.body[0].value
        if not isinstance(inner, ast.Call):
            continue
        path_src = ast.get_source_segment(source, open_call.args[0])
        if path_src is None:
            continue
        indent = " " * node.col_offset
        func = inner.func
        if (
            isinstance(func, ast.Attribute)
            and isinstance(func.value, ast.Name)
            and func.value.id == "json"
            and func.attr == "dump"
            and len(inner.args) == 2
            and isinstance(inner.args[1], ast.Name)
            and inner.args[1].id == fname
            and all(kw.arg in _DUMP_KW_OK for kw in inner.keywords)
        ):
            obj_src = ast.get_source_segment(source, inner.args[0])
            if obj_src is None:
                continue
            kw_src = ""
            for kw in inner.keywords:
                kw_val = ast.get_source_segment(source, kw.value)
                if kw_val is None:
                    kw_src = None
                    break
                kw_src += f", {kw.arg}={kw_val}"
            if kw_src is None:
                continue
            helper = "atomic_write_json"
            call_src = f"{helper}({path_src}, {obj_src}{kw_src})"
        elif (
            isinstance(func, ast.Attribute)
            and isinstance(func.value, ast.Name)
            and func.value.id == fname
            and func.attr == "write"
            and len(inner.args) == 1
            and not inner.keywords
        ):
            text_src = ast.get_source_segment(source, inner.args[0])
            if text_src is None:
                continue
            helper = "atomic_write_text"
            call_src = f"{helper}({path_src}, {text_src})"
        else:
            continue
        lines = [f"{indent}{call_src}\n"]
        if not _module_imports_helper(ctx.tree, helper):
            lines.insert(
                0,
                f"{indent}from shockwave_tpu.utils.fileio import "
                f"{helper}\n",
            )
        fixes.append(
            Fix(
                node.lineno,
                node.body[0].end_lineno,
                lines,
                f"{relpath}:{node.lineno}: open+{func.attr} -> {helper}",
            )
        )
    return fixes


def apply_fixes(source: str, fixes: List[Fix]) -> str:
    lines = source.splitlines(keepends=True)
    for fix in sorted(fixes, key=lambda f: f.start, reverse=True):
        lines[fix.start - 1: fix.end] = fix.replacement
    return "".join(lines)


def fix_files(
    paths_and_sources: List[Tuple[str, str, str]], dry_run: bool
) -> Tuple[List[str], str]:
    """Run the fixer over ``(abspath, relpath, source)`` triples.
    Returns (descriptions, unified diff). Writes files unless
    ``dry_run``."""
    from shockwave_tpu.utils.fileio import atomic_write_text

    descriptions: List[str] = []
    diffs: List[str] = []
    for abspath, relpath, source in paths_and_sources:
        fixes = plan_fixes(source, relpath)
        if not fixes:
            continue
        fixed = apply_fixes(source, fixes)
        descriptions.extend(f.description for f in fixes)
        diffs.append(
            "".join(
                difflib.unified_diff(
                    source.splitlines(keepends=True),
                    fixed.splitlines(keepends=True),
                    fromfile=f"a/{relpath}",
                    tofile=f"b/{relpath}",
                )
            )
        )
        if not dry_run:
            atomic_write_text(abspath, fixed)
    return descriptions, "".join(diffs)
