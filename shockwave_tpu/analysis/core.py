"""shockwave-lint core: AST rule framework, suppressions, file walking.

The invariants PRs 1-4 established (donated-buffer discipline, no host
syncs in hot loops, RNG hygiene, lock-guarded shared state, atomic
artifact writes, solver-backend interface conformance) are enforced
nowhere but reviewer memory. This module is the machinery that turns
them into machine-checked rules: each rule is an AST pass over one file
producing :class:`Finding` records; inline ``# shockwave-lint:
disable=<rule>`` comments suppress individual lines with a visible
justification; the committed baseline (see :mod:`.baseline`) ratchets
the repo-wide count monotonically toward zero.
"""

from __future__ import annotations

import ast
import dataclasses
import io
import os
import re
import tokenize
from typing import Dict, Iterable, Iterator, List, Optional, Sequence, Set

# -- findings -----------------------------------------------------------

_SEVERITIES = ("error", "warning")


@dataclasses.dataclass(frozen=True)
class Finding:
    """One rule violation at one source location.

    ``line_text`` (the stripped source of the flagged line) is part of
    the identity used by the baseline fingerprint, so findings stay
    matched across unrelated edits that only shift line numbers.
    """

    rule: str
    path: str  # repo-relative, forward slashes
    line: int
    col: int
    message: str
    line_text: str = ""
    suppressed: bool = False

    def location(self) -> str:
        return f"{self.path}:{self.line}:{self.col}"

    def render(self) -> str:
        return f"{self.location()}: [{self.rule}] {self.message}"

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


class Rule:
    """Base class for one lint rule.

    Subclasses set ``name``/``description`` and implement
    :meth:`check`; ``applies_to`` narrows the rule to the paths where
    its hazard class lives (e.g. lock discipline only in ``obs/`` and
    ``runtime/``).
    """

    name: str = ""
    description: str = ""
    rationale: str = ""
    project_level: bool = False

    def applies_to(self, relpath: str) -> bool:
        return True

    def check(self, ctx: "FileContext") -> Iterator[Finding]:
        raise NotImplementedError

    def finding(
        self, ctx: "FileContext", node_or_line, message: str
    ) -> Finding:
        if isinstance(node_or_line, int):
            line, col = node_or_line, 0
        else:
            line = getattr(node_or_line, "lineno", 1)
            col = getattr(node_or_line, "col_offset", 0)
        text = ""
        if 1 <= line <= len(ctx.lines):
            text = ctx.lines[line - 1].strip()
        return Finding(
            rule=self.name,
            path=ctx.relpath,
            line=line,
            col=col,
            message=message,
            line_text=text,
            suppressed=ctx.is_suppressed(line, self.name),
        )


class ProjectRule(Rule):
    """A rule that analyzes the whole project at once (symbol table +
    call graph) instead of one file at a time.

    ``check`` is a no-op so project rules compose with the per-file
    runner; :func:`run_paths` invokes :meth:`check_project` exactly once
    per run and scopes the findings to the checked files.
    """

    project_level = True

    def check(self, ctx: "FileContext") -> Iterator[Finding]:
        return iter(())

    def check_project(self, project) -> Iterator[Finding]:
        raise NotImplementedError


# -- per-file context ---------------------------------------------------

_SUPPRESS_RE = re.compile(
    r"#\s*shockwave-lint:\s*disable=([A-Za-z0-9_,\- ]+)"
)


def _parse_suppressions(source: str) -> Dict[int, Set[str]]:
    """Map line number -> rule names disabled on that line.

    A trailing comment suppresses its own line; a standalone comment
    line suppresses the next line too (so a justification can sit above
    the flagged statement).
    """
    suppressions: Dict[int, Set[str]] = {}
    try:
        tokens = tokenize.generate_tokens(io.StringIO(source).readline)
        for tok in tokens:
            if tok.type != tokenize.COMMENT:
                continue
            m = _SUPPRESS_RE.search(tok.string)
            if not m:
                continue
            rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
            line = tok.start[0]
            suppressions.setdefault(line, set()).update(rules)
            # Standalone comment: nothing but whitespace before it.
            if tok.line[: tok.start[1]].strip() == "":
                suppressions.setdefault(line + 1, set()).update(rules)
    except tokenize.TokenError:
        pass
    return suppressions


class FileContext:
    """Parsed source + suppression map + parent links, shared by rules."""

    def __init__(self, relpath: str, source: str):
        self.relpath = relpath.replace(os.sep, "/")
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source)
        self.suppressions = _parse_suppressions(source)
        self._parents: Dict[ast.AST, ast.AST] = {}
        for parent in ast.walk(self.tree):
            for child in ast.iter_child_nodes(parent):
                self._parents[child] = parent

    def parent(self, node: ast.AST) -> Optional[ast.AST]:
        return self._parents.get(node)

    def ancestors(self, node: ast.AST) -> Iterator[ast.AST]:
        cur = self._parents.get(node)
        while cur is not None:
            yield cur
            cur = self._parents.get(cur)

    def is_suppressed(self, line: int, rule: str) -> bool:
        rules = self.suppressions.get(line, set())
        return rule in rules or "all" in rules


# -- shared AST helpers (used by the rule modules) ----------------------

def dotted_name(node: ast.AST) -> str:
    """'jax.random.split' for an Attribute chain, '' when not a chain."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def iter_scopes(tree: ast.Module) -> Iterator[ast.AST]:
    """Module plus every (async) function def, each a binding scope."""
    yield tree
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


def walk_scope(scope: ast.AST) -> Iterator[ast.AST]:
    """Walk a scope's body without descending into nested scopes.

    Ordering-sensitive rules (donation-after-use, rng-key-reuse) reason
    about execution order, which nested function bodies do not share
    with their enclosing scope.
    """
    body = scope.body if hasattr(scope, "body") else []
    stack: List[ast.AST] = list(body)
    while stack:
        node = stack.pop()
        yield node
        if isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda, ast.ClassDef)
        ):
            continue
        stack.extend(ast.iter_child_nodes(node))


def node_pos(node: ast.AST):
    return (getattr(node, "lineno", 0), getattr(node, "col_offset", 0))


# -- running ------------------------------------------------------------

DEFAULT_EXCLUDE_DIRS = {"__pycache__", ".git", "results", "traces", "docs"}


def iter_python_files(paths: Sequence[str]) -> Iterator[str]:
    for path in paths:
        if os.path.isfile(path):
            if path.endswith(".py"):
                yield path
            continue
        for root, dirs, files in os.walk(path):
            dirs[:] = sorted(
                d for d in dirs if d not in DEFAULT_EXCLUDE_DIRS
            )
            for name in sorted(files):
                if name.endswith(".py"):
                    yield os.path.join(root, name)


def check_source(
    source: str, relpath: str, rules: Sequence[Rule]
) -> List[Finding]:
    """Run ``rules`` over one source string as if it lived at ``relpath``.

    Returns every finding including suppressed ones (callers filter on
    ``Finding.suppressed``). Unparseable sources yield a single
    ``parse-error`` finding rather than raising, so one bad file cannot
    take down a repo-wide run.
    """
    try:
        ctx = FileContext(relpath, source)
    except SyntaxError as e:
        return [
            Finding(
                rule="parse-error",
                path=relpath.replace(os.sep, "/"),
                line=e.lineno or 1,
                col=e.offset or 0,
                message=f"file does not parse: {e.msg}",
            )
        ]
    findings: List[Finding] = []
    for rule in rules:
        if not rule.applies_to(ctx.relpath):
            continue
        findings.extend(rule.check(ctx))
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings


def repo_root() -> str:
    """The directory holding the ``shockwave_tpu`` package."""
    here = os.path.dirname(os.path.abspath(__file__))
    return os.path.dirname(os.path.dirname(here))


DEFAULT_SCOPE = ("shockwave_tpu", "scripts", "bench.py")


def run_paths(
    paths: Optional[Sequence[str]] = None,
    rules: Optional[Sequence[Rule]] = None,
    root: Optional[str] = None,
) -> List[Finding]:
    """Run rules over files under ``paths`` (repo-relative or absolute).

    Defaults to the committed enforcement scope (the package, scripts,
    and bench.py) rooted at the repo.
    """
    from shockwave_tpu.analysis.rules import default_rules

    root = root or repo_root()
    rules = list(rules) if rules is not None else default_rules()
    file_rules = [r for r in rules if not r.project_level]
    project_rules = [r for r in rules if r.project_level]
    resolved = [
        p if os.path.isabs(p) else os.path.join(root, p)
        for p in (paths or DEFAULT_SCOPE)
    ]
    findings: List[Finding] = []
    checked: Set[str] = set()
    for path in iter_python_files([p for p in resolved if os.path.exists(p)]):
        relpath = os.path.relpath(path, root)
        checked.add(relpath.replace(os.sep, "/"))
        with open(path, encoding="utf-8") as f:
            source = f.read()
        findings.extend(check_source(source, relpath, file_rules))
    if project_rules and any(
        c.startswith("shockwave_tpu/") for c in checked
    ):
        # Project rules always analyze the whole package (a cross-file
        # hazard needs both halves in view) but only REPORT findings in
        # the checked scope, so --changed-only stays fast and exact —
        # and skips the build entirely when no checked file could
        # receive an interprocedural finding.
        from shockwave_tpu.analysis.project import Project

        project = Project.build(root)
        for rule in project_rules:
            for f in rule.check_project(project):
                if f.path in checked:
                    findings.append(f)
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings


def checked_relpaths(
    paths: Optional[Sequence[str]] = None, root: Optional[str] = None
) -> Set[str]:
    """The repo-relative files a :func:`run_paths` call with the same
    arguments would check — what the baseline's stale-entry scoping
    uses for partial (``--changed-only``) runs."""
    root = root or repo_root()
    resolved = [
        p if os.path.isabs(p) else os.path.join(root, p)
        for p in (paths or DEFAULT_SCOPE)
    ]
    return {
        os.path.relpath(p, root).replace(os.sep, "/")
        for p in iter_python_files(
            [p for p in resolved if os.path.exists(p)]
        )
    }


def active(findings: Iterable[Finding]) -> List[Finding]:
    return [f for f in findings if not f.suppressed]
