"""Schema model for the repo's ``.proto`` files.

The runtime speaks proto3 through hand-rolled codecs
(``runtime/protobuf/*_pb2.py``), so the ``.proto`` files are the
*contract*, not generated-from source — which is exactly why the
analyzer needs a first-class parse of them.  This module turns the
proto3 subset the repo uses (messages, scalar/message fields,
``repeated``, ``reserved``, enums, services) into a small schema model
that :mod:`shockwave_tpu.analysis.rules.wirecheck`,
:mod:`shockwave_tpu.analysis.wireregistry`, and
:mod:`shockwave_tpu.analysis.wirefuzz` all consume.

No dependency on ``google.protobuf`` — the parser is a few hundred
lines of tokenizer + recursive descent so the lint gate runs on any
box.  Wire-type resolution follows the proto3 encoding spec:

========  =======================================  =========
wire type  scalar types                            kind
========  =======================================  =========
0 varint  int32 int64 uint32 uint64 sint32
          sint64 bool enum                         varint
1 64-bit  double fixed64 sfixed64                  fixed64
5 32-bit  float fixed32 sfixed32                   fixed32
2 len     string bytes embedded-message            len
========  =======================================  =========

``repeated`` numeric scalars are PACKED in proto3 (wire type 2 with
the element type recoverable via :attr:`FieldSpec.element_wire_type`).
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field as dc_field
from pathlib import Path
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

from shockwave_tpu.analysis.core import repo_root

#: scalar proto3 type name -> wire kind
_VARINT_TYPES = frozenset(
    {"int32", "int64", "uint32", "uint64", "sint32", "sint64", "bool"}
)
_FIXED64_TYPES = frozenset({"double", "fixed64", "sfixed64"})
_FIXED32_TYPES = frozenset({"float", "fixed32", "sfixed32"})
_LEN_TYPES = frozenset({"string", "bytes"})
_SCALAR_TYPES = _VARINT_TYPES | _FIXED64_TYPES | _FIXED32_TYPES | _LEN_TYPES

#: proto reserves this tag range for its own wire format extensions.
IMPLEMENTATION_RESERVED = (19000, 19999)

WIRE_VARINT = 0
WIRE_FIXED64 = 1
WIRE_LEN = 2
WIRE_FIXED32 = 5


class ProtoParseError(ValueError):
    """Raised when a .proto file does not parse under the supported subset."""

    def __init__(self, message: str, filename: str = "<proto>", line: int = 0):
        super().__init__(f"{filename}:{line}: {message}")
        self.filename = filename
        self.line = line


@dataclass
class FieldSpec:
    """One field declaration inside a message."""

    name: str
    number: int
    type: str  # declared type name as written (scalar keyword or message/enum name)
    repeated: bool = False
    line: int = 0
    # Resolved by ProtoSchema.resolve():
    kind: str = ""  # varint | fixed64 | fixed32 | string | bytes | message | enum
    wire_type: int = -1  # wire type this field serializes with (packed => 2)
    element_wire_type: int = -1  # element wire type (unpacked repeated scalar)
    packed: bool = False

    @property
    def is_scalar(self) -> bool:
        return self.type in _SCALAR_TYPES or self.kind == "enum"


@dataclass
class MessageSpec:
    name: str
    proto: str  # relative proto filename, e.g. "admission.proto"
    line: int = 0
    fields: List[FieldSpec] = dc_field(default_factory=list)
    reserved_ranges: List[Tuple[int, int]] = dc_field(default_factory=list)
    reserved_names: List[str] = dc_field(default_factory=list)

    @property
    def by_number(self) -> Dict[int, FieldSpec]:
        return {f.number: f for f in self.fields}

    @property
    def by_name(self) -> Dict[str, FieldSpec]:
        return {f.name: f for f in self.fields}

    def reserved_hit(self, number: int) -> Optional[Tuple[int, int]]:
        """Return the reserved range containing ``number``, if any
        (declared ranges plus the 19000-19999 implementation range)."""
        for lo, hi in list(self.reserved_ranges) + [IMPLEMENTATION_RESERVED]:
            if lo <= number <= hi:
                return (lo, hi)
        return None


@dataclass
class EnumValueSpec:
    name: str
    number: int
    line: int = 0


@dataclass
class EnumSpec:
    name: str
    proto: str
    line: int = 0
    values: List[EnumValueSpec] = dc_field(default_factory=list)


@dataclass
class MethodSpec:
    name: str
    request: str
    response: str
    line: int = 0


@dataclass
class ServiceSpec:
    name: str
    proto: str
    line: int = 0
    methods: List[MethodSpec] = dc_field(default_factory=list)


@dataclass
class ProtoFile:
    name: str  # relative filename, e.g. "admission.proto"
    package: str = ""
    imports: List[str] = dc_field(default_factory=list)
    messages: List[MessageSpec] = dc_field(default_factory=list)
    enums: List[EnumSpec] = dc_field(default_factory=list)
    services: List[ServiceSpec] = dc_field(default_factory=list)


class ProtoSchema:
    """All parsed proto files of a package, with cross-file lookups."""

    def __init__(self, files: Dict[str, ProtoFile]):
        self.files = files
        self._messages: Dict[str, MessageSpec] = {}
        self._enums: Dict[str, EnumSpec] = {}
        for pf in files.values():
            for msg in pf.messages:
                self._messages[msg.name] = msg
            for enum in pf.enums:
                self._enums[enum.name] = enum
        self._resolve()

    # -- lookups -------------------------------------------------------
    def message(self, name: str) -> Optional[MessageSpec]:
        return self._messages.get(name)

    def enum(self, name: str) -> Optional[EnumSpec]:
        return self._enums.get(name)

    @property
    def messages(self) -> List[MessageSpec]:
        return [m for pf in self.files.values() for m in pf.messages]

    @property
    def enums(self) -> List[EnumSpec]:
        return [e for pf in self.files.values() for e in pf.enums]

    @property
    def services(self) -> List[ServiceSpec]:
        return [s for pf in self.files.values() for s in pf.services]

    def iter_fields(self) -> Iterator[Tuple[MessageSpec, FieldSpec]]:
        for msg in self.messages:
            for fld in msg.fields:
                yield msg, fld

    # -- wire-type resolution -----------------------------------------
    def _resolve(self) -> None:
        for msg in self._messages.values():
            for fld in msg.fields:
                self._resolve_field(fld)

    def _resolve_field(self, fld: FieldSpec) -> None:
        t = fld.type
        if t in _VARINT_TYPES or t in self._enums:
            fld.kind = "enum" if t in self._enums else "varint"
            element = WIRE_VARINT
        elif t in _FIXED64_TYPES:
            fld.kind = "fixed64"
            element = WIRE_FIXED64
        elif t in _FIXED32_TYPES:
            fld.kind = "fixed32"
            element = WIRE_FIXED32
        elif t in _LEN_TYPES:
            fld.kind = t  # "string" | "bytes"
            element = WIRE_LEN
        elif t in self._messages:
            fld.kind = "message"
            element = WIRE_LEN
        else:
            # Unknown type name: treat as message-like (imported from a
            # file outside the parsed set). wirecheck reports unknowns
            # through its own finding rather than a parse failure.
            fld.kind = "message"
            element = WIRE_LEN
        fld.element_wire_type = element
        if fld.repeated and element in (WIRE_VARINT, WIRE_FIXED64, WIRE_FIXED32):
            fld.packed = True
            fld.wire_type = WIRE_LEN
        else:
            fld.packed = False
            fld.wire_type = element

    # -- constructors --------------------------------------------------
    @classmethod
    def from_sources(cls, sources: Dict[str, str]) -> "ProtoSchema":
        return cls({name: parse_proto_text(text, name) for name, text in sources.items()})

    @classmethod
    def from_dir(cls, proto_dir: Path) -> "ProtoSchema":
        sources = {
            path.name: path.read_text(encoding="utf-8")
            for path in sorted(proto_dir.glob("*.proto"))
        }
        return cls.from_sources(sources)


# ---------------------------------------------------------------------------
# Tokenizer + parser
# ---------------------------------------------------------------------------

_TOKEN_RE = re.compile(
    r"""
    (?P<comment>//[^\n]*|/\*.*?\*/)
  | (?P<string>"(?:[^"\\]|\\.)*")
  | (?P<number>-?\d+)
  | (?P<ident>[A-Za-z_][A-Za-z0-9_.]*)
  | (?P<punct>[{}()\[\]=;,<>])
  | (?P<ws>\s+)
  | (?P<bad>.)
    """,
    re.VERBOSE | re.DOTALL,
)


def _tokenize(text: str, filename: str) -> List[Tuple[str, str, int]]:
    tokens: List[Tuple[str, str, int]] = []
    line = 1
    for match in _TOKEN_RE.finditer(text):
        kind = match.lastgroup
        value = match.group()
        if kind == "bad":
            raise ProtoParseError(f"unexpected character {value!r}", filename, line)
        if kind not in ("ws", "comment"):
            tokens.append((kind, value, line))
        line += value.count("\n")
    return tokens


class _Parser:
    def __init__(self, tokens: List[Tuple[str, str, int]], filename: str):
        self.tokens = tokens
        self.pos = 0
        self.filename = filename

    # -- token plumbing ------------------------------------------------
    def _peek(self) -> Optional[Tuple[str, str, int]]:
        return self.tokens[self.pos] if self.pos < len(self.tokens) else None

    def _next(self) -> Tuple[str, str, int]:
        tok = self._peek()
        if tok is None:
            last_line = self.tokens[-1][2] if self.tokens else 0
            raise ProtoParseError("unexpected end of file", self.filename, last_line)
        self.pos += 1
        return tok

    def _expect(self, value: str) -> Tuple[str, str, int]:
        tok = self._next()
        if tok[1] != value:
            raise ProtoParseError(
                f"expected {value!r}, got {tok[1]!r}", self.filename, tok[2]
            )
        return tok

    def _expect_kind(self, kind: str) -> Tuple[str, str, int]:
        tok = self._next()
        if tok[0] != kind:
            raise ProtoParseError(
                f"expected {kind}, got {tok[1]!r}", self.filename, tok[2]
            )
        return tok

    def _skip_statement(self) -> None:
        """Consume through the next ';' (used for option/syntax lines)."""
        while True:
            tok = self._next()
            if tok[1] == ";":
                return

    # -- grammar -------------------------------------------------------
    def parse_file(self) -> ProtoFile:
        pf = ProtoFile(name=self.filename)
        while self._peek() is not None:
            kind, value, line = self._peek()  # type: ignore[misc]
            if value == "syntax" or value == "option":
                self._skip_statement()
            elif value == "package":
                self._next()
                pf.package = self._expect_kind("ident")[1]
                self._expect(";")
            elif value == "import":
                self._next()
                tok = self._next()
                if tok[1] in ("public", "weak"):
                    tok = self._next()
                pf.imports.append(tok[1].strip('"'))
                self._expect(";")
            elif value == "message":
                pf.messages.append(self.parse_message())
            elif value == "enum":
                pf.enums.append(self.parse_enum())
            elif value == "service":
                pf.services.append(self.parse_service())
            elif value == ";":
                self._next()
            else:
                raise ProtoParseError(
                    f"unsupported top-level element {value!r}", self.filename, line
                )
        return pf

    def parse_message(self) -> MessageSpec:
        _, _, line = self._expect("message")
        name = self._expect_kind("ident")[1]
        msg = MessageSpec(name=name, proto=self.filename, line=line)
        self._expect("{")
        while True:
            tok = self._peek()
            if tok is None:
                raise ProtoParseError("unterminated message", self.filename, line)
            if tok[1] == "}":
                self._next()
                return msg
            if tok[1] == "reserved":
                self._parse_reserved(msg)
            elif tok[1] == "option":
                self._skip_statement()
            elif tok[1] == ";":
                self._next()
            elif tok[1] == "message":
                # Nested messages are flattened into the file's message
                # list under their simple name (the repo does not nest,
                # but fixtures may).
                msg_nested = self.parse_message()
                msg_nested.proto = self.filename
                self._nested_messages.append(msg_nested)
            elif tok[1] == "enum":
                self._nested_enums.append(self.parse_enum())
            else:
                msg.fields.append(self._parse_field())

    _nested_messages: List[MessageSpec]
    _nested_enums: List[EnumSpec]

    def _parse_field(self) -> FieldSpec:
        repeated = False
        tok = self._next()
        line = tok[2]
        if tok[1] in ("repeated", "optional", "required"):
            repeated = tok[1] == "repeated"
            tok = self._next()
        if tok[0] != "ident":
            raise ProtoParseError(
                f"expected field type, got {tok[1]!r}", self.filename, tok[2]
            )
        ftype = tok[1]
        if ftype == "map":
            raise ProtoParseError("map fields are not supported", self.filename, line)
        fname = self._expect_kind("ident")[1]
        self._expect("=")
        number = int(self._expect_kind("number")[1])
        tok = self._next()
        if tok[1] == "[":
            # field options, e.g. [packed = false] — parsed and ignored;
            # the repo's codecs only emit proto3 defaults.
            while self._next()[1] != "]":
                pass
            tok = self._next()
        if tok[1] != ";":
            raise ProtoParseError(
                f"expected ';' after field, got {tok[1]!r}", self.filename, tok[2]
            )
        return FieldSpec(name=fname, number=number, type=ftype, repeated=repeated, line=line)

    def _parse_reserved(self, msg: MessageSpec) -> None:
        self._expect("reserved")
        while True:
            tok = self._next()
            if tok[0] == "number":
                lo = int(tok[1])
                peek = self._peek()
                if peek is not None and peek[1] == "to":
                    self._next()
                    hi_tok = self._next()
                    if hi_tok[1] == "max":
                        hi = 536870911  # 2**29 - 1, proto3 field-number ceiling
                    else:
                        hi = int(hi_tok[1])
                else:
                    hi = lo
                msg.reserved_ranges.append((lo, hi))
            elif tok[0] == "string":
                msg.reserved_names.append(tok[1].strip('"'))
            else:
                raise ProtoParseError(
                    f"bad reserved entry {tok[1]!r}", self.filename, tok[2]
                )
            tok = self._next()
            if tok[1] == ";":
                return
            if tok[1] != ",":
                raise ProtoParseError(
                    f"expected ',' or ';' in reserved, got {tok[1]!r}",
                    self.filename,
                    tok[2],
                )

    def parse_enum(self) -> EnumSpec:
        _, _, line = self._expect("enum")
        name = self._expect_kind("ident")[1]
        enum = EnumSpec(name=name, proto=self.filename, line=line)
        self._expect("{")
        while True:
            tok = self._next()
            if tok[1] == "}":
                return enum
            if tok[1] == "option":
                self._skip_statement()
                continue
            if tok[1] == ";":
                continue
            if tok[0] != "ident":
                raise ProtoParseError(
                    f"expected enum value name, got {tok[1]!r}", self.filename, tok[2]
                )
            vname, vline = tok[1], tok[2]
            self._expect("=")
            number = int(self._expect_kind("number")[1])
            nxt = self._next()
            if nxt[1] == "[":
                while self._next()[1] != "]":
                    pass
                nxt = self._next()
            if nxt[1] != ";":
                raise ProtoParseError(
                    f"expected ';' after enum value, got {nxt[1]!r}",
                    self.filename,
                    nxt[2],
                )
            enum.values.append(EnumValueSpec(name=vname, number=number, line=vline))

    def parse_service(self) -> ServiceSpec:
        _, _, line = self._expect("service")
        name = self._expect_kind("ident")[1]
        svc = ServiceSpec(name=name, proto=self.filename, line=line)
        self._expect("{")
        while True:
            tok = self._next()
            if tok[1] == "}":
                return svc
            if tok[1] == ";":
                continue
            if tok[1] == "option":
                self._skip_statement()
                continue
            if tok[1] != "rpc":
                raise ProtoParseError(
                    f"expected 'rpc', got {tok[1]!r}", self.filename, tok[2]
                )
            mline = tok[2]
            mname = self._expect_kind("ident")[1]
            self._expect("(")
            request = self._rpc_type()
            self._expect(")")
            self._expect_ident("returns")
            self._expect("(")
            response = self._rpc_type()
            self._expect(")")
            nxt = self._next()
            if nxt[1] == "{":
                depth = 1
                while depth:
                    inner = self._next()
                    if inner[1] == "{":
                        depth += 1
                    elif inner[1] == "}":
                        depth -= 1
            elif nxt[1] != ";":
                raise ProtoParseError(
                    f"expected ';' or '{{' after rpc, got {nxt[1]!r}",
                    self.filename,
                    nxt[2],
                )
            svc.methods.append(
                MethodSpec(name=mname, request=request, response=response, line=mline)
            )

    def _rpc_type(self) -> str:
        tok = self._next()
        if tok[1] == "stream":
            tok = self._next()
        return tok[1]

    def _expect_ident(self, value: str) -> None:
        tok = self._next()
        if tok[1] != value:
            raise ProtoParseError(
                f"expected {value!r}, got {tok[1]!r}", self.filename, tok[2]
            )


def parse_proto_text(text: str, filename: str = "<proto>") -> ProtoFile:
    """Parse one .proto source string into a :class:`ProtoFile`."""
    parser = _Parser(_tokenize(text, filename), filename)
    parser._nested_messages = []
    parser._nested_enums = []
    pf = parser.parse_file()
    pf.messages.extend(parser._nested_messages)
    pf.enums.extend(parser._nested_enums)
    return pf


# ---------------------------------------------------------------------------
# Repo-level loading
# ---------------------------------------------------------------------------

PROTO_DIR = Path("shockwave_tpu") / "runtime" / "protobuf"

_schema_cache: Dict[Tuple[Tuple[str, float], ...], ProtoSchema] = {}


def proto_dir(root: Optional[Path] = None) -> Path:
    return (root or repo_root()) / PROTO_DIR


def load_repo_schema(root: Optional[Path] = None) -> ProtoSchema:
    """Parse every .proto under ``runtime/protobuf`` (cached by mtime)."""
    directory = proto_dir(root)
    paths = sorted(directory.glob("*.proto"))
    key = tuple((p.name, p.stat().st_mtime) for p in paths)
    schema = _schema_cache.get(key)
    if schema is None:
        schema = ProtoSchema.from_dir(directory)
        _schema_cache.clear()  # one live entry; old mtimes never recur
        _schema_cache[key] = schema
    return schema


def schema_field_numbers(schema: ProtoSchema, message: str) -> Sequence[int]:
    msg = schema.message(message)
    return sorted(msg.by_number) if msg else ()
