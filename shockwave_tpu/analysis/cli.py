"""``python -m shockwave_tpu.analysis`` — run shockwave-lint.

Exit codes: 0 clean (no findings beyond the baseline, no stale
baseline debt), 1 new findings, 2 stale baseline (ratchet: the debt
shrank but the committed ledger didn't), 3 usage/internal error.

Output formats (``--format``): ``text`` (default), ``json``
(machine-readable; ``--json`` is an alias), ``github`` (GitHub
Actions ``::error file=...`` workflow annotations — what
``scripts/ci/lint.py`` emits on CI so findings land inline on the PR
diff).

When the checked paths are a subset of the repo (``--changed-only``
pre-commit runs), baseline entries for UNCHECKED files are not
reported stale — a partial run can only see partial debt.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import List, Optional

from shockwave_tpu.analysis import baseline as baseline_mod
from shockwave_tpu.analysis.core import (
    DEFAULT_SCOPE,
    Finding,
    active,
    checked_relpaths,
    repo_root,
    run_paths,
)
from shockwave_tpu.analysis.rules import RULE_CLASSES, rule_by_name


def _parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m shockwave_tpu.analysis",
        description=(
            "shockwave-lint: repo-specific JAX-aware static analysis "
            "with a ratcheting baseline"
        ),
    )
    p.add_argument(
        "paths",
        nargs="*",
        help=f"files/dirs to check (default: {' '.join(DEFAULT_SCOPE)})",
    )
    p.add_argument(
        "--format",
        choices=("text", "json", "github"),
        default=None,
        help="output format: text (default), json, or github "
        "(::error workflow annotations for Actions)",
    )
    p.add_argument(
        "--json",
        action="store_true",
        help="machine-readable output (alias for --format json)",
    )
    p.add_argument(
        "--rules",
        help="comma-separated rule names to run (default: all)",
    )
    p.add_argument(
        "--fix",
        action="store_true",
        help="apply the available autofixes (currently: rewrite "
        'open(..., "w")+json.dump/f.write to the atomic '
        "utils/fileio helpers) in place",
    )
    p.add_argument(
        "--dry-run",
        action="store_true",
        help="with --fix: print the unified diff, write nothing",
    )
    p.add_argument(
        "--lock-graph",
        action="store_true",
        help="print the interprocedural lock acquisition-order graph "
        "as JSON (the static prediction to diff against the "
        "SHOCKWAVE_SANITIZE=locks observed order) and exit",
    )
    p.add_argument(
        "--thread-roots",
        action="store_true",
        help="print the discovered thread topology (Thread targets, "
        "RPC handler roots, control-plane roots) and the shared-state "
        "race table as JSON (the static prediction to diff against "
        "SHOCKWAVE_SANITIZE=threads) and exit",
    )
    p.add_argument(
        "--wire-registry",
        action="store_true",
        help="print the wire-contract registry derived from the "
        "current .proto schema as JSON and exit",
    )
    p.add_argument(
        "--write-wire-registry",
        action="store_true",
        help="write <repo>/wire_registry.json from the current schema "
        "(append new fields; the CI ratchet rejects renumbering, "
        "retyping, or deleting committed entries)",
    )
    p.add_argument(
        "--check-wire-registry",
        action="store_true",
        help="diff the current .proto schema against the committed "
        "wire_registry.json ratchet and exit (0 green, 1 violations, "
        "2 missing registry)",
    )
    p.add_argument(
        "--baseline",
        default=None,
        help="baseline file (default: <repo>/lint_baseline.json)",
    )
    p.add_argument(
        "--no-baseline",
        action="store_true",
        help="report every finding, ignoring the baseline",
    )
    p.add_argument(
        "--write-baseline",
        action="store_true",
        help="accept the current findings as the new (smaller) baseline",
    )
    p.add_argument(
        "--show-suppressed",
        action="store_true",
        help="also list findings silenced by inline disable comments",
    )
    p.add_argument(
        "--list-rules", action="store_true", help="print the rule catalog"
    )
    return p


def _resolve_rules(spec: Optional[str]):
    if not spec:
        return None
    rules = []
    for name in spec.split(","):
        name = name.strip()
        if not name:
            continue
        try:
            rules.append(rule_by_name(name))
        except KeyError:
            raise SystemExit(f"unknown rule {name!r}; see --list-rules")
    return rules


def _github_escape(text: str) -> str:
    """GitHub workflow-command data escaping (%, CR, LF)."""
    return (
        text.replace("%", "%25").replace("\r", "%0D").replace("\n", "%0A")
    )


def _emit_github(new: List[Finding], stale: List[dict]) -> None:
    for f in new:
        print(
            f"::error file={f.path},line={f.line},col={f.col + 1},"
            f"title=shockwave-lint {f.rule}::{_github_escape(f.message)}"
        )
    for e in stale:
        print(
            f"::warning file={e['path']},line={e['line']},"
            f"title=shockwave-lint stale baseline::"
            f"{_github_escape('finding fixed; shrink the baseline with --write-baseline')}"
        )


def _run_fix(args) -> int:
    import os

    from shockwave_tpu.analysis import fixers
    from shockwave_tpu.analysis.core import iter_python_files

    root = repo_root()
    resolved = [
        p if os.path.isabs(p) else os.path.join(root, p)
        for p in (args.paths or DEFAULT_SCOPE)
    ]
    triples = []
    for path in iter_python_files(
        [p for p in resolved if os.path.exists(p)]
    ):
        relpath = os.path.relpath(path, root).replace(os.sep, "/")
        with open(path, encoding="utf-8") as f:
            triples.append((path, relpath, f.read()))
    descriptions, diff = fixers.fix_files(triples, dry_run=args.dry_run)
    if args.dry_run:
        if diff:
            print(diff, end="")
        print(
            f"shockwave-lint --fix --dry-run: {len(descriptions)} "
            "rewrite(s) available (nothing written)"
        )
    else:
        for d in descriptions:
            print(f"fixed {d}")
        print(f"shockwave-lint --fix: {len(descriptions)} rewrite(s) applied")
    return 0


def _run_wire_registry(args) -> int:
    from shockwave_tpu.analysis import protospec, wireregistry

    schema = protospec.load_repo_schema()
    path = wireregistry.default_registry_path()
    if args.wire_registry:
        print(json.dumps(wireregistry.make_registry(schema), indent=2))
        return 0
    if args.write_wire_registry:
        registry = wireregistry.make_registry(schema)
        committed = wireregistry.load_registry(path)
        if committed is not None:
            # Writing may only APPEND: refuse to paper over a ratchet
            # violation by regenerating the ledger around it.
            problems = [
                p
                for p in wireregistry.diff_registry(schema, committed)
                if "is not in" not in p
            ]
            if problems:
                for p in problems:
                    print(f"wire-registry: {p}", file=sys.stderr)
                print(
                    "refusing to rewrite the registry over ratchet "
                    "violations; fix the schema instead",
                    file=sys.stderr,
                )
                return 1
        wireregistry.save_registry(path, registry)
        print(f"wrote {path} with {len(registry['entries'])} entries")
        return 0
    committed = wireregistry.load_registry(path)
    if committed is None:
        print(
            f"wire-registry: {path} missing — the schema-evolution "
            "ratchet is not in place (generate it with "
            "--write-wire-registry and commit it)",
            file=sys.stderr,
        )
        return 2
    problems = wireregistry.diff_registry(schema, committed)
    for p in problems:
        print(f"wire-registry: {p}")
    print(
        f"wire-registry: {len(committed.get('entries', []))} committed "
        f"entries, {len(problems)} violation(s)"
    )
    return 1 if problems else 0


def main(argv: Optional[List[str]] = None) -> int:
    args = _parser().parse_args(argv)
    fmt = args.format or ("json" if args.json else "text")

    if args.list_rules:
        for cls in RULE_CLASSES:
            print(f"{cls.name}: {cls.description}")
            print(f"    why: {cls.rationale}")
        return 0

    if args.lock_graph:
        from shockwave_tpu.analysis.rules.interproc import lock_graph_dict

        print(json.dumps(lock_graph_dict(), indent=2))
        return 0

    if args.thread_roots:
        from shockwave_tpu.analysis.rules.races import thread_roots_dict

        print(json.dumps(thread_roots_dict(), indent=2))
        return 0

    if args.wire_registry or args.write_wire_registry or args.check_wire_registry:
        return _run_wire_registry(args)

    if args.fix:
        return _run_fix(args)

    try:
        rules = _resolve_rules(args.rules)
    except SystemExit as e:
        print(e, file=sys.stderr)
        return 3

    findings = run_paths(args.paths or None, rules=rules)
    act = active(findings)
    suppressed = [f for f in findings if f.suppressed]

    baseline_path = args.baseline or baseline_mod.default_baseline_path()
    if args.write_baseline:
        bl = baseline_mod.make_baseline(act)
        baseline_mod.save_baseline(baseline_path, bl)
        print(
            f"wrote {baseline_path} with {len(bl['entries'])} accepted "
            "finding(s)"
        )
        return 0

    if args.no_baseline:
        new, stale = act, []
    else:
        bl = baseline_mod.load_baseline(baseline_path)
        new, stale = baseline_mod.diff_against_baseline(act, bl)
        if args.paths:
            # Partial run: only entries for files we actually checked
            # can be judged stale.
            checked = checked_relpaths(args.paths)
            stale = [e for e in stale if e["path"] in checked]

    if fmt == "json":
        print(
            json.dumps(
                {
                    "checked_root": repo_root(),
                    "total_findings": len(act),
                    "suppressed": len(suppressed),
                    "new_findings": [f.to_dict() for f in new],
                    "stale_baseline_entries": stale,
                    "findings": [f.to_dict() for f in act],
                },
                indent=2,
            )
        )
    elif fmt == "github":
        _emit_github(new, stale)
        print(
            f"shockwave-lint: {len(act)} finding(s) "
            f"({len(new)} new, {len(stale)} stale baseline "
            f"entr{'y' if len(stale) == 1 else 'ies'})"
        )
    else:
        report = new if not args.no_baseline else act
        for f in report:
            print(f.render())
        if args.show_suppressed:
            for f in suppressed:
                print(f"{f.render()}  [suppressed]")
        for e in stale:
            print(
                f"stale baseline entry {e['path']}:{e['line']} "
                f"[{e['rule']}] — finding fixed; shrink the baseline "
                "with --write-baseline"
            )
        print(
            f"shockwave-lint: {len(act)} finding(s) "
            f"({len(new)} new, {len(suppressed)} suppressed, "
            f"{len(stale)} stale baseline entr{'y' if len(stale) == 1 else 'ies'})"
        )

    if new:
        return 1
    if stale:
        return 2
    return 0
