"""``python -m shockwave_tpu.analysis`` — run shockwave-lint.

Exit codes: 0 clean (no findings beyond the baseline, no stale
baseline debt), 1 new findings, 2 stale baseline (ratchet: the debt
shrank but the committed ledger didn't), 3 usage/internal error.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import List, Optional

from shockwave_tpu.analysis import baseline as baseline_mod
from shockwave_tpu.analysis.core import (
    DEFAULT_SCOPE,
    Finding,
    active,
    repo_root,
    run_paths,
)
from shockwave_tpu.analysis.rules import RULE_CLASSES, rule_by_name


def _parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m shockwave_tpu.analysis",
        description=(
            "shockwave-lint: repo-specific JAX-aware static analysis "
            "with a ratcheting baseline"
        ),
    )
    p.add_argument(
        "paths",
        nargs="*",
        help=f"files/dirs to check (default: {' '.join(DEFAULT_SCOPE)})",
    )
    p.add_argument("--json", action="store_true", help="machine-readable output")
    p.add_argument(
        "--rules",
        help="comma-separated rule names to run (default: all)",
    )
    p.add_argument(
        "--baseline",
        default=None,
        help="baseline file (default: <repo>/lint_baseline.json)",
    )
    p.add_argument(
        "--no-baseline",
        action="store_true",
        help="report every finding, ignoring the baseline",
    )
    p.add_argument(
        "--write-baseline",
        action="store_true",
        help="accept the current findings as the new (smaller) baseline",
    )
    p.add_argument(
        "--show-suppressed",
        action="store_true",
        help="also list findings silenced by inline disable comments",
    )
    p.add_argument(
        "--list-rules", action="store_true", help="print the rule catalog"
    )
    return p


def _resolve_rules(spec: Optional[str]):
    if not spec:
        return None
    rules = []
    for name in spec.split(","):
        name = name.strip()
        if not name:
            continue
        try:
            rules.append(rule_by_name(name))
        except KeyError:
            raise SystemExit(f"unknown rule {name!r}; see --list-rules")
    return rules


def main(argv: Optional[List[str]] = None) -> int:
    args = _parser().parse_args(argv)

    if args.list_rules:
        for cls in RULE_CLASSES:
            print(f"{cls.name}: {cls.description}")
            print(f"    why: {cls.rationale}")
        return 0

    try:
        rules = _resolve_rules(args.rules)
    except SystemExit as e:
        print(e, file=sys.stderr)
        return 3

    findings = run_paths(args.paths or None, rules=rules)
    act = active(findings)
    suppressed = [f for f in findings if f.suppressed]

    baseline_path = args.baseline or baseline_mod.default_baseline_path()
    if args.write_baseline:
        bl = baseline_mod.make_baseline(act)
        baseline_mod.save_baseline(baseline_path, bl)
        print(
            f"wrote {baseline_path} with {len(bl['entries'])} accepted "
            "finding(s)"
        )
        return 0

    if args.no_baseline:
        new, stale = act, []
    else:
        bl = baseline_mod.load_baseline(baseline_path)
        new, stale = baseline_mod.diff_against_baseline(act, bl)

    if args.json:
        print(
            json.dumps(
                {
                    "checked_root": repo_root(),
                    "total_findings": len(act),
                    "suppressed": len(suppressed),
                    "new_findings": [f.to_dict() for f in new],
                    "stale_baseline_entries": stale,
                    "findings": [f.to_dict() for f in act],
                },
                indent=2,
            )
        )
    else:
        report = new if not args.no_baseline else act
        for f in report:
            print(f.render())
        if args.show_suppressed:
            for f in suppressed:
                print(f"{f.render()}  [suppressed]")
        for e in stale:
            print(
                f"stale baseline entry {e['path']}:{e['line']} "
                f"[{e['rule']}] — finding fixed; shrink the baseline "
                "with --write-baseline"
            )
        print(
            f"shockwave-lint: {len(act)} finding(s) "
            f"({len(new)} new, {len(suppressed)} suppressed, "
            f"{len(stale)} stale baseline entr{'y' if len(stale) == 1 else 'ies'})"
        )

    if new:
        return 1
    if stale:
        return 2
    return 0
