"""shockwave-lint: repo-specific, JAX-aware static analysis.

The rule catalog targets the hazard classes this codebase actually
has (donated-buffer reuse, host syncs in hot loops, PRNG key reuse,
unlocked shared-state mutation, non-atomic artifact writes, solver
backend interface drift — and, interprocedurally, lock-order cycles,
transitive host syncs, swallowed exceptions, shared-state races
across the discovered thread topology, and snapshot escapes from the
speculation clone's deep-copy contract); a committed baseline
ratchets the repo-wide finding count monotonically toward zero. CLI:
``python -m shockwave_tpu.analysis`` (see ``docs/USAGE.md``).

This ``__init__`` is LAZY (PEP 562): production modules (obs, runtime,
native, the solver) import :mod:`shockwave_tpu.analysis.sanitize` on
their hot import paths, and reaching it must not pay for the whole
rule catalog — the exports below resolve on first attribute access.
"""

import importlib

# name -> submodule that defines it.
_EXPORTS = {
    "default_baseline_path": "baseline",
    "diff_against_baseline": "baseline",
    "load_baseline": "baseline",
    "make_baseline": "baseline",
    "save_baseline": "baseline",
    "DEFAULT_SCOPE": "core",
    "FileContext": "core",
    "Finding": "core",
    "ProjectRule": "core",
    "Rule": "core",
    "active": "core",
    "check_source": "core",
    "checked_relpaths": "core",
    "repo_root": "core",
    "run_paths": "core",
    "RULE_CLASSES": "rules",
    "default_rules": "rules",
    "rule_by_name": "rules",
    "ProtoSchema": "protospec",
    "load_repo_schema": "protospec",
    "parse_proto_text": "protospec",
    "default_registry_path": "wireregistry",
    "diff_registry": "wireregistry",
    "load_registry": "wireregistry",
    "make_registry": "wireregistry",
    "save_registry": "wireregistry",
}

__all__ = sorted(_EXPORTS)


def __getattr__(name):
    try:
        modname = _EXPORTS[name]
    except KeyError:
        raise AttributeError(
            f"module {__name__!r} has no attribute {name!r}"
        ) from None
    value = getattr(
        importlib.import_module(f".{modname}", __name__), name
    )
    globals()[name] = value  # cache: subsequent access skips __getattr__
    return value


def __dir__():
    return sorted(set(globals()) | set(_EXPORTS))
