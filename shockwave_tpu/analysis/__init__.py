"""shockwave-lint: repo-specific, JAX-aware static analysis.

The rule catalog targets the hazard classes this codebase actually
has (donated-buffer reuse, host syncs in hot loops, PRNG key reuse,
unlocked shared-state mutation, non-atomic artifact writes, solver
backend interface drift); a committed baseline ratchets the repo-wide
finding count monotonically toward zero. CLI:
``python -m shockwave_tpu.analysis`` (see ``docs/USAGE.md``).
"""

from shockwave_tpu.analysis.baseline import (
    default_baseline_path,
    diff_against_baseline,
    load_baseline,
    make_baseline,
    save_baseline,
)
from shockwave_tpu.analysis.core import (
    DEFAULT_SCOPE,
    FileContext,
    Finding,
    Rule,
    active,
    check_source,
    repo_root,
    run_paths,
)
from shockwave_tpu.analysis.rules import RULE_CLASSES, default_rules, rule_by_name

__all__ = [
    "DEFAULT_SCOPE",
    "FileContext",
    "Finding",
    "Rule",
    "RULE_CLASSES",
    "active",
    "check_source",
    "default_baseline_path",
    "default_rules",
    "diff_against_baseline",
    "load_baseline",
    "make_baseline",
    "repo_root",
    "rule_by_name",
    "run_paths",
    "save_baseline",
]
