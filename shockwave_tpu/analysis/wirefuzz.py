"""Schema-derived differential wire fuzzer.

The wirecheck rules prove the codecs AGREE with the ``.proto`` files
statically; this module proves the bytes agree at runtime. From the
parsed schema (:mod:`.protospec`) it generates seeded random message
instances and, per message family:

* **protoc differential** — the same instance built through a
  *dynamically generated* protoc message class (a
  ``FileDescriptorProto`` synthesized from the schema model, so every
  hand-rolled message gets a real protoc counterpart without protoc in
  the build) must serialize byte-for-byte identically;
* **round-trip** — ``FromString(SerializeToString(x))`` must
  reproduce every field and re-serialize to the same bytes;
* **unknown-field tolerance** — appending/prepending unknown fields
  (wire types 0/1/2/5, numbers above the schema's) must parse cleanly
  with the known fields intact (proto3 forward compatibility);
* **truncation tolerance** — any byte-prefix must either parse or
  raise ``ValueError`` — never an ``IndexError``/``struct.error``
  escape (hostile-peer hygiene);
* **legacy goldens** — instances restricted to the pre-extension
  field set must be byte-identical to the frozen protoc modules under
  ``runtime/protobuf/legacy/`` in both directions, and full
  new-schema bytes must parse cleanly through the legacy parser (the
  old-reader contract every rolling upgrade depends on);
* **columnar differential** — ``encode_columnar_block`` /
  ``decode_columnar_block`` round-trip spec dicts exactly, the frame
  re-serializes canonically through the protoc mirror, and
  ``FastSubmitRequest`` decodes the legacy encoding to the same
  columns.

Everything is deterministic in ``seed`` (per-case RNGs are keyed
``seed:family:index``), so a CI failure replays locally with the same
number. One deliberate, documented divergence is excluded by the
generator: ``DoneRequest.trace_context`` omits an all-empty repeated
string list entirely (legacy byte identity — see the codec comment),
where protoc would serialize the empty elements, so non-empty
generated lists always carry at least one non-empty element.

Gate entry points: :func:`fuzz_schema` (report dict) and
:func:`descriptor_conformance_problems` (the protoc-generated and
legacy modules' runtime descriptors checked against the schema),
both consumed by ``scripts/ci/wire_smoke.py``.
"""

from __future__ import annotations

import hashlib
import importlib
import random
import struct
from typing import Dict, List, Optional, Sequence

from shockwave_tpu.analysis import protospec

DEFAULT_SEED = 20260807
DEFAULT_CASES = 50

#: proto file -> hand-rolled codec module (import name under
#: shockwave_tpu.runtime.protobuf).
HANDROLLED_MODULES = {
    "admission.proto": "admission_pb2",
    "explain.proto": "explain_pb2",
    "scheduler_to_worker.proto": "scheduler_to_worker_pb2",
    "telemetry.proto": "telemetry_pb2",
    "worker_to_scheduler.proto": "worker_to_scheduler_pb2",
}

#: proto file -> real protoc-generated module (descriptor-checked, not
#: fuzzed — google.protobuf's own codec is the authority there).
PROTOC_MODULES = {
    "common.proto": "common_pb2",
    "enums.proto": "enums_pb2",
    "iterator_to_scheduler.proto": "iterator_to_scheduler_pb2",
}

#: frozen pre-extension protoc modules (the byte-identity goldens).
LEGACY_MODULES = {
    "worker_to_scheduler.proto": "legacy.worker_to_scheduler_pb2",
    "scheduler_to_worker.proto": "legacy.scheduler_to_worker_pb2",
}

_RUNTIME_PKG = "shockwave_tpu.runtime.protobuf"

_MAX_UINT32 = 2**32 - 1
_MAX_UINT64 = 2**64 - 1

_STRING_POOL = (
    "",
    "a",
    "resnet50",
    "Model (batch size 32)",
    "accordion",
    "tenant-α/β✓",
    "x" * 40,
)

_DOUBLE_POOL = (0.0, 1.0, -2.5, 0.125, 3.5, 1e-300, 1e300, 17.25)


def _import_runtime(modname: str):
    return importlib.import_module(f"{_RUNTIME_PKG}.{modname}")


def codec_index(schema) -> Dict[str, type]:
    """message name -> hand-rolled codec class, across the hand-rolled
    modules (JobState lives in worker_to_scheduler_pb2 though declared
    in common.proto)."""
    index: Dict[str, type] = {}
    names = {msg.name for msg in schema.messages}
    for modname in HANDROLLED_MODULES.values():
        module = _import_runtime(modname)
        for name in names:
            cls = getattr(module, name, None)
            if cls is not None and name not in index:
                index[name] = cls
    return index


# ---------------------------------------------------------------------------
# Dynamic protoc mirror
# ---------------------------------------------------------------------------

_SCALAR_TYPE_CODES = {
    "double": 1,
    "float": 2,
    "int64": 3,
    "uint64": 4,
    "int32": 5,
    "fixed64": 6,
    "fixed32": 7,
    "bool": 8,
    "string": 9,
    "bytes": 12,
    "uint32": 13,
    "sfixed32": 15,
    "sfixed64": 16,
    "sint32": 17,
    "sint64": 18,
}

_MIRROR_PACKAGE = "shockwave_fuzz"


def build_protoc_mirror(schema) -> Optional[Dict[str, type]]:
    """message name -> dynamically generated protoc class mirroring the
    schema, or None when google.protobuf is unavailable."""
    try:
        from google.protobuf import (
            descriptor_pb2,
            descriptor_pool,
            message_factory,
        )
    except Exception:
        return None
    fdp = descriptor_pb2.FileDescriptorProto()
    fdp.name = f"{_MIRROR_PACKAGE}/mirror.proto"
    fdp.package = _MIRROR_PACKAGE
    fdp.syntax = "proto3"
    enum_names = {e.name for e in schema.enums}
    for enum in schema.enums:
        edp = fdp.enum_type.add()
        edp.name = enum.name
        for value in enum.values:
            vdp = edp.value.add()
            vdp.name = f"{enum.name}_{value.name}"  # avoid C++-scope clashes
            vdp.number = value.number
    for msg in schema.messages:
        mdp = fdp.message_type.add()
        mdp.name = msg.name
        for fld in msg.fields:
            fdp_field = mdp.field.add()
            fdp_field.name = fld.name
            fdp_field.number = fld.number
            fdp_field.label = 3 if fld.repeated else 1
            if fld.type in _SCALAR_TYPE_CODES:
                fdp_field.type = _SCALAR_TYPE_CODES[fld.type]
            elif fld.type in enum_names:
                fdp_field.type = 14
                fdp_field.type_name = f".{_MIRROR_PACKAGE}.{fld.type}"
            else:
                fdp_field.type = 11
                fdp_field.type_name = f".{_MIRROR_PACKAGE}.{fld.type}"
    pool = descriptor_pool.DescriptorPool()
    file_desc = pool.Add(fdp)
    return {
        msg.name: message_factory.GetMessageClass(
            file_desc.message_types_by_name[msg.name]
        )
        for msg in schema.messages
    }


def _fill_protoc(mirror_msg, schema, spec, values: dict) -> None:
    for fld in spec.fields:
        value = values.get(fld.name)
        if value is None:
            continue
        if fld.repeated:
            target = getattr(mirror_msg, fld.name)
            if fld.kind == "message":
                sub_spec = schema.message(fld.type)
                for sub_values in value:
                    _fill_protoc(target.add(), schema, sub_spec, sub_values)
            else:
                target.extend(value)
        elif fld.kind == "message":
            _fill_protoc(
                getattr(mirror_msg, fld.name),
                schema,
                schema.message(fld.type),
                value,
            )
        else:
            setattr(mirror_msg, fld.name, value)


# ---------------------------------------------------------------------------
# Value generation
# ---------------------------------------------------------------------------

def _gen_scalar(rng: random.Random, schema, fld):
    if fld.kind == "enum":
        enum = schema.enum(fld.type)
        return rng.choice([v.number for v in enum.values])
    if fld.type == "bool":
        return rng.random() < 0.5
    if fld.kind == "varint":
        cap = _MAX_UINT32 if fld.type == "uint32" else _MAX_UINT64
        return rng.choice(
            (0, 1, 7, 300, 65536, cap // 3, cap - 1, rng.randrange(cap))
        )
    if fld.kind == "fixed64":
        return rng.choice(_DOUBLE_POOL + (rng.random() * 100.0,))
    if fld.kind == "string":
        return rng.choice(_STRING_POOL)
    if fld.kind == "bytes":
        return bytes(rng.getrandbits(8) for _ in range(rng.randrange(0, 16)))
    raise AssertionError(f"unhandled scalar kind {fld.kind}")


def _gen_field(rng: random.Random, schema, fld, depth: int, restrict=None):
    if not fld.repeated:
        if fld.kind == "message":
            return _gen_message(
                rng, schema, schema.message(fld.type), depth + 1, restrict
            )
        return _gen_scalar(rng, schema, fld)
    count = rng.choice((0, 1, 2, 4)) if depth == 0 else rng.choice((0, 1, 2))
    if fld.kind == "message":
        sub_spec = schema.message(fld.type)
        return [
            _gen_message(rng, schema, sub_spec, depth + 1, restrict)
            for _ in range(count)
        ]
    values = [_gen_scalar(rng, schema, fld) for _ in range(count)]
    if fld.kind == "string" and values and not any(values):
        # Deliberate divergence exclusion: hand-rolled codecs omit an
        # all-empty repeated string list for legacy byte identity,
        # where protoc serializes the empty elements.
        values[rng.randrange(len(values))] = rng.choice(_STRING_POOL[1:])
    return values


def _gen_message(
    rng: random.Random, schema, spec, depth: int = 0, restrict=None
) -> dict:
    """Generate a values dict for ``spec``. ``restrict`` is an optional
    protoc Descriptor (the frozen legacy shape): only its field numbers
    are populated, recursively — nested messages are restricted to the
    legacy sub-descriptor too."""
    values = {}
    for fld in spec.fields:
        sub_restrict = None
        if restrict is not None:
            legacy_fld = restrict.fields_by_number.get(fld.number)
            if legacy_fld is None:
                continue
            if fld.kind == "message":
                sub_restrict = legacy_fld.message_type
        values[fld.name] = _gen_field(rng, schema, fld, depth, sub_restrict)
    return values


def _build_handrolled(index, schema, spec, values: dict):
    kwargs = {}
    for fld in spec.fields:
        value = values.get(fld.name)
        if value is None:
            continue
        if fld.kind == "message":
            sub_spec = schema.message(fld.type)
            if fld.repeated:
                kwargs[fld.name] = [
                    _build_handrolled(index, schema, sub_spec, sub)
                    for sub in value
                ]
            else:
                kwargs[fld.name] = _build_handrolled(
                    index, schema, sub_spec, value
                )
        else:
            kwargs[fld.name] = value
    return index[spec.name](**kwargs)


def _equals(schema, spec, obj, values: dict) -> bool:
    for fld in spec.fields:
        want = values.get(fld.name)
        if want is None:
            continue
        got = getattr(obj, fld.name)
        if fld.kind == "message":
            sub_spec = schema.message(fld.type)
            if fld.repeated:
                if len(got) != len(want):
                    return False
                if not all(
                    _equals(schema, sub_spec, g, w) for g, w in zip(got, want)
                ):
                    return False
            elif not _equals(schema, sub_spec, got, want):
                return False
        elif fld.repeated:
            if [_norm(fld, v) for v in got] != [_norm(fld, v) for v in want]:
                return False
        elif _norm(fld, got) != _norm(fld, want):
            return False
    return True


def _norm(fld, value):
    if fld.type == "bool":
        return bool(value)
    if fld.kind == "varint" or fld.kind == "enum":
        return int(value)
    if fld.kind == "fixed64":
        return float(value)
    return value


# ---------------------------------------------------------------------------
# Mutations
# ---------------------------------------------------------------------------

def _unknown_fields_blob(rng: random.Random, first_free: int) -> bytes:
    from shockwave_tpu.runtime.protobuf.wire import encode_varint, tag

    out = bytearray()
    for _ in range(rng.randint(1, 3)):
        number = rng.randint(first_free, first_free + 40)
        wt = rng.choice((0, 1, 2, 5))
        if wt == 0:
            out += tag(number, 0) + encode_varint(rng.randrange(_MAX_UINT64))
        elif wt == 1:
            out += tag(number, 1) + struct.pack("<d", rng.random())
        elif wt == 2:
            blob = bytes(rng.getrandbits(8) for _ in range(rng.randrange(0, 9)))
            out += tag(number, 2) + encode_varint(len(blob)) + blob
        else:
            out += tag(number, 5) + struct.pack("<f", 1.5)
    return bytes(out)


# ---------------------------------------------------------------------------
# The fuzz run
# ---------------------------------------------------------------------------

def fuzz_schema(
    schema=None,
    cases: int = DEFAULT_CASES,
    seed: int = DEFAULT_SEED,
    messages: Optional[Sequence[str]] = None,
) -> dict:
    """Run the differential fuzz over every hand-rolled message family
    (plus legacy goldens and the columnar frame). Returns a report
    dict; ``report["failures"]`` empty means the gate is green."""
    schema = schema or protospec.load_repo_schema()
    index = codec_index(schema)
    mirror = build_protoc_mirror(schema)
    report: dict = {
        "seed": seed,
        "cases_per_family": cases,
        "families": {},
        "failures": [],
        "skipped": [],
    }
    if mirror is None:
        report["skipped"].append(
            "protoc-differential (google.protobuf unavailable)"
        )
    fuzzed = sorted(name for name in index if messages is None or name in messages)
    for name in fuzzed:
        _fuzz_family(report, schema, index, mirror, name, cases, seed)
    if messages is None:
        _fuzz_legacy(report, schema, index, cases, seed)
        _fuzz_columnar(report, mirror, cases, seed)
    return report


def _family(report: dict, name: str) -> dict:
    fam = report["families"].setdefault(
        name, {"cases": 0, "digest": hashlib.sha256()}
    )
    return fam


def _finish_digests(report: dict) -> dict:
    for fam in report["families"].values():
        if not isinstance(fam["digest"], str):
            fam["digest"] = fam["digest"].hexdigest()[:16]
    return report


def _fail(report: dict, message: str) -> None:
    if len(report["failures"]) < 50:
        report["failures"].append(message)


def _fuzz_family(report, schema, index, mirror, name, cases, seed) -> None:
    spec = schema.message(name)
    cls = index[name]
    fam = _family(report, name)
    max_number = max(spec.by_number, default=0)
    for i in range(cases):
        rng = random.Random(f"{seed}:{name}:{i}")
        tagline = f"{name} case {i} (seed {seed})"
        values = _gen_message(rng, schema, spec)
        try:
            obj = _build_handrolled(index, schema, spec, values)
            data = obj.SerializeToString()
        except Exception as e:
            # A crash on schema-legal values is itself a codec/schema
            # disagreement, not fuzzer infrastructure.
            _fail(report, f"{tagline}: codec crashed on encode: {e!r}")
            fam["cases"] += 1
            continue
        fam["cases"] += 1
        fam["digest"].update(data)
        if mirror is not None:
            m = mirror[name]()
            _fill_protoc(m, schema, spec, values)
            protoc_bytes = m.SerializeToString()
            if protoc_bytes != data:
                _fail(
                    report,
                    f"{tagline}: hand-rolled bytes differ from protoc "
                    f"({data.hex()} != {protoc_bytes.hex()})",
                )
            try:
                m2 = mirror[name].FromString(data)
            except Exception as e:  # pragma: no cover - defensive
                _fail(report, f"{tagline}: protoc failed to parse: {e!r}")
            else:
                if m2.SerializeToString() != data:
                    _fail(
                        report,
                        f"{tagline}: protoc re-serialization differs "
                        "(non-canonical hand-rolled encoding)",
                    )
        try:
            back = cls.FromString(data)
            if not _equals(schema, spec, back, values):
                _fail(report, f"{tagline}: round-trip changed field values")
            if back.SerializeToString() != data:
                _fail(
                    report, f"{tagline}: round-trip re-serialization differs"
                )
        except Exception as e:
            _fail(report, f"{tagline}: codec crashed on round-trip: {e!r}")
        # Unknown-field tolerance: inject at field boundaries.
        blob = _unknown_fields_blob(rng, max_number + 1)
        mutated = blob + data if rng.random() < 0.5 else data + blob
        try:
            tolerant = cls.FromString(mutated)
        except Exception as e:
            _fail(
                report,
                f"{tagline}: decoder raised on unknown fields: {e!r}",
            )
        else:
            if not _equals(schema, spec, tolerant, values):
                _fail(
                    report,
                    f"{tagline}: unknown-field injection corrupted "
                    "known fields",
                )
        # Truncation tolerance: ValueError or success, nothing else.
        for _ in range(3):
            if len(data) < 2:
                break
            cut = rng.randrange(1, len(data))
            try:
                cls.FromString(data[:cut])
            except ValueError:
                pass
            except Exception as e:
                _fail(
                    report,
                    f"{tagline}: truncation at {cut} escaped as "
                    f"{type(e).__name__}: {e!r}",
                )


def _fuzz_legacy(report, schema, index, cases, seed) -> None:
    for proto_name, legacy_modname in LEGACY_MODULES.items():
        try:
            legacy_mod = _import_runtime(legacy_modname)
        except Exception:
            report["skipped"].append(
                f"legacy goldens for {proto_name} (google.protobuf "
                "unavailable)"
            )
            continue
        for msg_name in sorted(
            legacy_mod.DESCRIPTOR.message_types_by_name
        ):
            ldesc = legacy_mod.DESCRIPTOR.message_types_by_name[msg_name]
            spec = schema.message(msg_name)
            if spec is None or msg_name not in index:
                _fail(
                    report,
                    f"legacy golden {msg_name}: no live schema/codec "
                    "counterpart",
                )
                continue
            legacy_cls = getattr(legacy_mod, msg_name)
            fam = _family(report, f"legacy:{msg_name}")
            for i in range(cases):
                rng = random.Random(f"{seed}:legacy:{msg_name}:{i}")
                values = _gen_message(rng, schema, spec, restrict=ldesc)
                obj = _build_handrolled(index, schema, spec, values)
                data = obj.SerializeToString()
                fam["cases"] += 1
                fam["digest"].update(data)
                tagline = f"legacy {msg_name} case {i} (seed {seed})"
                golden = legacy_cls()
                _fill_protoc(golden, schema, spec, values)
                golden_bytes = golden.SerializeToString()
                if golden_bytes != data:
                    _fail(
                        report,
                        f"{tagline}: hand-rolled bytes differ from the "
                        f"frozen protoc golden ({data.hex()} != "
                        f"{golden_bytes.hex()})",
                    )
                back = index[msg_name].FromString(golden_bytes)
                if not _equals(schema, spec, back, values):
                    _fail(
                        report,
                        f"{tagline}: hand-rolled parse of golden bytes "
                        "changed values",
                    )
                # Old-reader contract: a FULL new-schema instance must
                # parse cleanly through the legacy parser.
                full_rng = random.Random(f"{seed}:legacyfull:{msg_name}:{i}")
                full_values = _gen_message(full_rng, schema, spec)
                full_bytes = _build_handrolled(
                    index, schema, spec, full_values
                ).SerializeToString()
                try:
                    legacy_cls.FromString(full_bytes)
                except Exception as e:
                    _fail(
                        report,
                        f"{tagline}: legacy parser rejected new-schema "
                        f"bytes: {e!r}",
                    )


def _random_spec_dict(rng: random.Random) -> dict:
    return {
        "job_type": rng.choice(_STRING_POOL),
        "command": rng.choice(_STRING_POOL),
        "working_directory": rng.choice(_STRING_POOL),
        "num_steps_arg": rng.choice(_STRING_POOL),
        "total_steps": rng.choice((0, 1, 500, 2**40)),
        "scale_factor": rng.choice((0, 1, 8)),
        "mode": rng.choice(("", "static", "accordion", "gns")),
        "priority_weight": rng.choice((0.0, 1.0, 2.5)),
        "slo": rng.choice((0.0, 3600.0)),
        "duration": rng.choice((0.0, 120.5)),
        "needs_data_dir": rng.random() < 0.5,
        "tenant": rng.choice(_STRING_POOL),
        "trace_context": rng.choice(_STRING_POOL),
    }


def _fuzz_columnar(report, mirror, cases, seed) -> None:
    try:
        from shockwave_tpu.runtime.protobuf import admission_pb2, fastwire
    except Exception as e:  # pragma: no cover - numpy always present
        report["skipped"].append(f"columnar (fastwire unavailable: {e!r})")
        return
    fam = _family(report, "columnar:ColumnarJobBlock")
    mirror_cls = mirror.get("ColumnarJobBlock") if mirror else None
    for i in range(cases):
        rng = random.Random(f"{seed}:columnar:{i}")
        specs = [_random_spec_dict(rng) for _ in range(rng.choice((0, 1, 2, 5)))]
        block = fastwire.encode_columnar_block(specs)
        fam["cases"] += 1
        fam["digest"].update(block)
        tagline = f"columnar case {i} (seed {seed})"
        cols = fastwire.decode_columnar_block(block)
        if cols.to_spec_dicts() != specs:
            _fail(report, f"{tagline}: columnar round-trip changed specs")
            continue
        if mirror_cls is not None:
            m = mirror_cls.FromString(block)
            if m.SerializeToString() != block:
                _fail(
                    report,
                    f"{tagline}: block is not canonical proto3 "
                    "(protoc re-serialization differs)",
                )
            if int(m.num_jobs) != len(specs):
                _fail(report, f"{tagline}: num_jobs mismatch via protoc")
        # The legacy repeated-JobSpec encoding must decode to the SAME
        # columns through FastSubmitRequest (decision identity).
        request = admission_pb2.SubmitJobsRequest(
            token="t",
            jobs=[admission_pb2.JobSpec(**spec) for spec in specs],
        )
        fast = fastwire.FastSubmitRequest.FromString(
            request.SerializeToString()
        )
        if fast.columns.to_spec_dicts() != specs:
            _fail(
                report,
                f"{tagline}: FastSubmitRequest columns diverge from "
                "the scalar decode",
            )
        # And the columnar frame carried inside a request decodes
        # identically.
        framed = admission_pb2.SubmitJobsRequest(
            token="t", jobs_columnar=block, wire_caps=fastwire.CAP_COLUMNAR
        )
        fast2 = fastwire.FastSubmitRequest.FromString(
            framed.SerializeToString()
        )
        if fast2.columns.to_spec_dicts() != specs:
            _fail(
                report,
                f"{tagline}: framed columnar decode diverges from specs",
            )


# ---------------------------------------------------------------------------
# Descriptor conformance (protoc-generated + legacy modules)
# ---------------------------------------------------------------------------

_DESCRIPTOR_KIND = {
    1: "fixed64",  # double
    2: "fixed32",  # float
    3: "varint",  # int64
    4: "varint",  # uint64
    5: "varint",  # int32
    6: "fixed64",
    7: "fixed32",
    8: "varint",  # bool
    9: "string",
    11: "message",
    12: "bytes",
    13: "varint",  # uint32
    14: "enum",
    15: "fixed32",
    16: "fixed64",
    17: "varint",
    18: "varint",
}


def _descriptor_problems(schema, proto_name, module, subset: bool) -> List[str]:
    problems: List[str] = []
    for msg_name, desc in module.DESCRIPTOR.message_types_by_name.items():
        spec = schema.message(msg_name)
        if spec is None:
            problems.append(
                f"{module.__name__}: message {msg_name} has no live "
                "schema counterpart"
            )
            continue
        for fld in desc.fields:
            live = spec.by_number.get(fld.number)
            if live is None:
                problems.append(
                    f"{msg_name}.{fld.name} (= {fld.number}) exists in "
                    f"{module.__name__} but not in the live schema"
                )
                continue
            if live.name != fld.name:
                problems.append(
                    f"{msg_name} field {fld.number}: descriptor says "
                    f"{fld.name}, schema says {live.name}"
                )
            desc_kind = _DESCRIPTOR_KIND.get(fld.type)
            live_kind = live.kind
            if desc_kind != live_kind:
                problems.append(
                    f"{msg_name}.{fld.name}: descriptor wire kind "
                    f"{desc_kind}, schema {live_kind}"
                )
            is_rep = getattr(fld, "is_repeated", None)
            desc_repeated = bool(
                is_rep() if callable(is_rep) else is_rep
            ) if is_rep is not None else fld.label == 3
            if desc_repeated != live.repeated:
                problems.append(
                    f"{msg_name}.{fld.name}: descriptor "
                    f"{'repeated' if desc_repeated else 'singular'}, "
                    f"schema the opposite"
                )
        if not subset:
            desc_numbers = {f.number for f in desc.fields}
            for fld in spec.fields:
                if fld.number not in desc_numbers:
                    problems.append(
                        f"{msg_name}.{fld.name} (= {fld.number}) in "
                        f"{proto_name} is missing from "
                        f"{module.__name__}'s descriptor — regenerate "
                        "the protoc module"
                    )
    for enum_name, desc in getattr(
        module.DESCRIPTOR, "enum_types_by_name", {}
    ).items():
        enum = schema.enum(enum_name)
        if enum is None:
            problems.append(
                f"{module.__name__}: enum {enum_name} has no live "
                "schema counterpart"
            )
            continue
        live_values = {v.number: v.name for v in enum.values}
        for value in desc.values:
            if value.number not in live_values:
                problems.append(
                    f"enum {enum_name} value {value.name} = "
                    f"{value.number} missing from the live schema"
                )
    return problems


def descriptor_conformance_problems(schema=None) -> List[str]:
    """Check every protoc-generated module's runtime descriptor (the
    three live ones exactly; the legacy frozen ones as a subset — every
    legacy field must still mean the same thing) against the schema.
    Returns rendered problems; raises ImportError if google.protobuf
    is unavailable (callers skip the check explicitly)."""
    schema = schema or protospec.load_repo_schema()
    problems: List[str] = []
    for proto_name, modname in sorted(PROTOC_MODULES.items()):
        module = _import_runtime(modname)
        problems.extend(
            _descriptor_problems(schema, proto_name, module, subset=False)
        )
    for proto_name, modname in sorted(LEGACY_MODULES.items()):
        module = _import_runtime(modname)
        problems.extend(
            _descriptor_problems(schema, proto_name, module, subset=True)
        )
    return problems


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------

def main(argv: Optional[Sequence[str]] = None) -> int:
    import argparse
    import json

    parser = argparse.ArgumentParser(
        prog="python -m shockwave_tpu.analysis.wirefuzz",
        description="schema-derived differential wire fuzzer",
    )
    parser.add_argument("--cases", type=int, default=DEFAULT_CASES)
    parser.add_argument("--seed", type=int, default=DEFAULT_SEED)
    parser.add_argument("--json", action="store_true")
    args = parser.parse_args(argv)
    report = _finish_digests(
        fuzz_schema(cases=args.cases, seed=args.seed)
    )
    if args.json:
        print(json.dumps(report, indent=2))
    else:
        for name, fam in sorted(report["families"].items()):
            print(f"{name}: {fam['cases']} cases, digest {fam['digest']}")
        for skip in report["skipped"]:
            print(f"skipped: {skip}")
        for failure in report["failures"]:
            print(f"FAIL: {failure}")
        print(
            f"wirefuzz: {sum(f['cases'] for f in report['families'].values())} "
            f"cases, {len(report['failures'])} failure(s)"
        )
    return 1 if report["failures"] else 0


if __name__ == "__main__":
    raise SystemExit(main())
