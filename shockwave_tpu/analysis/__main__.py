import sys

from shockwave_tpu.analysis.cli import main

sys.exit(main())
