"""The lint baseline: a committed ledger of accepted findings that
ratchets monotonically toward zero.

Semantics (enforced by ``scripts/ci/lint.py`` and the tier-1 test):

- a finding whose fingerprint is NOT in the baseline is **new** — the
  gate fails; fix it or suppress it with a justified inline comment.
- a baseline entry matched by no current finding is **stale** — the
  debt was paid down, so the gate also fails until the baseline is
  regenerated smaller (``--write-baseline``). Debt can only shrink.

Fingerprints hash (path, rule, stripped source line, occurrence index
among identical lines) — stable across edits that merely shift line
numbers, specific enough that a *new* copy of an old sin fingerprints
differently via the occurrence index.
"""

from __future__ import annotations

import hashlib
import json
import os
from typing import Dict, Iterable, List, Tuple

from shockwave_tpu.analysis.core import Finding, repo_root

DEFAULT_BASELINE_NAME = "lint_baseline.json"


def default_baseline_path(root: str | None = None) -> str:
    return os.path.join(root or repo_root(), DEFAULT_BASELINE_NAME)


def fingerprint_findings(
    findings: Iterable[Finding],
) -> List[Tuple[str, Finding]]:
    """(fingerprint, finding) pairs; occurrence index disambiguates
    repeated identical lines within one file."""
    seen: Dict[Tuple[str, str, str], int] = {}
    out: List[Tuple[str, Finding]] = []
    for f in sorted(findings, key=lambda f: (f.path, f.line, f.col, f.rule)):
        key = (f.path, f.rule, f.line_text)
        index = seen.get(key, 0)
        seen[key] = index + 1
        digest = hashlib.sha256(
            "\x1f".join([f.path, f.rule, f.line_text, str(index)]).encode(
                "utf-8"
            )
        ).hexdigest()[:16]
        out.append((digest, f))
    return out


def make_baseline(findings: Iterable[Finding]) -> dict:
    entries = [
        {
            "fingerprint": fp,
            "rule": f.rule,
            "path": f.path,
            "line": f.line,
            "line_text": f.line_text,
        }
        for fp, f in fingerprint_findings(findings)
    ]
    entries.sort(key=lambda e: (e["path"], e["line"], e["rule"]))
    return {
        "comment": (
            "shockwave-lint ratchet baseline: accepted findings may "
            "only disappear. Regenerate (only ever smaller) with "
            "`python -m shockwave_tpu.analysis --write-baseline` after "
            "paying down debt."
        ),
        "entries": entries,
    }


def save_baseline(path: str, baseline: dict) -> None:
    from shockwave_tpu.utils.fileio import atomic_write_text

    atomic_write_text(path, json.dumps(baseline, indent=2) + "\n")


def load_baseline(path: str) -> dict:
    if not os.path.exists(path):
        return {"entries": []}
    with open(path, encoding="utf-8") as f:
        return json.load(f)


def diff_against_baseline(
    findings: Iterable[Finding], baseline: dict
) -> Tuple[List[Finding], List[dict]]:
    """(new_findings, stale_entries).

    ``new_findings``: active findings not covered by the baseline.
    ``stale_entries``: baseline entries no current finding matches —
    debt that was paid down and must now be removed from the ledger.
    """
    pairs = fingerprint_findings(findings)
    current = {fp for fp, _ in pairs}
    known = {e["fingerprint"] for e in baseline.get("entries", [])}
    new = [f for fp, f in pairs if fp not in known]
    stale = [
        e for e in baseline.get("entries", []) if e["fingerprint"] not in current
    ]
    return new, stale
