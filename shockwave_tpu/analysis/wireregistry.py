"""The wire registry: a committed ledger of every
``(message, field, number, type)`` the schema has ever declared.

Like :mod:`.baseline` it makes evolution an explicit, reviewed diff —
but where the lint baseline ratchets toward zero, the wire registry is
**append-only**: wire history cannot be rewritten, because bytes
already sent with an old tag are decoded by whatever the number means
NOW. Enforced failure modes (``scripts/ci/wire_smoke.py`` and the
``--check-wire-registry`` CLI gate):

- **renumbered** — a registered field name moved to a different
  number: old peers' bytes for the old number silently land in the
  wrong (or no) field;
- **retyped / repurposed** — a registered number changed name, type,
  or packedness: the classic number-reuse bug, undetectable at
  runtime between same-build peers;
- **removed** — a registered field vanished from the schema without a
  ``reserved`` tombstone: the number is now free to be reused by a
  future edit against live traffic;
- **unregistered** — a new schema field not yet in the registry:
  append it (``--write-wire-registry``) so the diff is part of the PR.

Removal with a ``reserved`` declaration for the retired number is the
one legal deletion: the tombstone keeps the number unusable forever.
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional, Tuple

from shockwave_tpu.analysis.core import repo_root

DEFAULT_REGISTRY_NAME = "wire_registry.json"


def default_registry_path(root: Optional[str] = None) -> str:
    return os.path.join(root or repo_root(), DEFAULT_REGISTRY_NAME)


def registry_entries(schema) -> List[dict]:
    """The schema flattened into sorted registry entries."""
    entries = [
        {
            "message": msg.name,
            "field": fld.name,
            "number": fld.number,
            "type": ("repeated " if fld.repeated else "") + fld.type,
            "proto": msg.proto,
        }
        for msg, fld in schema.iter_fields()
    ]
    entries.sort(key=lambda e: (e["message"], e["number"]))
    return entries


def make_registry(schema) -> dict:
    return {
        "comment": (
            "Wire-contract registry: every (message, field, number, "
            "type) the schema has ever declared. APPEND-ONLY — "
            "renumbering, retyping, or deleting an entry fails CI "
            "(scripts/ci/wire_smoke.py); retire a field by reserving "
            "its number in the .proto instead. Append new fields with "
            "`python -m shockwave_tpu.analysis --write-wire-registry`."
        ),
        "entries": registry_entries(schema),
    }


def save_registry(path: str, registry: dict) -> None:
    from shockwave_tpu.utils.fileio import atomic_write_text

    atomic_write_text(path, json.dumps(registry, indent=2) + "\n")


def load_registry(path: str) -> Optional[dict]:
    """The committed registry, or None when the file is missing (a
    broken gate, not a clean slate — callers must fail loudly)."""
    if not os.path.exists(path):
        return None
    with open(path, encoding="utf-8") as f:
        return json.load(f)


def _reserved_numbers(schema, message: str) -> List[Tuple[int, int]]:
    msg = schema.message(message)
    return list(msg.reserved_ranges) if msg is not None else []


def diff_registry(schema, registry: dict) -> List[str]:
    """Ratchet violations between the live schema and the committed
    registry, as rendered problem strings (empty = gate green)."""
    problems: List[str] = []
    current = registry_entries(schema)
    cur_by_num: Dict[Tuple[str, int], dict] = {
        (e["message"], e["number"]): e for e in current
    }
    cur_by_name: Dict[Tuple[str, str], dict] = {
        (e["message"], e["field"]): e for e in current
    }
    reg_entries = registry.get("entries", [])
    reg_by_num = {(e["message"], e["number"]): e for e in reg_entries}
    for entry in reg_entries:
        message, name = entry["message"], entry["field"]
        number, ftype = entry["number"], entry["type"]
        live = cur_by_num.get((message, number))
        live_name = cur_by_name.get((message, name))
        if live is not None and live["field"] == name and live["type"] == ftype:
            continue  # intact
        if live_name is not None and live_name["number"] != number:
            problems.append(
                f"{message}.{name} renumbered: registry says {number}, "
                f"schema now says {live_name['number']} — peers built "
                "against the registry encode the old tag; field "
                "numbers are forever"
            )
            continue
        if live is None:
            if schema.message(message) is None:
                problems.append(
                    f"{message}: whole message removed from the schema "
                    "but its registry entries remain — messages are "
                    "wire history too; restore it or retire it "
                    "explicitly with reserved tombstones in a kept "
                    "message definition"
                )
                continue
            reserved = any(
                lo <= number <= hi
                for lo, hi in _reserved_numbers(schema, message)
            )
            if not reserved:
                problems.append(
                    f"{message}.{name} (= {number}) removed from the "
                    "schema without a reserved tombstone — the number "
                    "is free to be reused against live traffic; add "
                    f"`reserved {number};` to "
                    f"{entry.get('proto', 'the .proto')}"
                )
            continue
        problems.append(
            f"{message} field {number} repurposed: registry says "
            f"{name} ({ftype}), schema now says {live['field']} "
            f"({live['type']}) — old peers' bytes for tag {number} "
            "decode into the wrong field; pick a fresh number"
        )
    unregistered = [
        e for e in current if (e["message"], e["number"]) not in reg_by_num
    ]
    for entry in unregistered:
        problems.append(
            f"{entry['message']}.{entry['field']} (= {entry['number']}, "
            f"{entry['type']}) is not in {DEFAULT_REGISTRY_NAME} — "
            "append it with --write-wire-registry so the schema "
            "evolution is a reviewed diff"
        )
    return problems
