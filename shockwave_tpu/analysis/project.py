"""Project-wide symbol table and call graph for interprocedural rules.

The per-file rules (:mod:`.rules`) cannot see the hazards that only
exist ACROSS files: a lock acquired in ``runtime/dispatcher.py`` while
a call chain reaches another lock in ``obs/metrics.py``, a host sync
buried two calls below a hot loop, an exception swallowed by a helper
the gRPC handler delegates to. This module parses every module under
``shockwave_tpu/`` once and answers the questions those rules need:

* **symbol table** — modules, module-level functions/classes/instances,
  class methods, with ``from``-import and alias resolution between
  project modules (external imports are recorded but opaque);
* **method resolution** — ``self.foo()`` through the class and its
  project-local bases; ``obj.foo()`` through the inferred type of
  ``obj`` (module-level instances, ``self._attr = Class(...)`` fields,
  flow-insensitive function locals);
* **decorator unwrapping** — ``f = jax.jit(step)`` /
  ``@functools.partial(jax.jit, ...)`` resolve calls to the wrapped
  function, so tracing follows the python body, not the wrapper;
* **call graph + fixpoints** — per-function callee sets with call-site
  nodes, and transitive "which locks does this call acquire" /
  "which host-sync sites does this call reach" closures with witness
  chains for the findings.

Everything is flow-insensitive and intentionally conservative in the
direction each rule needs (see the rule docstrings).
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, Iterator, List, Optional, Sequence, Set, Tuple

from shockwave_tpu.analysis.core import (
    _parse_suppressions,
    dotted_name,
    repo_root,
)

# Leaf callables that create a lock object. ``make_lock``/``make_rlock``
# are the sanitizer factories (:mod:`shockwave_tpu.analysis.sanitize`);
# the threading names are the raw primitives they wrap.
LOCK_FACTORIES = {"Lock", "RLock", "make_lock", "make_rlock"}
CONDITION_FACTORIES = {"Condition", "make_condition"}

# Factories whose product is internally synchronized (or thread-local):
# fields holding one are exempt from shared-state analysis.
THREADSAFE_FACTORIES = {
    "Queue", "LifoQueue", "PriorityQueue", "SimpleQueue",
    "Event", "Semaphore", "BoundedSemaphore", "Barrier", "local",
}

# In-place mutators on builtin containers (list/dict/set/deque/
# OrderedDict). A call ``self.field.append(...)`` that does NOT resolve
# to a project method is assumed to mutate the field.
MUTATOR_METHODS = {
    "append", "extend", "insert", "remove", "discard", "pop", "popitem",
    "clear", "update", "setdefault", "sort", "reverse", "add",
    "appendleft", "popleft", "extendleft", "rotate", "move_to_end",
}

# The repo's lock-discipline convention: a helper that runs under its
# caller's critical section declares so in its docstring ("Caller
# holds the lock (_cv)."). Thread-root seeding honors the declaration
# the same way the host-sync rule honors "host-boundary" docstrings.
_CALLER_HOLDS_RE = re.compile(r"[Cc]aller holds the lock \((\w+)\)")

# Access kinds, ordered by severity for the race rule's GIL model:
# READ    plain attribute load — atomic under the GIL;
# REBIND  plain ``self.f = <expr not reading f>`` — atomic publication
#         of a fresh value;
# RMW     ``self.f += 1`` / ``self.f = f(self.f)`` — a read-modify-write
#         on the FIELD BINDING (non-atomic across threads, but no
#         structural aliasing: the new value is a fresh object);
# MUTATE  in-place container mutation — subscript store/del, a mutator
#         method call — which both races other accesses AND follows
#         aliases (the snapshot-escape hazard).
READ, REBIND, RMW, MUTATE = "read", "rebind", "rmw", "mutate"

# The kinds that count as a WRITE for the shared-state-race rule.
WRITE_KINDS = frozenset({RMW, MUTATE})


class FunctionInfo:
    """One function or method definition."""

    __slots__ = (
        "qname", "name", "module", "cls", "node", "calls", "decorators",
        "local_imports",
    )

    def __init__(self, qname, name, module, cls, node):
        self.qname: str = qname
        self.name: str = name
        self.module: "ModuleInfo" = module
        self.cls: Optional["ClassInfo"] = cls
        self.node: ast.AST = node
        # filled by Project._link: list of (call_node, callee_qname)
        self.calls: List[Tuple[ast.Call, str]] = []
        self.decorators: List[str] = [
            dotted_name(d.func) if isinstance(d, ast.Call) else dotted_name(d)
            for d in node.decorator_list
        ]
        # Function-local `from shockwave_tpu import obs`-style imports
        # (the repo's lazy-import idiom); merged over module imports
        # during call resolution.
        self.local_imports: Dict[str, str] = {}

    def __repr__(self):  # pragma: no cover - debugging aid
        return f"<fn {self.qname}>"


class ClassInfo:
    __slots__ = (
        "qname", "name", "module", "node", "methods", "bases",
        "lock_attrs", "lock_aliases", "attr_types", "safe_attrs",
    )

    def __init__(self, qname, name, module, node):
        self.qname: str = qname
        self.name: str = name
        self.module: "ModuleInfo" = module
        self.node: ast.ClassDef = node
        self.methods: Dict[str, FunctionInfo] = {}
        self.bases: List[str] = [dotted_name(b) for b in node.bases]
        # self attributes assigned a lock factory call anywhere in the
        # class body (typically __init__).
        self.lock_attrs: Set[str] = set()
        # Condition(self._lock)-style aliases: alias attr -> lock attr.
        self.lock_aliases: Dict[str, str] = {}
        # self._attr = SomeProjectClass(...) -> class qname (field types).
        self.attr_types: Dict[str, str] = {}
        # self attributes holding internally-synchronized objects
        # (queue.Queue, threading.Event, ...): exempt from race checks.
        self.safe_attrs: Set[str] = set()

    def __repr__(self):  # pragma: no cover - debugging aid
        return f"<class {self.qname}>"


class ModuleInfo:
    __slots__ = (
        "modname", "relpath", "tree", "source", "lines", "suppressions",
        "functions", "classes", "imports", "instances", "module_locks",
        "aliased_defs", "traced_defs", "shared_globals",
    )

    def __init__(self, modname, relpath, source, tree):
        self.modname: str = modname
        self.relpath: str = relpath
        self.source: str = source
        self.lines: List[str] = source.splitlines()
        self.tree: ast.Module = tree
        self.suppressions = _parse_suppressions(source)
        self.functions: Dict[str, FunctionInfo] = {}
        self.classes: Dict[str, ClassInfo] = {}
        # local name -> dotted target ("shockwave_tpu.obs" for modules,
        # "shockwave_tpu.obs.metrics.MetricsRegistry" for symbols).
        self.imports: Dict[str, str] = {}
        # module-level `x = SomeClass(...)` -> class qname.
        self.instances: Dict[str, str] = {}
        # module-level `_lock = threading.Lock()` names.
        self.module_locks: Set[str] = set()
        # module-level `g = jax.jit(f)` / `g = f` aliases -> local fn name.
        self.aliased_defs: Dict[str, str] = {}
        # Local fn names wrapped by a TRACING wrapper (jit/remat) at
        # module level — only these make the body device code; a plain
        # `public = _impl` alias or lru_cache wrapper does not.
        self.traced_defs: Set[str] = set()
        # Module-level mutable-container globals (`_violations = []`):
        # the module-global shared state the race analysis tracks when
        # the module also owns a module-level lock.
        self.shared_globals: Set[str] = set()

    def __repr__(self):  # pragma: no cover - debugging aid
        return f"<module {self.modname}>"


# -- jit/decorator unwrapping -------------------------------------------

_WRAPPER_LEAVES = {"jit", "partial", "wraps", "lru_cache", "cache", "remat"}


def unwrap_call(value: ast.AST) -> ast.AST:
    """Peel ``jax.jit(f, ...)`` / ``functools.partial(g, ...)`` wrappers
    down to the innermost wrapped expression."""
    while isinstance(value, ast.Call):
        leaf = dotted_name(value.func).split(".")[-1]
        if leaf in _WRAPPER_LEAVES and value.args:
            value = value.args[0]
        else:
            break
    return value


_TRACING_LEAVES = {"jit", "remat"}


def _wrapper_chain_traces(value: ast.AST) -> bool:
    """True when a ``g = wrapper(...)(f)`` chain contains a TRACING
    wrapper (jit/remat) — those make the wrapped body device code; a
    plain alias or ``lru_cache``/``wraps`` does not."""
    while isinstance(value, ast.Call):
        leaf = dotted_name(value.func).split(".")[-1]
        if leaf in _TRACING_LEAVES:
            return True
        if leaf in _WRAPPER_LEAVES and value.args:
            value = value.args[0]
        else:
            break
    return False


# -- building -----------------------------------------------------------

def _module_name(relpath: str) -> str:
    mod = relpath[:-3] if relpath.endswith(".py") else relpath
    mod = mod.replace("/", ".")
    if mod.endswith(".__init__"):
        mod = mod[: -len(".__init__")]
    return mod


class Project:
    """Symbol table + call graph over one package tree."""

    def __init__(self, root: str, package: str = "shockwave_tpu"):
        self.root = root
        self.package = package
        self.modules: Dict[str, ModuleInfo] = {}  # by modname
        self.by_path: Dict[str, ModuleInfo] = {}  # by relpath
        self.functions: Dict[str, FunctionInfo] = {}  # by qname
        self.classes: Dict[str, ClassInfo] = {}  # by qname
        # Fixpoint memo: lock/effect closures are O(project) to build
        # and several ProjectRules need the same ones, so one analysis
        # run computes each exactly once (the CLI builds ONE Project and
        # every rule shares it; see core.run_paths). Keys are fixpoint
        # names ("transitive_acquires", "effects", "held:<root>", ...).
        self._cache: Dict[str, object] = {}

    def cached(self, key: str, thunk):
        """Memoize ``thunk()`` under ``key`` for this Project's lifetime
        (the symbol table is immutable after :meth:`link`)."""
        if key not in self._cache:
            self._cache[key] = thunk()
        return self._cache[key]

    # -- construction ----------------------------------------------------
    @classmethod
    def build(
        cls, root: Optional[str] = None, package: str = "shockwave_tpu"
    ) -> "Project":
        root = root or repo_root()
        project = cls(root, package)
        pkg_dir = os.path.join(root, package)
        for dirpath, dirnames, filenames in os.walk(pkg_dir):
            dirnames[:] = sorted(
                d for d in dirnames if d not in ("__pycache__",)
            )
            for name in sorted(filenames):
                if not name.endswith(".py"):
                    continue
                path = os.path.join(dirpath, name)
                relpath = os.path.relpath(path, root).replace(os.sep, "/")
                with open(path, encoding="utf-8") as f:
                    source = f.read()
                project.add_module(relpath, source)
        project.link()
        return project

    def add_module(self, relpath: str, source: str) -> Optional[ModuleInfo]:
        try:
            tree = ast.parse(source)
        except SyntaxError:
            return None  # per-file rules report parse errors already
        mod = ModuleInfo(_module_name(relpath), relpath, source, tree)
        self.modules[mod.modname] = mod
        self.by_path[relpath] = mod
        self._collect(mod)
        return mod

    def _collect(self, mod: ModuleInfo) -> None:
        for stmt in mod.tree.body:
            if isinstance(stmt, ast.Import):
                for alias in stmt.names:
                    local = alias.asname or alias.name.split(".")[0]
                    target = alias.name if alias.asname else alias.name.split(".")[0]
                    mod.imports[local] = target
            elif isinstance(stmt, ast.ImportFrom):
                base = self._from_base(mod, stmt)
                for alias in stmt.names:
                    if alias.name == "*":
                        continue
                    local = alias.asname or alias.name
                    mod.imports[local] = f"{base}.{alias.name}" if base else alias.name
            elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qname = f"{mod.modname}.{stmt.name}"
                info = FunctionInfo(qname, stmt.name, mod, None, stmt)
                mod.functions[stmt.name] = info
                self.functions[qname] = info
            elif isinstance(stmt, ast.ClassDef):
                self._collect_class(mod, stmt)
            elif isinstance(stmt, ast.Assign) and len(stmt.targets) == 1:
                target = stmt.targets[0]
                if not isinstance(target, ast.Name):
                    continue
                value = stmt.value
                if isinstance(
                    value,
                    (ast.Dict, ast.List, ast.Set, ast.DictComp,
                     ast.ListComp, ast.SetComp),
                ):
                    mod.shared_globals.add(target.id)
                    continue
                if isinstance(value, ast.Call):
                    leaf = dotted_name(value.func).split(".")[-1]
                    if leaf in LOCK_FACTORIES | CONDITION_FACTORIES:
                        mod.module_locks.add(target.id)
                        continue
                    if leaf in (
                        "dict", "list", "set", "OrderedDict",
                        "defaultdict", "deque",
                    ):
                        mod.shared_globals.add(target.id)
                        continue
                    inner = unwrap_call(value)
                    if isinstance(inner, ast.Name):
                        # g = jax.jit(f): alias to the wrapped local def.
                        mod.aliased_defs[target.id] = inner.id
                        if _wrapper_chain_traces(value):
                            mod.traced_defs.add(inner.id)
                    elif isinstance(value.func, (ast.Name, ast.Attribute)):
                        # x = SomeClass(...): module-level instance.
                        mod.instances[target.id] = dotted_name(value.func) or (
                            value.func.id
                            if isinstance(value.func, ast.Name)
                            else ""
                        )
                elif isinstance(value, ast.Name):
                    mod.aliased_defs[target.id] = value.id

    def _from_base(self, mod: ModuleInfo, stmt: ast.ImportFrom) -> str:
        if stmt.level == 0:
            return stmt.module or ""
        # Relative import: resolve against this module's package.
        parts = mod.modname.split(".")
        # A package __init__ counts as the package itself.
        is_pkg = mod.relpath.endswith("__init__.py")
        up = stmt.level - (1 if is_pkg else 0)
        base_parts = parts[: len(parts) - up] if up else parts
        if stmt.module:
            base_parts = base_parts + stmt.module.split(".")
        return ".".join(base_parts)

    def _collect_class(self, mod: ModuleInfo, node: ast.ClassDef) -> None:
        qname = f"{mod.modname}.{node.name}"
        cls = ClassInfo(qname, node.name, mod, node)
        mod.classes[node.name] = cls
        self.classes[qname] = cls
        for stmt in node.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                fq = f"{qname}.{stmt.name}"
                info = FunctionInfo(fq, stmt.name, mod, cls, stmt)
                cls.methods[stmt.name] = info
                self.functions[fq] = info
        # Lock attrs, Condition aliases, and field types from every
        # method body (typically __init__).
        for sub in ast.walk(node):
            if not isinstance(sub, ast.Assign) or not isinstance(
                sub.value, ast.Call
            ):
                continue
            for target in sub.targets:
                if not (
                    isinstance(target, ast.Attribute)
                    and isinstance(target.value, ast.Name)
                    and target.value.id == "self"
                ):
                    continue
                leaf = dotted_name(sub.value.func).split(".")[-1]
                if leaf in THREADSAFE_FACTORIES:
                    cls.safe_attrs.add(target.attr)
                elif leaf in LOCK_FACTORIES:
                    cls.lock_attrs.add(target.attr)
                elif leaf in CONDITION_FACTORIES:
                    # Condition(self._lock) aliases the underlying lock;
                    # a bare Condition() owns a fresh (anonymous) lock.
                    alias_of = None
                    for arg in sub.value.args:
                        if (
                            isinstance(arg, ast.Attribute)
                            and isinstance(arg.value, ast.Name)
                            and arg.value.id == "self"
                        ):
                            alias_of = arg.attr
                    if alias_of:
                        cls.lock_aliases[target.attr] = alias_of
                    else:
                        cls.lock_attrs.add(target.attr)
                else:
                    callee = dotted_name(sub.value.func)
                    if callee:
                        cls.attr_types[target.attr] = callee

    # -- linking ---------------------------------------------------------
    def link(self) -> None:
        """Resolve attr_types/instances to class qnames and build the
        per-function callee lists."""
        for mod in self.modules.values():
            mod.instances = {
                name: resolved
                for name, target in mod.instances.items()
                if (resolved := self._resolve_class_name(mod, target))
            }
            for cls in mod.classes.values():
                cls.attr_types = {
                    attr: resolved
                    for attr, target in cls.attr_types.items()
                    if (resolved := self._resolve_class_name(mod, target))
                }
        for fn in list(self.functions.values()):
            fn.calls = list(self._resolve_calls(fn))

    def _resolve_dotted(
        self, mod: ModuleInfo, dotted: str, extra: Optional[Dict[str, str]] = None
    ) -> Optional[str]:
        """Resolve a dotted reference seen in ``mod`` to a fully
        qualified project name (module, class, or function), or None.
        ``extra`` holds function-local imports that shadow the module's."""
        if not dotted:
            return None
        head, _, rest = dotted.partition(".")
        target = (extra or {}).get(head) or mod.imports.get(head)
        if target is None:
            # An unimported head: either a module-local symbol or junk.
            if head in mod.classes or head in mod.functions:
                target = f"{mod.modname}.{head}"
            elif dotted.startswith(self.package):
                target = head
            else:
                return None
        full = f"{target}.{rest}" if rest else target
        # Normalize chains that route through modules:
        # "shockwave_tpu.obs.metrics.MetricsRegistry" etc.
        return full

    def _resolve_class_name(
        self, mod: ModuleInfo, dotted: str
    ) -> Optional[str]:
        full = self._resolve_dotted(mod, dotted)
        if full is None:
            return None
        if full in self.classes:
            return full
        # "pkg.module.Class" where the import bound a module.
        modname, _, leaf = full.rpartition(".")
        target_mod = self.modules.get(modname)
        if target_mod and leaf in target_mod.classes:
            return f"{modname}.{leaf}"
        return None

    def resolve_function(
        self,
        mod: ModuleInfo,
        dotted: str,
        extra: Optional[Dict[str, str]] = None,
    ) -> Optional[FunctionInfo]:
        """Resolve a (possibly dotted) callee name seen in ``mod``."""
        full = self._resolve_dotted(mod, dotted, extra)
        if full is None:
            return None
        if full in self.functions:
            return self.functions[full]
        modname, _, leaf = full.rpartition(".")
        target_mod = self.modules.get(modname)
        if target_mod:
            if leaf in target_mod.aliased_defs:
                leaf = target_mod.aliased_defs[leaf]
            if leaf in target_mod.functions:
                return target_mod.functions[leaf]
            if leaf in target_mod.classes:
                init = target_mod.classes[leaf].methods.get("__init__")
                return init
        if full in self.classes:
            return self.classes[full].methods.get("__init__")
        return None

    def _method_on(self, cls_qname: str, name: str) -> Optional[FunctionInfo]:
        """Method lookup through project-local bases (one-level MRO walk,
        depth-limited against cycles)."""
        seen = set()
        stack = [cls_qname]
        while stack:
            qn = stack.pop(0)
            if qn in seen or qn not in self.classes:
                continue
            seen.add(qn)
            cls = self.classes[qn]
            if name in cls.methods:
                return cls.methods[name]
            for base in cls.bases:
                resolved = self._resolve_class_name(cls.module, base)
                if resolved:
                    stack.append(resolved)
        return None

    def _resolve_calls(
        self, fn: FunctionInfo
    ) -> Iterator[Tuple[ast.Call, str]]:
        mod = fn.module
        fn.local_imports = self._collect_local_imports(fn)
        local_types = self._local_types(fn)
        for node in ast.walk(fn.node):
            if not isinstance(node, ast.Call):
                continue
            callee = self._resolve_call(fn, mod, node, local_types)
            if callee is not None:
                yield node, callee.qname

    def _collect_local_imports(self, fn: FunctionInfo) -> Dict[str, str]:
        out: Dict[str, str] = {}
        for node in ast.walk(fn.node):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    local = alias.asname or alias.name.split(".")[0]
                    out[local] = (
                        alias.name if alias.asname else alias.name.split(".")[0]
                    )
            elif isinstance(node, ast.ImportFrom):
                base = self._from_base(fn.module, node)
                for alias in node.names:
                    if alias.name == "*":
                        continue
                    local = alias.asname or alias.name
                    out[local] = (
                        f"{base}.{alias.name}" if base else alias.name
                    )
        return out

    def _local_types(self, fn: FunctionInfo) -> Dict[str, str]:
        """Flow-insensitive ``x = SomeClass(...)`` locals."""
        types: Dict[str, str] = {}
        for node in ast.walk(fn.node):
            if (
                isinstance(node, ast.Assign)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and isinstance(node.value, ast.Call)
            ):
                resolved = self._resolve_class_name(
                    fn.module, dotted_name(node.value.func)
                )
                if resolved:
                    types[node.targets[0].id] = resolved
        return types

    def _resolve_call(
        self,
        fn: FunctionInfo,
        mod: ModuleInfo,
        node: ast.Call,
        local_types: Dict[str, str],
    ) -> Optional[FunctionInfo]:
        func = node.func
        if isinstance(func, ast.Name):
            name = func.id
            if name in mod.aliased_defs:
                name = mod.aliased_defs[name]
            # Function-local jit aliases: g = jax.jit(f); g(...)
            local_alias = self._local_alias(fn, func.id)
            if local_alias:
                name = local_alias
            if fn.cls and name in fn.cls.methods:
                # A bare method name only resolves via self/cls, skip.
                pass
            if name in mod.functions:
                return mod.functions[name]
            if name in mod.classes:
                return mod.classes[name].methods.get("__init__")
            resolved = self.resolve_function(mod, name, fn.local_imports)
            if resolved:
                return resolved
            return None
        if not isinstance(func, ast.Attribute):
            return None
        base = func.value
        if isinstance(base, ast.Name):
            if base.id == "self" and fn.cls is not None:
                m = self._method_on(fn.cls.qname, func.attr)
                if m is not None:
                    return m
                # self._field.method()-style handled below via attr_types
                return None
            if base.id in local_types:
                return self._method_on(local_types[base.id], func.attr)
            if base.id in mod.instances:
                return self._method_on(mod.instances[base.id], func.attr)
            # module.func() or Class.method() via imports
            return self.resolve_function(
                mod, f"{base.id}.{func.attr}", fn.local_imports
            )
        if (
            isinstance(base, ast.Attribute)
            and isinstance(base.value, ast.Name)
            and base.value.id == "self"
            and fn.cls is not None
        ):
            # self._field.method(): field type from __init__.
            field_type = fn.cls.attr_types.get(base.attr)
            if field_type:
                return self._method_on(field_type, func.attr)
            return None
        # module.sub.func() chains
        return self.resolve_function(mod, dotted_name(func), fn.local_imports)

    def _local_alias(self, fn: FunctionInfo, name: str) -> Optional[str]:
        """``jit_step = jax.jit(step_fn, ...)`` inside ``fn`` aliases
        jit_step -> step_fn (decorator unwrapping, assignment form)."""
        for node in ast.walk(fn.node):
            if (
                isinstance(node, ast.Assign)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and node.targets[0].id == name
                and isinstance(node.value, ast.Call)
            ):
                inner = unwrap_call(node.value)
                if isinstance(inner, ast.Name) and inner.id != name:
                    return inner.id
        return None

    # -- lock model ------------------------------------------------------
    def lock_node(self, fn: FunctionInfo, expr: ast.AST) -> Optional[str]:
        """The project-wide lock identity acquired by ``with <expr>:`` (or
        ``<expr>.acquire()``), e.g. ``"obs.metrics.MetricsRegistry._lock"``
        — or None when expr is not a recognizable lock reference."""
        short = lambda qn: qn[len(self.package) + 1:] if qn.startswith(
            self.package + "."
        ) else qn
        if isinstance(expr, ast.Attribute) and isinstance(
            expr.value, ast.Name
        ):
            owner = expr.value.id
            attr = expr.attr
            if owner == "self" and fn.cls is not None:
                attr = fn.cls.lock_aliases.get(attr, attr)
                if attr in fn.cls.lock_attrs:
                    return f"{short(fn.cls.qname)}.{attr}"
                return None
            # registry._lock style cross-object reference.
            cls_qn = None
            if owner in fn.module.instances:
                cls_qn = fn.module.instances[owner]
            else:
                lt = self._local_types(fn)
                cls_qn = lt.get(owner)
            if cls_qn and cls_qn in self.classes:
                cls = self.classes[cls_qn]
                attr = cls.lock_aliases.get(attr, attr)
                if attr in cls.lock_attrs:
                    return f"{short(cls_qn)}.{attr}"
            return None
        if isinstance(expr, ast.Name):
            if expr.id in fn.module.module_locks:
                return f"{short(fn.module.modname)}.{expr.id}"
        return None

    def direct_acquisitions(
        self, fn: FunctionInfo
    ) -> List[Tuple[ast.AST, str]]:
        """(site, lock node) for every with-statement acquisition
        directly in ``fn``'s body (nested defs excluded — they run when
        called, under the caller's lock context)."""
        out: List[Tuple[ast.AST, str]] = []
        for node in self._walk_own(fn.node):
            if isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    lock = self.lock_node(fn, item.context_expr)
                    if lock:
                        out.append((node, lock))
            elif isinstance(node, ast.Call):
                func = node.func
                if (
                    isinstance(func, ast.Attribute)
                    and func.attr == "acquire"
                ):
                    lock = self.lock_node(fn, func.value)
                    if lock:
                        out.append((node, lock))
        return out

    @staticmethod
    def _walk_own(fn_node: ast.AST) -> Iterator[ast.AST]:
        stack = list(ast.iter_child_nodes(fn_node))
        while stack:
            node = stack.pop()
            yield node
            if isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
            ):
                continue
            stack.extend(ast.iter_child_nodes(node))

    # -- fixpoints -------------------------------------------------------
    def transitive_acquires(self) -> Dict[str, Set[str]]:
        """qname -> set of lock nodes the function may acquire, directly
        or through any resolvable call chain. Memoized: every rule that
        asks gets the same closure from one computation."""
        return self.cached("transitive_acquires", self._transitive_acquires)

    def _transitive_acquires(self) -> Dict[str, Set[str]]:
        direct: Dict[str, Set[str]] = {
            qn: {lock for _, lock in self.direct_acquisitions(fn)}
            for qn, fn in self.functions.items()
        }
        return self._closure(direct)

    def _closure(self, direct: Dict[str, Set[str]]) -> Dict[str, Set[str]]:
        result = {qn: set(s) for qn, s in direct.items()}
        changed = True
        while changed:
            changed = False
            for qn, fn in self.functions.items():
                acc = result[qn]
                before = len(acc)
                for _, callee in fn.calls:
                    acc |= result.get(callee, set())
                if len(acc) != before:
                    changed = True
        return result

    def witness_chain(
        self,
        start: str,
        predicate,
        reach: Optional[Dict[str, Set[str]]] = None,
        want=None,
        limit: int = 8,
    ) -> List[str]:
        """A shortest call chain from ``start`` to a function where
        ``predicate(qname)`` holds — with ``reach``, following only
        edges that keep ``want`` reachable (the pruned form the
        lock/host-sync rules use); with ``reach=None``, plain BFS over
        every call edge. Returns qnames including both endpoints."""
        from collections import deque

        queue = deque([[start]])
        seen = {start}
        while queue:
            path = queue.popleft()
            qn = path[-1]
            if predicate(qn):
                return path
            if len(path) >= limit:
                continue
            fn = self.functions.get(qn)
            if fn is None:
                continue
            for _, callee in fn.calls:
                if callee in seen:
                    continue
                if (
                    reach is not None
                    and want not in reach.get(callee, set())
                    and not predicate(callee)
                ):
                    continue
                seen.add(callee)
                queue.append(path + [callee])
        return [start]

    def is_suppressed(self, relpath: str, line: int, rule: str) -> bool:
        mod = self.by_path.get(relpath)
        if mod is None:
            return False
        rules = mod.suppressions.get(line, set())
        return rule in rules or "all" in rules

    # -- thread topology -------------------------------------------------
    def short(self, qn: str) -> str:
        return (
            qn[len(self.package) + 1:]
            if qn.startswith(self.package + ".")
            else qn
        )

    def class_family(self, cls_qname: str) -> str:
        """The topmost project-local base of ``cls_qname`` — the
        identity shared state is attributed to, so a field defined on a
        base and touched from subclass methods pairs up correctly."""
        families: Dict[str, str] = self.cached("families", dict)
        if cls_qname in families:
            return families[cls_qname]
        seen = set()
        cur = cls_qname
        while cur not in seen and cur in self.classes:
            seen.add(cur)
            cls = self.classes[cur]
            parent = None
            for base in cls.bases:
                resolved = self._resolve_class_name(cls.module, base)
                if resolved and resolved not in seen:
                    parent = resolved
                    break
            if parent is None:
                break
            cur = parent
        families[cls_qname] = cur
        return cur

    def family_lock_attrs(self, family: str) -> Tuple[Set[str], Set[str]]:
        """(lock-or-alias attrs, threadsafe attrs) unioned over every
        class whose family root is ``family``."""
        memo: Dict[str, tuple] = self.cached("family_attrs", dict)
        if family not in memo:
            locks: Set[str] = set()
            safe: Set[str] = set()
            for qn, cls in self.classes.items():
                if self.class_family(qn) != family:
                    continue
                locks |= cls.lock_attrs
                locks |= set(cls.lock_aliases)
                safe |= cls.safe_attrs
            memo[family] = (locks, safe)
        return memo[family]

    def family_owns_lock(self, family: str) -> bool:
        locks, _ = self.family_lock_attrs(family)
        return bool(locks)

    def caller_holds_locks(self, fn: FunctionInfo) -> frozenset:
        """Lock nodes a function's docstring contract declares held on
        entry ("Caller holds the lock (_cv)." — the repo's convention
        for helpers that run inside their caller's critical section)."""
        doc = ast.get_docstring(fn.node) or ""
        out: Set[str] = set()
        for attr in _CALLER_HOLDS_RE.findall(doc):
            if fn.cls is not None:
                real = fn.cls.lock_aliases.get(attr, attr)
                if real in fn.cls.lock_attrs:
                    out.add(f"{self.short(fn.cls.qname)}.{real}")
                    continue
            if attr in fn.module.module_locks:
                out.add(f"{self.short(fn.module.modname)}.{attr}")
        return frozenset(out)

    def thread_roots(self) -> List["ThreadRoot"]:
        """Every entry point the process can run CONCURRENTLY with the
        others: ``threading.Thread`` targets, the RPC handler methods
        wired into a servicer's ``serve(port, {...})`` callback dict,
        and the explicit control-plane roots (the physical round loop,
        heartbeat reaper, watchdog tick, admission drain). ``multi``
        marks roots that can race THEMSELVES (a thread spawned per
        event, a gRPC handler running on a thread pool)."""
        return self.cached("thread_roots", self._thread_roots)

    def _thread_roots(self) -> List["ThreadRoot"]:
        roots: Dict[str, ThreadRoot] = {}

        def add(fn: FunctionInfo, kind: str, multi: bool, site) -> None:
            existing = roots.get(fn.qname)
            if existing is not None:
                existing.multi = existing.multi or multi
                return
            roots[fn.qname] = ThreadRoot(
                qname=fn.qname,
                kind=kind,
                multi=multi,
                relpath=fn.module.relpath,
                line=getattr(site, "lineno", fn.node.lineno),
                seed_locks=self.caller_holds_locks(fn),
            )

        for fn in self.functions.values():
            local_types = self._local_types(fn)
            for node in ast.walk(fn.node):
                if not isinstance(node, ast.Call):
                    continue
                leaf = dotted_name(node.func).split(".")[-1]
                if leaf == "Thread":
                    target = next(
                        (
                            kw.value
                            for kw in node.keywords
                            if kw.arg == "target"
                        ),
                        None,
                    )
                    resolved = self._resolve_callable_ref(
                        fn, target, local_types
                    )
                    if resolved is not None:
                        add(resolved, "thread", True, node)
                elif leaf == "serve":
                    # scheduler_server.serve(port, {"done": self._done_rpc,
                    # ...}): every dict value is an RPC handler root run
                    # on the server's thread pool.
                    for arg in list(node.args) + [
                        kw.value for kw in node.keywords
                    ]:
                        if not isinstance(arg, ast.Dict):
                            continue
                        for value in arg.values:
                            resolved = self._resolve_callable_ref(
                                fn, value, local_types
                            )
                            if resolved is not None:
                                add(resolved, "rpc", True, value)

        for suffix, kind, multi in EXPLICIT_THREAD_ROOTS:
            fn = self.functions.get(f"{self.package}.{suffix}")
            if fn is not None:
                add(fn, kind, multi, fn.node)
        return sorted(roots.values(), key=lambda r: r.qname)

    def _resolve_callable_ref(
        self, fn: FunctionInfo, node, local_types: Dict[str, str]
    ) -> Optional[FunctionInfo]:
        """Resolve a callable REFERENCE (not a call): a Thread target or
        a servicer callback-dict value."""
        if node is None:
            return None
        node = unwrap_call(node)  # functools.partial(f, ...) -> f
        if isinstance(node, ast.Attribute) and isinstance(
            node.value, ast.Name
        ):
            base = node.value.id
            if base == "self" and fn.cls is not None:
                return self._method_on(fn.cls.qname, node.attr)
            if base in local_types:
                return self._method_on(local_types[base], node.attr)
            if base in fn.module.instances:
                return self._method_on(
                    fn.module.instances[base], node.attr
                )
            return self.resolve_function(
                fn.module, dotted_name(node), fn.local_imports
            )
        if isinstance(node, ast.Name):
            if node.id in fn.module.functions:
                return fn.module.functions[node.id]
            return self.resolve_function(
                fn.module, node.id, fn.local_imports
            )
        return None

    # -- effect summaries ------------------------------------------------
    def function_effects(self) -> Dict[str, "FunctionEffects"]:
        """qname -> the function's shared-state accesses (with the lock
        set lexically held at each site) and its call sites (with the
        lock set held around each call). One walk per function, shared
        by every rule that needs effects."""
        return self.cached("effects", self._function_effects)

    def _function_effects(self) -> Dict[str, "FunctionEffects"]:
        out: Dict[str, FunctionEffects] = {}
        for qn, fn in self.functions.items():
            eff = FunctionEffects()
            eff.local_names = self._locally_bound_names(fn)
            resolved = {id(c): callee for c, callee in fn.calls}
            self._effects_walk(fn, fn.node, (), eff, resolved)
            out[qn] = eff
        return out

    @staticmethod
    def _locally_bound_names(fn: FunctionInfo) -> Set[str]:
        """Names bound in ``fn``'s own scope (params, assignment/for/
        with/comprehension targets, local imports) MINUS names declared
        ``global`` — a local shadowing a module global must not be
        recorded as an access to the global."""
        bound: Set[str] = set()
        globals_declared: Set[str] = set()
        args = fn.node.args
        for a in (
            list(args.posonlyargs) + list(args.args)
            + list(args.kwonlyargs)
            + ([args.vararg] if args.vararg else [])
            + ([args.kwarg] if args.kwarg else [])
        ):
            bound.add(a.arg)
        for node in Project._walk_own(fn.node):
            if isinstance(node, ast.Global):
                globals_declared.update(node.names)
            elif isinstance(node, ast.Name) and isinstance(
                node.ctx, (ast.Store, ast.Del)
            ):
                bound.add(node.id)
            elif isinstance(node, (ast.Import, ast.ImportFrom)):
                for alias in node.names:
                    bound.add(
                        alias.asname or alias.name.split(".")[0]
                    )
            elif isinstance(
                node, (ast.ListComp, ast.SetComp, ast.DictComp,
                       ast.GeneratorExp)
            ):
                for comp in node.generators:
                    for sub in ast.walk(comp.target):
                        if isinstance(sub, ast.Name):
                            bound.add(sub.id)
        return bound - globals_declared

    def _self_attr(self, fn: FunctionInfo, node) -> Optional[str]:
        if (
            isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"
            and fn.cls is not None
        ):
            return node.attr
        return None

    def _record_self_access(
        self, fn, eff, attr: str, kind: str, held, node
    ) -> None:
        family = self.class_family(fn.cls.qname)
        lockish, safe = self.family_lock_attrs(family)
        if attr in lockish or attr in safe:
            return
        eff.accesses.append(
            FieldAccess(
                owner=self.short(family),
                attr=attr,
                kind=kind,
                locks=frozenset(held),
                fn=fn.qname,
                node=node,
                in_ctor=fn.name == "__init__",
            )
        )

    def _record_global_access(
        self, fn, eff, name: str, kind: str, held, node
    ) -> None:
        if name not in fn.module.shared_globals:
            return
        if name in eff.local_names:
            return  # a local shadows the module global in this scope
        eff.accesses.append(
            FieldAccess(
                owner=self.short(fn.module.modname),
                attr=name,
                kind=kind,
                locks=frozenset(held),
                fn=fn.qname,
                node=node,
                in_ctor=False,
            )
        )

    def _reads_same_field(self, fn, value, attr: str) -> bool:
        """Whether ``value`` (a rebind RHS) reads ``self.<attr>`` — the
        read-modify-write pattern that makes a rebind non-atomic."""
        for sub in ast.walk(value):
            if self._self_attr(fn, sub) == attr and isinstance(
                sub.ctx, ast.Load
            ):
                return True
        return False

    def _effects_walk(self, fn, node, held, eff, resolved) -> None:
        if isinstance(node, (ast.With, ast.AsyncWith)):
            inner = held
            for item in node.items:
                # An item's context expression evaluates BEFORE the
                # lock IT acquires is held — but with every EARLIER
                # item's lock already held (left-to-right acquisition).
                self._effects_walk(
                    fn, item.context_expr, inner, eff, resolved
                )
                lock = self.lock_node(fn, item.context_expr)
                if lock:
                    inner = inner + (lock,)
            for child in node.body:
                self._effects_walk(fn, child, inner, eff, resolved)
            return
        if isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
        ):
            if node is not fn.node:
                return  # nested defs run on their caller's schedule
        elif isinstance(node, ast.Call):
            callee = resolved.get(id(node))
            if callee is not None:
                eff.calls.append((callee, frozenset(held), node))
            elif (
                isinstance(node.func, ast.Attribute)
                and node.func.attr in MUTATOR_METHODS
            ):
                # An unresolved mutator call on a field: an in-place
                # container mutation (self._outstanding.add(...)).
                base = node.func.value
                attr = self._self_attr(fn, base)
                if attr is not None:
                    self._record_self_access(
                        fn, eff, attr, MUTATE, held, node
                    )
                elif isinstance(base, ast.Name):
                    self._record_global_access(
                        fn, eff, base.id, MUTATE, held, node
                    )
        elif isinstance(node, ast.Assign):
            for target in node.targets:
                self._effects_record_store(
                    fn, eff, target, node.value, held, node
                )
        elif isinstance(node, ast.AugAssign):
            self._effects_record_store(
                fn, eff, node.target, None, held, node, aug=True
            )
        elif isinstance(node, ast.Delete):
            for target in node.targets:
                self._effects_record_store(
                    fn, eff, target, None, held, node, aug=True
                )
        elif isinstance(node, ast.Attribute) and isinstance(
            node.ctx, ast.Load
        ):
            attr = self._self_attr(fn, node)
            if attr is not None:
                self._record_self_access(fn, eff, attr, READ, held, node)
        elif isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
            self._record_global_access(
                fn, eff, node.id, READ, held, node
            )
        for child in ast.iter_child_nodes(node):
            self._effects_walk(fn, child, held, eff, resolved)

    def _effects_record_store(
        self, fn, eff, target, value, held, node, aug: bool = False
    ) -> None:
        attr = self._self_attr(fn, target)
        if attr is not None:
            kind = RMW if aug or (
                value is not None and self._reads_same_field(fn, value, attr)
            ) else REBIND
            self._record_self_access(fn, eff, attr, kind, held, node)
            return
        if isinstance(target, ast.Subscript):
            attr = self._self_attr(fn, target.value)
            if attr is not None:
                self._record_self_access(
                    fn, eff, attr, MUTATE, held, node
                )
            elif isinstance(target.value, ast.Name):
                self._record_global_access(
                    fn, eff, target.value.id, MUTATE, held, node
                )
            return
        if isinstance(target, ast.Name):
            kind = RMW if aug else REBIND
            self._record_global_access(
                fn, eff, target.id, kind, held, node
            )
        elif isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                self._effects_record_store(
                    fn, eff, elt, None, held, node, aug=aug
                )

    # -- per-root guaranteed-held dataflow -------------------------------
    def guaranteed_held(self, root: "ThreadRoot") -> Dict[str, frozenset]:
        """qname -> the lock set guaranteed held on entry whenever the
        function runs on ``root``'s thread — the MEET (intersection)
        over every call path from the root, so it is a sound lower
        bound: a lock in the set is held on every path."""
        return self.cached(
            f"held:{root.qname}", lambda: self._guaranteed_held(root)
        )

    def _guaranteed_held(self, root: "ThreadRoot") -> Dict[str, frozenset]:
        effects = self.function_effects()
        entry: Dict[str, frozenset] = {root.qname: root.seed_locks}
        work = [root.qname]
        while work:
            qn = work.pop()
            eff = effects.get(qn)
            if eff is None:
                continue
            base = entry[qn]
            for callee, held_at_site, _ in eff.calls:
                at_entry = base | held_at_site
                prev = entry.get(callee)
                if prev is None:
                    entry[callee] = at_entry
                    work.append(callee)
                else:
                    met = prev & at_entry
                    if met != prev:
                        entry[callee] = met
                        work.append(callee)
        return entry

    def call_chain(self, root_qname: str, target: str) -> List[str]:
        """Shortest call chain root -> ... -> target (qnames, both ends
        included), or [] when unreachable — the witness the race
        findings print. The unpruned form of :meth:`witness_chain`."""
        chain = self.witness_chain(
            root_qname, lambda q: q == target, limit=12
        )
        return chain if chain[-1] == target else []


class ThreadRoot:
    """One concurrent entry point (see :meth:`Project.thread_roots`)."""

    __slots__ = ("qname", "kind", "multi", "relpath", "line", "seed_locks")

    def __init__(self, qname, kind, multi, relpath, line, seed_locks):
        self.qname: str = qname
        self.kind: str = kind
        self.multi: bool = multi
        self.relpath: str = relpath
        self.line: int = line
        self.seed_locks: frozenset = seed_locks

    def to_dict(self) -> dict:
        return {
            "qname": self.qname,
            "kind": self.kind,
            "multi": self.multi,
            "site": f"{self.relpath}:{self.line}",
            "seed_locks": sorted(self.seed_locks),
        }

    def __repr__(self):  # pragma: no cover - debugging aid
        return f"<root {self.kind} {self.qname}>"


class FieldAccess:
    """One shared-state access inside one function."""

    __slots__ = ("owner", "attr", "kind", "locks", "fn", "node", "in_ctor")

    def __init__(self, owner, attr, kind, locks, fn, node, in_ctor):
        self.owner: str = owner
        self.attr: str = attr
        self.kind: str = kind  # READ / REBIND / MUTATE
        self.locks: frozenset = locks
        self.fn: str = fn
        self.node: ast.AST = node
        self.in_ctor: bool = in_ctor

    def __repr__(self):  # pragma: no cover - debugging aid
        return (
            f"<{self.kind} {self.owner}.{self.attr} in {self.fn} "
            f"locks={sorted(self.locks)}>"
        )


class FunctionEffects:
    __slots__ = ("accesses", "calls", "local_names")

    def __init__(self):
        self.accesses: List[FieldAccess] = []
        # (callee qname, locks held around the call, call node)
        self.calls: List[Tuple[str, frozenset, ast.Call]] = []
        # Locally-bound names (shadow module globals; see
        # _locally_bound_names).
        self.local_names: Set[str] = set()


# Control-plane entry points that are thread roots by construction
# rather than by a discoverable ``Thread(...)``/``serve(...)`` site:
# the physical round loop is the implicit main root; the heartbeat
# reaper and admission drain are distinct phases of it (rooted
# separately so their docstring-declared lock contracts are checked
# even if call-graph resolution to them ever regresses); the watchdog
# tick runs on whichever scheduler thread calls check_round. Entries
# missing from a (fixture) project are skipped.
EXPLICIT_THREAD_ROOTS: Tuple[Tuple[str, str, bool], ...] = (
    ("core.physical.PhysicalScheduler.run", "main", False),
    ("core.physical.PhysicalScheduler._reap_dead_workers", "reaper", False),
    (
        "core.physical.PhysicalScheduler._drain_admission_queue",
        "admission",
        False,
    ),
    ("obs.watchdog.Watchdog.check_round", "watchdog", False),
    # HA survivability plane (shockwave_tpu/ha/): the lease-renewal
    # daemon fences the scheduler from its own thread (on_lost ->
    # _ha_fenced -> shutdown), concurrent with the round loop and RPC
    # handlers; journal replay runs on the driver thread before the
    # round loop starts but shares the journal's writer lock with
    # every live hook. Rooted explicitly so their lock contracts are
    # checked even if Thread-target discovery ever regresses.
    ("ha.election.LeaderElection._renew_loop", "ha-renew", False),
    ("core.physical.PhysicalScheduler._ha_fenced", "ha-fence", False),
)
