"""Per-job dynamic-adaptation predictor state.

One :class:`JobMetadata` per job tracks its epoch profile (batch size and
wall-clock duration of every epoch), the measured per-round throughput
schedule, and a Dirichlet prior over batch-size "regimes". From these it
predicts the job's remaining runtime — the quantity the Shockwave planner's
finish-time-fairness and makespan terms are built on.

Capability parity with reference: scheduler/job_metadata.py:1-202. The
implementation here is vectorized numpy (cumsum/bincount over epoch arrays
instead of Python loops) so the same math can be lifted into the batched JAX
round-prep path (see :func:`batch_remaining_runtimes`).
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence, Tuple

import numpy as np

INFINITY = 1e9

# Change-point reweight (see JobMetadata._regime_posterior): after a
# batch-size switch is OBSERVED in the measured throughput schedule,
# this fraction of the Dirichlet prior mass stays spread over the
# profiled regimes; the rest concentrates on the regime the job was
# last measured in. The profile's bs-per-epoch pattern predicts WHEN a
# gns/accordion job switches, but the realized switch point (driven by
# measured gradient noise / critical regimes) routinely lands epochs
# away — pricing the remaining epochs mostly at the observed regime is
# what closes the MAPE outliers the calibration tracker exposed.
CHANGEPOINT_RETAIN = 0.1


class JobMetadata:
    """Epoch profile + throughput history + Dirichlet regime posterior.

    Profile schema (reference: job_metadata.py:14-23):
      num_epochs, num_samples_per_epoch, scale_factor, duration,
      bs_every_epoch, mem_every_epoch, util_every_epoch, duration_every_epoch.
    """

    def __init__(
        self,
        profile: dict,
        round_duration: float,
        scale_factor: Optional[int] = None,
    ):
        self.total_epochs = int(profile["num_epochs"])
        self.completed_epochs = 0
        self.nsamples_per_epoch = profile["num_samples_per_epoch"]
        self.nworkers = (
            int(scale_factor)
            if scale_factor is not None
            else int(profile["scale_factor"])
        )
        self.epoch_batch_sizes = np.asarray(profile["bs_every_epoch"], dtype=np.int64)
        self.epoch_mem_reqs = list(profile.get("mem_every_epoch", []))
        self.epoch_gpu_reqs = list(profile.get("util_every_epoch", []))

        # Durations are clamped to whole >=1s values up front
        # (reference: job_metadata.py:39).
        durations = np.asarray(profile["duration_every_epoch"], dtype=np.float64)
        self.epoch_durations = np.maximum(1.0, np.round(durations))
        # The as-profiled durations stay fixed; ``epoch_durations`` is
        # re-scaled in place from measured throughput.
        self.estimated_epoch_durations = self.epoch_durations.copy()

        # Dirichlet prior: uniform over the distinct batch sizes in the
        # profile, with total concentration = total_epochs
        # (reference: job_metadata.py:42-45).
        self.regimes = np.unique(self.epoch_batch_sizes)
        self.dirichlet: Dict[int, float] = {
            int(bs): self.total_epochs / len(self.regimes) for bs in self.regimes
        }

        self.submit_time: Optional[float] = None
        # round_id -> (throughput, batch size), insertion-ordered.
        self.throughput_schedule: Dict[int, tuple] = {}
        self.round_duration = round_duration
        # The duration rescale is a pure (and idempotent) function of the
        # throughput schedule; the planner calls it for every job on
        # every replan, so memoize on the schedule's version.
        self._schedule_version = 0
        self._rescale_key: Optional[int] = None
        self._bs_durations_cache: Optional[Dict[int, float]] = None

    # -- serialization --------------------------------------------------
    def state_dict(self) -> dict:
        """Plain dicts/arrays snapshot for simulator checkpointing. Every
        field is host-side numpy/python state (no jitted objects), so the
        snapshot round-trips losslessly."""
        return dict(self.__dict__)

    @classmethod
    def from_state(cls, state: dict) -> "JobMetadata":
        obj = cls.__new__(cls)
        obj.__dict__.update(state)
        return obj

    # -- lifecycle ------------------------------------------------------
    def submit(self, time: float) -> None:
        if self.submit_time is None:
            self.submit_time = time

    def complete(self, num_epochs: Optional[int] = None) -> None:
        """Record epoch progress; with no argument, mark fully finished
        (reference: job_metadata.py:64-78)."""
        if num_epochs is None:
            self.completed_epochs = self.total_epochs
        else:
            if num_epochs > self.total_epochs:
                raise ValueError(f"epoch progress {num_epochs} > {self.total_epochs}")
            self.completed_epochs = int(num_epochs)

    def record_round_throughput(self, round_id: int, throughput: float, bs: int) -> None:
        """(reference: job_metadata.py:80-92)"""
        self.throughput_schedule[int(round_id)] = (float(throughput), int(bs))
        self._schedule_version += 1

    # -- duration model -------------------------------------------------
    def recompute_epoch_durations(self) -> None:
        """Rescale the per-epoch duration estimates so that the samples/sec
        they imply matches what the measured throughput schedule observed
        (reference: job_metadata.py:94-148).

        measured samples: integrate throughput*bs over the measured rounds
        (each measurement is extended back to the previous one). estimated
        samples: walk the original per-epoch durations across the same time
        window, counting whole epochs plus the in-progress fraction.
        """
        if not self.throughput_schedule:
            return
        if self._schedule_version == self._rescale_key:
            return
        self._rescale_key = self._schedule_version
        self._bs_durations_cache = None
        rounds = np.array(sorted(self.throughput_schedule), dtype=np.int64)
        tputs = np.array(
            [self.throughput_schedule[r][0] for r in rounds], dtype=np.float64
        )
        bss = np.array([self.throughput_schedule[r][1] for r in rounds], dtype=np.float64)
        spans = np.diff(np.concatenate([[0], rounds])).astype(np.float64)
        measured_nsamples = float(np.sum(bss * tputs * self.round_duration * spans))
        measured_time_range = self.round_duration * float(rounds[-1])

        cum = np.cumsum(self.estimated_epoch_durations)
        # Number of whole estimated epochs that fit in the measured window.
        whole = int(np.searchsorted(cum, measured_time_range, side="right"))
        whole = min(whole, len(cum))
        estimated_nsamples = self.nsamples_per_epoch * whole
        elapsed = float(cum[whole - 1]) if whole > 0 else 0.0
        partial = measured_time_range - elapsed
        if partial > 0:
            # The fractional epoch is valued against the same as-profiled
            # durations the whole-epoch count uses, making this recompute
            # idempotent. (The reference prices the fraction at the
            # already-rescaled duration, job_metadata.py:131-134, so its
            # repeated recomputes oscillate with no new measurements — a
            # consciously fixed quirk, SURVEY §7.)
            idx = min(whole, len(self.estimated_epoch_durations) - 1)
            estimated_nsamples += self.nsamples_per_epoch * (
                partial / self.estimated_epoch_durations[idx]
            )

        if measured_nsamples <= 0 or estimated_nsamples <= 0:
            return
        scale = estimated_nsamples / measured_nsamples
        self.epoch_durations = self.estimated_epoch_durations * scale

    def bs_epoch_durations(self) -> Dict[int, float]:
        """Mean epoch duration per batch-size regime, after rescaling
        (reference: job_metadata.py:150-165)."""
        self.recompute_epoch_durations()
        if self._bs_durations_cache is None:
            out: Dict[int, float] = {}
            for bs in self.regimes:
                mask = self.epoch_batch_sizes == bs
                out[int(bs)] = float(np.mean(self.epoch_durations[mask]))
            self._bs_durations_cache = out
        # Copy: callers may adjust the mapping for what-if math without
        # corrupting the cache.
        return dict(self._bs_durations_cache)

    def mean_epoch_duration(self) -> float:
        """Interpolated epoch duration: mean over the completed epochs plus
        the one in progress (reference: shockwave.py:116-120 footnote of
        EQ 7)."""
        return float(np.mean(self.epoch_durations[: self.completed_epochs + 1]))

    # -- remaining-runtime prediction -----------------------------------
    def _measured_changepoint(self):
        """``(last_bs, switched)`` derived from the measured throughput
        schedule: the regime the job was last observed running in, and
        whether a batch-size switch was ever MEASURED (recorded bs
        differing across rounds). A pure function of the schedule —
        no hidden detector state — so checkpoint restore and
        flight-recorder replay (which reconstruct the schedule exactly)
        re-derive the identical change-point, and a planner decision
        downstream of the reweight replays bit-for-bit. Memoized on the
        schedule version like the duration rescale."""
        if getattr(self, "_changepoint_key", None) == self._schedule_version:
            return self._changepoint
        last_bs = None
        switched = False
        for r in sorted(self.throughput_schedule):
            bs = self.throughput_schedule[r][1]
            if last_bs is not None and bs != last_bs:
                switched = True
            last_bs = bs
        self._changepoint_key = self._schedule_version
        self._changepoint = (last_bs, switched)
        return self._changepoint

    def _regime_posterior(self) -> Tuple[Dict[int, int], Dict[int, float]]:
        """Dirichlet posterior over batch-size regimes for the epochs
        ahead: prior + one count per observed (profile-pattern) epoch.

        Change-point fix: once the MEASURED schedule shows the job
        switched regimes, the profile's switch point is known wrong, so
        the prior is reweighted to concentrate ``1 - CHANGEPOINT_RETAIN``
        of its mass on the regime the job was last observed in (the
        observed-epoch counts still ride on top and the rebase/subtract
        in the callers is unchanged). Without a measured switch — every
        static job — the posterior is bit-identical to the unweighted
        math."""
        observed = self.epoch_batch_sizes[: self.completed_epochs + 1]
        counts = {
            int(bs): int(np.sum(observed == bs)) for bs in np.unique(observed)
        }
        prior = self.dirichlet
        last_bs, switched = self._measured_changepoint()
        if switched and last_bs in self.dirichlet and len(self.dirichlet) > 1:
            total = float(sum(self.dirichlet.values()))
            spread = CHANGEPOINT_RETAIN * total / len(self.dirichlet)
            prior = {bs: spread for bs in self.dirichlet}
            prior[last_bs] += (1.0 - CHANGEPOINT_RETAIN) * total
        return counts, {
            bs: conc + counts.get(bs, 0) for bs, conc in prior.items()
        }

    def remaining_runtime(self) -> float:
        """Expected remaining runtime under the Dirichlet regime posterior
        (reference: job_metadata.py:167-202).

        Posterior = prior + one count per observed epoch (including the
        in-progress one); rebased so the concentrations sum to total_epochs;
        observed epochs are then subtracted back out (floored at zero); what
        remains is the expected number of future epochs in each regime,
        priced at that regime's mean epoch duration.
        """
        if len(self.dirichlet) == 0 or self.completed_epochs >= self.total_epochs:
            return 1.0
        counts, posterior = self._regime_posterior()
        total_conc = sum(posterior.values())
        rebased = {
            bs: self.total_epochs * conc / total_conc for bs, conc in posterior.items()
        }
        for bs, n in counts.items():
            rebased[bs] = max(0.0, rebased[bs] - n)
        durations = self.bs_epoch_durations()
        expected = float(sum(rebased[bs] * durations[bs] for bs in rebased))
        # Floor at 1 s: an incomplete job always has work left. A
        # single-epoch job would otherwise predict exactly 0 (its
        # in-progress epoch is counted as observed and subtracted back
        # out), which zeroes the planner's finish-time estimate — latent
        # in the reference, whose traces have no 1-epoch jobs.
        return max(1.0, expected)

    def remaining_runtime_to_completion(
        self, run_time_so_far_s: float, base: Optional[float] = None
    ) -> float:
        """Remaining processing seconds from NOW to job completion.

        :meth:`remaining_runtime` prices only the epochs AFTER the
        in-progress one (the reference counts the in-progress epoch as
        observed and subtracts it from the posterior, job_metadata.py:
        167-202) — correct for the planner's horizon math but short by
        up to one epoch as a now-to-finish forecast. For calibration
        scoring against realized processing time, add back the
        unfinished remainder of the in-progress epoch, estimated from
        the processing seconds the job has already received.

        ``base`` lets a caller that already evaluated
        :meth:`remaining_runtime` (the posterior math is not memoized)
        avoid recomputing it.
        """
        if self.completed_epochs >= self.total_epochs:
            return 0.0
        done = float(np.sum(self.epoch_durations[: self.completed_epochs]))
        idx = min(self.completed_epochs, len(self.epoch_durations) - 1)
        current = float(self.epoch_durations[idx])
        into_epoch = min(max(float(run_time_so_far_s) - done, 0.0), current)
        if base is None:
            base = self.remaining_runtime()
        return base + (current - into_epoch)

    def remaining_runtime_interval(
        self, z: float = 1.645, mean: Optional[float] = None
    ):
        """(lo, hi) credible interval around :meth:`remaining_runtime`
        from the Dirichlet regime posterior — the uncertainty the
        calibration tracker scores coverage against.

        The regime mixture p ~ Dirichlet(alpha) prices one future epoch
        at sum_b p_b * d_b, whose closed-form variance is
        (sum_b a_b d_b^2 / a0 - mu^2) / (a0 + 1); over n remaining
        epochs (shared p) the runtime std is n * sqrt(var). The half
        width is floored at one mean epoch duration and 5% of the mean:
        a single-regime posterior has zero Dirichlet variance but its
        durations still carry >=1s rounding and rescale error, and a
        degenerate interval would score 0% coverage on forecasts that
        are in fact near-exact. ``mean`` takes a pre-computed
        :meth:`remaining_runtime` value (same contract as
        :meth:`remaining_runtime_to_completion`'s ``base``).
        """
        if mean is None:
            mean = self.remaining_runtime()
        if len(self.dirichlet) == 0 or self.completed_epochs >= self.total_epochs:
            return mean, mean
        _, posterior = self._regime_posterior()
        alpha0 = sum(posterior.values())
        durations = self.bs_epoch_durations()
        mu = sum(posterior[bs] * durations[bs] for bs in posterior) / alpha0
        second_moment = (
            sum(posterior[bs] * durations[bs] ** 2 for bs in posterior)
            / alpha0
        )
        var_per_epoch = max(second_moment - mu * mu, 0.0) / (alpha0 + 1.0)
        n_remaining = max(self.total_epochs - (self.completed_epochs + 1), 0)
        std = n_remaining * float(np.sqrt(var_per_epoch))
        half = max(z * std, self.mean_epoch_duration(), 0.05 * mean)
        return max(mean - half, 0.0), mean + half


def batch_remaining_runtimes(metadatas: Sequence[JobMetadata]) -> np.ndarray:
    """Remaining runtimes for a set of jobs as one array (round-prep path)."""
    return np.array([m.remaining_runtime() for m in metadatas], dtype=np.float64)
