"""Dynamic-adaptation predictor (reference: scheduler/job_metadata.py)."""

from shockwave_tpu.predictor.metadata import JobMetadata, batch_remaining_runtimes

__all__ = ["JobMetadata", "batch_remaining_runtimes"]
