"""Device-mesh construction and sharding helpers.

The framework's standard mesh axes:
  "data"  — batch (data parallelism; gradients psum over it)
  "model" — tensor parallelism (attention heads / MLP hidden / experts)
  "seq"   — sequence/context parallelism (ring attention shards)
  "pipe"  — pipeline parallelism (transformer stages; GPipe microbatch
            schedule in shockwave_tpu/parallel/pipeline.py)

Jobs pick a (data, model, seq[, pipe]) factorization of their gang;
single-chip jobs use a trivial 1x1x1x1 mesh. All collectives are emitted
by XLA from sharding annotations — nothing here issues them by hand
except ring attention's ppermute
(shockwave_tpu/parallel/ring_attention.py).
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

AXES = ("data", "model", "seq", "pipe")


def make_mesh(
    shape: Optional[Tuple[int, ...]] = None,
    devices: Optional[Sequence] = None,
) -> Mesh:
    """Mesh over (data, model, seq[, pipe]). A 3-tuple shape gets a
    trailing pipe axis of 1 (back-compat). Default: all devices on
    "data"."""
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    if shape is None:
        shape = (n, 1, 1, 1)
    shape = tuple(shape) + (1,) * (len(AXES) - len(shape))
    if int(np.prod(shape)) != n:
        raise ValueError(f"mesh shape {shape} != {n} devices")
    return Mesh(np.asarray(devices).reshape(shape), AXES)


def spec(*names) -> PartitionSpec:
    return PartitionSpec(*names)


def shard(mesh: Mesh, *names) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec(*names))


def batch_spec() -> PartitionSpec:
    """Activations: batch over data, sequence over seq."""
    return PartitionSpec("data", "seq")


def factorize_gang(
    num_devices: int,
    seq_parallel: int = 1,
    model_parallel: int = 1,
    pipe_parallel: int = 1,
):
    """(data, model, seq, pipe) shape for a gang of ``num_devices``."""
    denom = seq_parallel * model_parallel * pipe_parallel
    if num_devices % denom != 0:
        raise ValueError(
            f"{num_devices} devices not divisible by model={model_parallel} "
            f"x seq={seq_parallel} x pipe={pipe_parallel}"
        )
    return (
        num_devices // denom,
        model_parallel,
        seq_parallel,
        pipe_parallel,
    )
