"""Pipeline parallelism over the "pipe" mesh axis, SPMD-style.

The reference has no intra-job model parallelism of any kind (SURVEY
§2.3); this is part of the TPU build's beyond-parity parallelism story
(with tensor parallelism, ring-attention sequence parallelism, and MoE
expert parallelism in shockwave_tpu/models/transformer.py).

Design — the XLA-native formulation (no hand-written send/recv loop):

  * The transformer's blocks are STACKED into S stages: every parameter
    gains a leading [S] axis, sharded over the "pipe" mesh axis, so each
    device group holds exactly one stage's weights.
  * The GPipe schedule is one ``lax.scan`` over T = M + S - 1 ticks
    (M = number of microbatches). The carry holds a [S, microbatch, ...]
    activation buffer, also stage-sharded. Each tick applies
    ``vmap(stage_fn)`` across the stage axis — under the sharding this
    is embarrassingly parallel, one stage per device group — then ROLLS
    the buffer by one stage. The roll of a pipe-sharded axis is exactly
    a collective-permute over ICI, which is how XLA lowers it; no
    explicit ppermute needed.
  * Microbatch t enters stage 0 at tick t and exits stage S-1 at tick
    t + S - 1. Injection consumes the scan's xs input directly (the
    microbatch array zero-padded by S-1 ticks, statically sliced per
    iteration), and collection is the scan's stacked per-tick output
    with a STATIC ys[S-1:] slice at the end — no masked dynamic
    gathers/scatters and no output buffer in the carry. (The earlier
    formulation carried the output array through the scan and
    dynamic-update-scattered one microbatch per tick; that machinery
    measured 26.6% single-stage overhead, results/moe_pipeline_tpu.json
    v1.) S=1 short-circuits to an unrolled per-microbatch loop — the
    schedule has no bubble and needs no stage buffer at all.

The pipeline is differentiable end to end (scan + gather/scatter +
roll), so the same function serves forward and backward; the backward
pass pipelines in reverse automatically under ``jax.grad``. Bubble
fraction is the standard GPipe (S-1)/(M+S-1).
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec


def gpipe_apply(
    stage_fn: Callable[[Any, jnp.ndarray], jnp.ndarray],
    stage_params: Any,
    microbatches: jnp.ndarray,
) -> jnp.ndarray:
    """Run microbatches through S pipelined stages.

    Args:
      stage_fn: ``(params_of_one_stage, x [mb, ...]) -> y [mb, ...]`` —
        one stage's computation, same activation shape in and out.
      stage_params: pytree whose every leaf has a leading [S] stage axis
        (shard it over "pipe" for real pipeline parallelism).
      microbatches: ``[M, mb, ...]`` input microbatches.

    Returns:
      ``[M, mb, ...]`` outputs, microbatch-aligned with the input.
    """
    S = jax.tree_util.tree_leaves(stage_params)[0].shape[0]
    M = microbatches.shape[0]
    stage_apply = jax.vmap(stage_fn)

    if S == 1:
        # Degenerate pipeline: no bubble, no stage buffer — apply the
        # bare stage function per microbatch. Unrolled rather than
        # lax.map: at S=1 a scan buys no memory (the backward keeps
        # every microbatch's residuals either way, stacked in the scan
        # carry) but its per-iteration machinery measured ~25% of a
        # train step on CPU vs ~2% unrolled; the scan stays as a
        # fallback for microbatch counts where unrolling would bloat
        # compile time.
        params0 = jax.tree_util.tree_map(lambda p: p[0], stage_params)
        if M == 1:
            return stage_fn(params0, microbatches[0])[None]
        if M <= 32:
            return jnp.stack(
                [stage_fn(params0, microbatches[m]) for m in range(M)]
            )
        return jax.lax.map(lambda x: stage_fn(params0, x), microbatches)

    # Zero-pad the input stream by the drain ticks: tick t injects
    # xs[t] (a static scan slice); the pad values flow into stage 0
    # after the real microbatches and their outputs are never
    # collected.
    pad = jnp.zeros((S - 1,) + microbatches.shape[1:], microbatches.dtype)
    xs = jnp.concatenate([microbatches, pad], axis=0)  # [T, mb, ...]
    buf = jnp.zeros((S,) + microbatches.shape[1:], microbatches.dtype)

    def tick(buf, x_t):
        buf = buf.at[0].set(x_t)
        y = stage_apply(stage_params, buf)
        # Stage s's output becomes stage s+1's input: a roll of the
        # stage axis, which XLA lowers to a collective-permute when the
        # axis is sharded over "pipe". The last stage's output is the
        # tick's collected (scan-stacked) result.
        return jnp.roll(y, 1, axis=0), y[S - 1]

    _, ys = jax.lax.scan(tick, buf, xs)
    # Microbatch t exits at tick t + S - 1: a static slice of the
    # stacked outputs replaces the per-tick masked dynamic update.
    return ys[S - 1:]


def sequential_apply(
    stage_fn: Callable[[Any, jnp.ndarray], jnp.ndarray],
    stage_params: Any,
    x: jnp.ndarray,
) -> jnp.ndarray:
    """Reference semantics: the stages applied back-to-back on one batch
    (what the pipeline must numerically reproduce per microbatch)."""
    S = jax.tree_util.tree_leaves(stage_params)[0].shape[0]
    for s in range(S):
        params_s = jax.tree_util.tree_map(lambda p: p[s], stage_params)
        x = stage_fn(params_s, x)
    return x


class PipelinedLM:
    """The flagship transformer LM with its blocks pipelined over "pipe".

    Embedding/unembedding and final LayerNorm run outside the pipeline
    (replicated); the ``num_layers`` blocks are grouped into
    ``num_stages`` stages of equal depth, their parameters stacked with
    a leading stage axis and sharded over the mesh's "pipe" axis.

    Plain-function flavor (init/loss as pure functions over a params
    pytree) rather than a flax module: the stage stacking and the scan
    schedule live in JAX-land where their sharding is explicit.
    """

    def __init__(self, config, num_stages: int, num_microbatches: int,
                 mesh: Optional[Mesh] = None):
        from shockwave_tpu.models.transformer import Block

        if config.num_layers % num_stages != 0:
            raise ValueError(
                f"{config.num_layers} layers not divisible into "
                f"{num_stages} stages"
            )
        # Validated here, not only in init(): a PipelinedLM driven with
        # externally constructed params would otherwise silently run
        # with no position encoding (_embed just skips the table).
        if config.positional not in ("learned", "rope"):
            raise ValueError(
                f"positional must be 'learned' or 'rope', got "
                f"{config.positional!r}"
            )
        if config.num_experts > 0 and config.moe_aux_weight > 0.0:
            # The stage function applies blocks without a mutable
            # "losses" collection, so the router's sown balance loss
            # would be silently dropped — training an MoE here with the
            # config promising an aux loss would quietly reproduce the
            # v1 router collapse (same convention as attention_window
            # on non-flash paths).
            raise ValueError(
                "PipelinedLM does not thread the MoE load-balancing "
                "aux loss; set moe_aux_weight=0.0 to pipeline an MoE "
                "explicitly unbalanced"
            )
        self.config = config
        self.num_stages = num_stages
        self.num_microbatches = num_microbatches
        self.mesh = mesh
        self.layers_per_stage = config.num_layers // num_stages
        # One stage = layers_per_stage Blocks applied in sequence. The
        # blocks inside a stage are themselves stacked (cheap scan-free
        # python loop over a small constant).
        self._block = Block(config, mesh=None)

    # -- parameters -----------------------------------------------------
    def init(self, rng, tokens) -> dict:
        cfg = self.config
        S, Lps = self.num_stages, self.layers_per_stage
        d = cfg.d_model
        x = jnp.zeros(tokens[:, :-1].shape + (d,), jnp.float32)
        rngs = jax.random.split(rng, S * Lps + 1)

        def init_block(r):
            import flax

            params = self._block.init(r, x)["params"]
            # Unbox flax partitioning metadata: the stage stacking below
            # changes ranks, and the pipeline shards explicitly by axis
            # position rather than by logical name.
            return jax.tree_util.tree_map(
                lambda p: p.value
                if isinstance(p, flax.core.meta.Partitioned)
                else p,
                params,
                is_leaf=lambda p: isinstance(p, flax.core.meta.Partitioned),
            )

        block_params = jax.vmap(init_block)(
            rngs[: S * Lps]
        )  # leading axis [S * Lps]
        # Regroup into [S, Lps, ...].
        block_params = jax.tree_util.tree_map(
            lambda p: p.reshape((S, Lps) + p.shape[1:]), block_params
        )
        r = rngs[-1]
        params = {
            "blocks": block_params,
            "embedding": jax.random.normal(
                jax.random.fold_in(r, 0), (cfg.vocab_size, d)
            )
            * 0.02,
            "ln_f_scale": jnp.ones((d,)),
            "ln_f_bias": jnp.zeros((d,)),
        }
        # Under rope the positions live inside each Block's Attention
        # (apply_rope — correct here because GPipe microbatches split
        # the BATCH dim, so every stage sees whole sequences); adding
        # the learned table too would double-encode positions.
        if cfg.positional == "learned":
            params["positional"] = (
                jax.random.normal(
                    jax.random.fold_in(r, 1), (cfg.max_len, d)
                )
                * 0.02
            )
        if self.mesh is not None:
            params = self.shard_params(params)
        return params

    def shard_params(self, params: dict) -> dict:
        """Place block params stage-sharded over "pipe", the rest
        replicated."""
        mesh = self.mesh
        pipe = NamedSharding(mesh, PartitionSpec("pipe"))
        rep = NamedSharding(mesh, PartitionSpec())
        out = dict(params)
        out["blocks"] = jax.tree_util.tree_map(
            lambda p: jax.device_put(p, pipe), params["blocks"]
        )
        for k in ("embedding", "positional", "ln_f_scale", "ln_f_bias"):
            if k in params:
                out[k] = jax.device_put(params[k], rep)
        return out

    # -- compute --------------------------------------------------------
    def _stage_fn(self, stage_params, x):
        for i in range(self.layers_per_stage):
            p_i = jax.tree_util.tree_map(lambda p: p[i], stage_params)
            x = self._block.apply({"params": p_i}, x)
        return x

    def _embed(self, params, tokens):
        x = params["embedding"][tokens]
        if "positional" in params:
            x = x + params["positional"][: tokens.shape[1]]
        return x

    def _head(self, params, x):
        mean = jnp.mean(x, axis=-1, keepdims=True)
        var = jnp.var(x, axis=-1, keepdims=True)
        x = (x - mean) * jax.lax.rsqrt(var + 1e-6)
        x = x * params["ln_f_scale"] + params["ln_f_bias"]
        return x @ params["embedding"].T

    def logits(self, params, tokens) -> jnp.ndarray:
        """[B, S_len] tokens -> [B, S_len, vocab]; B must split into
        num_microbatches."""
        M = self.num_microbatches
        B = tokens.shape[0]
        if B % M != 0:
            raise ValueError(f"batch {B} not divisible by {M} microbatches")
        x = self._embed(params, tokens)
        mb = x.reshape((M, B // M) + x.shape[1:])
        y = gpipe_apply(self._stage_fn, params["blocks"], mb)
        y = y.reshape(x.shape)
        return self._head(params, y)

    def logits_sequential(self, params, tokens) -> jnp.ndarray:
        """Non-pipelined reference path (for equivalence tests)."""
        x = self._embed(params, tokens)
        y = sequential_apply(self._stage_fn, params["blocks"], x)
        return self._head(params, y)

    def loss(self, params, tokens) -> jnp.ndarray:
        from shockwave_tpu.models.small_models import token_xent

        return token_xent(
            self.logits(params, tokens[:, :-1]), tokens[:, 1:]
        )
