"""Ring attention: exact causal attention over a sequence-sharded mesh axis.

Long-context support: the sequence dimension is sharded across the "seq"
mesh axis; each device holds one block of Q and rotates K/V blocks around
the ring with ppermute, maintaining a numerically stable online softmax
(running max + normalizer). Compute overlaps the ICI transfer ring hop by
hop; memory per device is O(S/P * S/P) per block pair instead of O(S^2).

Two hop bodies, selected by ring_attention's ``inner`` argument: the
einsum body (original; materializes the local score block per hop) and
the Pallas flash body (default whenever the local block tiles into
lane-aligned kernel blocks) — per-hop compute is the flash kernel from
shockwave_tpu/ops/flash_attention.py via its lse-returning entry point,
so scores never leave VMEM even within a hop, and hops whose K/V block
is entirely in the causal future are skipped instead of computed fully
masked (~half of all hop work on a P-shard ring).

This is the TPU-native counterpart of the long-context machinery the task
calls for (the reference has none — SURVEY §5.7); the pattern follows the
public blockwise/ring-attention literature (Liu et al.) re-derived for
jax.shard_map + lax.ppermute.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from shockwave_tpu.utils.compat import pcast_varying, shard_map

from shockwave_tpu.ops.flash_attention import (
    flash_attention_lse,
    flash_tiles,
)


def _block_attention(q, k, v, scale, mask):
    """Scores and value products for one (Q-block, K/V-block) pair.
    q: [B, Sq, H, D], k/v: [B, Sk, H, D], mask: [Sq, Sk] additive.
    Softmax state is float32 regardless of the input dtype (bfloat16
    exp/normalizer arithmetic loses too much precision); the matmuls
    still run in the input dtype on the MXU."""
    scores = jnp.einsum(
        "bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32
    ) * scale
    scores = scores + mask[None, None, :, :]
    block_max = jnp.max(scores, axis=-1)  # [B, H, Sq]
    # A fully-masked row has block_max = -inf; subtracting it would give
    # exp(nan). Any finite subtrahend keeps exp(-inf) = 0.
    safe_max = jnp.where(jnp.isfinite(block_max), block_max, 0.0)
    probs = jnp.exp(scores - safe_max[..., None])
    block_denom = jnp.sum(probs, axis=-1)  # [B, H, Sq]
    block_out = jnp.einsum(
        "bhqk,bkhd->bqhd",
        probs.astype(v.dtype),
        v,
        preferred_element_type=jnp.float32,
    )
    return block_out, block_max, block_denom


def _ring_attention_local(q, k, v, axis_name: str, all_axes: tuple,
                          group: int = 1):
    """Per-shard body under shard_map: q [B, S_local, H, D], k/v
    [B, S_local, H // group, D]; returns the local attention output.
    With group > 1 (grouped-query attention) the K/V blocks rotate
    around the ring at their SMALL size — ICI traffic and carry HBM
    stay divided by the group — and are repeated to the query head
    count only transiently, per hop, for the einsum."""
    num_shards = jax.lax.psum(1, axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    B, S, H, D = q.shape
    scale = 1.0 / jnp.sqrt(jnp.asarray(D, jnp.float32))

    q_pos = my_idx * S + jnp.arange(S)

    def causal_mask(src_idx):
        k_pos = src_idx * S + jnp.arange(S)
        return jnp.where(
            k_pos[None, :] > q_pos[:, None], -jnp.inf, 0.0
        ).astype(jnp.float32)

    def step(i, carry):
        acc, m, l, k_blk, v_blk = carry
        src_idx = (my_idx - i) % num_shards
        k_full = jnp.repeat(k_blk, group, axis=2) if group > 1 else k_blk
        v_full = jnp.repeat(v_blk, group, axis=2) if group > 1 else v_blk
        blk_out, blk_max, blk_denom = _block_attention(
            q, k_full, v_full, scale, causal_mask(src_idx)
        )
        # Online softmax merge (running max m, normalizer l).
        new_m = jnp.maximum(m, blk_max)
        # A fully-masked block yields -inf max; exp(-inf - -inf) traps, so
        # clamp the correction exponents.
        old_scale = jnp.exp(jnp.clip(m - new_m, -80.0, 0.0))
        blk_scale = jnp.exp(jnp.clip(blk_max - new_m, -80.0, 0.0))
        # Where the block contributed nothing, keep the old state.
        empty = jnp.isinf(blk_max) & (blk_max < 0)
        blk_scale = jnp.where(empty, 0.0, blk_scale)
        new_m = jnp.where(jnp.isinf(new_m) & (new_m < 0), m, new_m)
        l = l * old_scale + blk_denom * blk_scale
        acc = (
            acc * old_scale.transpose(0, 2, 1)[..., None]
            + blk_out * blk_scale.transpose(0, 2, 1)[..., None]
        )
        k_blk = jax.lax.ppermute(
            k_blk, axis_name, [(j, (j + 1) % num_shards) for j in range(num_shards)]
        )
        v_blk = jax.lax.ppermute(
            v_blk, axis_name, [(j, (j + 1) % num_shards) for j in range(num_shards)]
        )
        return acc, new_m, l, k_blk, v_blk

    acc0 = jnp.zeros(q.shape, jnp.float32)
    m0 = jnp.full((B, H, S), -jnp.inf, dtype=jnp.float32)
    l0 = jnp.zeros((B, H, S), dtype=jnp.float32)
    # Mark the fresh carries as device-varying so the loop carry type
    # matches the per-shard outputs (shard_map vma tracking).
    acc0 = pcast_varying(acc0, all_axes)
    m0 = pcast_varying(m0, all_axes)
    l0 = pcast_varying(l0, all_axes)
    acc, m, l, _, _ = jax.lax.fori_loop(
        0, num_shards, step, (acc0, m0, l0, k, v)
    )
    denom = l.transpose(0, 2, 1)[..., None]
    return (acc / jnp.maximum(denom, 1e-20)).astype(q.dtype)


def _ring_flash_local(q, k, v, axis_name: str, all_axes: tuple):
    """Ring attention body whose hop compute is the Pallas flash kernel
    (shockwave_tpu/ops/flash_attention.py) instead of a dense einsum:
    no [S_local, S_local] score materialization per hop, and hops whose
    K/V block is entirely in the causal future are skipped outright
    (the dense body computes them fully masked — for a P-shard ring
    that is ~half of all hop work).

    Hop 0 (own block) is peeled out of the loop: it is the only
    causal hop, and the kernel's causal flag is compile-time. The
    remaining hops merge normalized partial results in (out, lse)
    space: out = sum_i out_i * exp(lse_i - lse_total), the exact
    identity the kernel's lse output exists to support."""
    num_shards = jax.lax.psum(1, axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    B, S, H, D = q.shape
    perm = [(j, (j + 1) % num_shards) for j in range(num_shards)]

    out0, lse0 = flash_attention_lse(q, k, v, causal=True)
    acc = out0.astype(jnp.float32)
    lse = lse0  # [B, H, S]; finite: every causal row sees >= 1 key

    def step(i, carry):
        acc, lse, k_blk, v_blk = carry
        k_blk = jax.lax.ppermute(k_blk, axis_name, perm)
        v_blk = jax.lax.ppermute(v_blk, axis_name, perm)
        src_idx = (my_idx - i) % num_shards

        def live(acc, lse, q, k_blk, v_blk):
            out_h, lse_h = flash_attention_lse(q, k_blk, v_blk,
                                               causal=False)
            lse_new = jnp.logaddexp(lse, lse_h)
            w_prev = jnp.exp(lse - lse_new).transpose(0, 2, 1)[..., None]
            w_hop = jnp.exp(lse_h - lse_new).transpose(0, 2, 1)[..., None]
            return acc * w_prev + out_h.astype(jnp.float32) * w_hop, lse_new

        def dead(acc, lse, q, k_blk, v_blk):
            return acc, lse

        # Blocks from shards ahead of this one are entirely in the
        # causal future: skip the kernel AND the merge arithmetic.
        acc, lse = jax.lax.cond(src_idx < my_idx, live, dead,
                                acc, lse, q, k_blk, v_blk)
        return acc, lse, k_blk, v_blk

    acc, lse, _, _ = jax.lax.fori_loop(
        1, num_shards, step, (acc, lse, k, v)
    )
    return acc.astype(q.dtype)


def ring_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    mesh: Mesh,
    seq_axis: str = "seq",
    inner: str = "auto",
) -> jnp.ndarray:
    """Causal ring attention over ``mesh``'s ``seq_axis``.

    q, k, v: [batch, seq, heads, head_dim] with seq sharded on seq_axis;
    batch shards over the mesh's first non-seq axis and heads over the
    second, whatever the mesh calls them (the canonical mesh names them
    "data" and "model").

    ``inner`` picks the per-hop compute: "flash" runs the Pallas flash
    kernels per hop (no per-hop score materialization, causally-dead
    hops skipped), "dense" the einsum body, "auto" (default) flash
    whenever the local sequence block tiles into lane-aligned kernel
    blocks.
    """
    other_axes = [a for a in mesh.axis_names if a != seq_axis]
    batch_axis = other_axes[0] if len(other_axes) > 0 else None
    head_axis = other_axes[1] if len(other_axes) > 1 else None
    io_spec = P(batch_axis, seq_axis, head_axis, None)
    # vma axes = exactly the axes the io spec shards over; pcast-ing the
    # fresh loop carries to MORE axes (e.g. an unused "pipe" axis) would
    # make the carry type diverge from the q-derived accumulator.
    vary_axes = tuple(a for a in (batch_axis, seq_axis, head_axis) if a)
    if inner not in ("auto", "flash", "dense"):
        raise ValueError(
            f"inner must be 'auto', 'flash' or 'dense', got {inner!r}"
        )
    s_local = q.shape[1] // mesh.shape[seq_axis]
    if inner == "auto":
        inner = "flash" if flash_tiles(s_local) else "dense"
    if k.shape[2] != q.shape[2]:
        # Grouped-query attention: both bodies rotate the SMALL K/V
        # tensors around the ring — ICI traffic and carry HBM divided
        # by the group. The flash body reads shared heads through the
        # kernel index maps; the dense einsum body repeats each block
        # transiently, per hop.
        if q.shape[2] % k.shape[2]:
            raise ValueError(
                f"q heads ({q.shape[2]}) must be a multiple of kv "
                f"heads ({k.shape[2]})"
            )
        kv_group = q.shape[2] // k.shape[2]
    else:
        kv_group = 1
    if head_axis is not None and k.shape[2] % mesh.shape[head_axis]:
        # shard_map would fail with an opaque divisibility error at
        # trace time; K/V heads shard over the head axis at their
        # grouped (small) count, so that count bounds the usable mesh.
        raise ValueError(
            f"K/V head count ({k.shape[2]}) must be divisible by the "
            f"mesh's {head_axis!r} axis size ({mesh.shape[head_axis]}): "
            "grouped-query K/V rotate sharded over that axis"
        )
    if inner == "flash":
        body = functools.partial(
            _ring_flash_local, axis_name=seq_axis, all_axes=vary_axes
        )
    else:
        body = functools.partial(
            _ring_attention_local, axis_name=seq_axis,
            all_axes=vary_axes, group=kv_group,
        )
    fn = shard_map(
        body,
        mesh=mesh,
        in_specs=(io_spec, io_spec, io_spec),
        out_specs=io_spec,
        # pallas_call's out_shape carries no vma type; disable the
        # varying-across-mesh check for the flash body (the same
        # constraint ulysses.py documents for its local flash kernel).
        check_vma=(inner != "flash"),
    )
    return fn(q, k, v)


def dense_causal_attention(q, k, v):
    """Reference single-device causal attention (tests compare against
    this). Softmax in float32; matmuls in the input dtype."""
    B, S, H, D = q.shape
    scale = 1.0 / jnp.sqrt(jnp.asarray(D, jnp.float32))
    scores = jnp.einsum(
        "bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32
    ) * scale
    mask = jnp.where(
        jnp.arange(S)[None, :] > jnp.arange(S)[:, None], -jnp.inf, 0.0
    ).astype(jnp.float32)
    scores = scores + mask[None, None]
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum(
        "bhqk,bkhd->bqhd",
        probs.astype(v.dtype),
        v,
        preferred_element_type=jnp.float32,
    )
    return out.astype(q.dtype)
