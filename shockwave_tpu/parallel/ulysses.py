"""All-to-all (Ulysses-style) sequence/context parallelism.

The second of the framework's two long-context strategies (SURVEY §5.7;
the reference has neither). Ring attention (ring_attention.py) keeps the
sequence sharded and rotates K/V blocks over ICI — communication scales
with the number of hops. Ulysses instead re-shards *once* per attention
call: an all-to-all over the "seq" mesh axis exchanges the sequence
sharding for a head sharding, so every device holds the FULL sequence
for H/P of the heads, runs an ordinary (or flash) causal attention
locally, and a second all-to-all restores the sequence sharding. Two
collectives total, each moving S*H*D/P elements per device — cheaper
than the ring when heads are plentiful and the per-hop latency of P-1
ppermutes would dominate; the trade-off follows the public DeepSpeed-
Ulysses pattern, re-derived for jax.shard_map + lax.all_to_all.

Requires the local head count to divide by the seq-axis size (heads may
additionally be tensor-parallel over "model"; the constraint applies
after that split).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from shockwave_tpu.utils.compat import shard_map

from shockwave_tpu.parallel.ring_attention import dense_causal_attention


def _ulysses_local(q, k, v, axis_name: str, local_attention: str):
    """Per-shard body under shard_map.

    q/k/v: the local [B, S/P, H, D] block (H already divided by any
    tensor-parallel axis). all_to_all trades the seq shard for a head
    shard, attention runs on the full sequence, and the inverse
    all_to_all restores the input sharding.
    """
    a2a = functools.partial(jax.lax.all_to_all, axis_name=axis_name, tiled=True)
    # [B, S/P, H, D] -> [B, S, H/P, D]; tiled all_to_all concatenates the
    # received pieces in device order, so sequence blocks land in
    # position order and the plain causal mask is correct.
    q = a2a(q, split_axis=2, concat_axis=1)
    k = a2a(k, split_axis=2, concat_axis=1)
    v = a2a(v, split_axis=2, concat_axis=1)
    if local_attention == "flash":
        from shockwave_tpu.ops.flash_attention import flash_attention

        out = flash_attention(q, k, v)
    else:
        out = dense_causal_attention(q, k, v)
    # [B, S, H/P, D] -> [B, S/P, H, D]
    return a2a(out, split_axis=1, concat_axis=2)


def ulysses_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    mesh: Mesh,
    seq_axis: str = "seq",
    local_attention: str = "dense",
) -> jnp.ndarray:
    """Causal attention with all-to-all sequence parallelism.

    Same contract as :func:`ring_attention`: q/k/v are
    [batch, seq, heads, head_dim] with seq sharded on ``seq_axis``,
    batch on the mesh's first non-seq axis and heads on the second
    (canonically "data" and "model"). ``local_attention`` selects the
    per-device kernel: "dense" or "flash" (the Pallas kernel from
    shockwave_tpu/ops/flash_attention.py).
    """
    seq_par = mesh.shape[seq_axis]
    other_axes = [a for a in mesh.axis_names if a != seq_axis]
    batch_axis = other_axes[0] if len(other_axes) > 0 else None
    head_axis = other_axes[1] if len(other_axes) > 1 else None
    heads_local = q.shape[2]
    if head_axis is not None:
        if heads_local % mesh.shape[head_axis]:
            raise ValueError(
                f"{heads_local} heads not divisible by mesh axis "
                f"{head_axis}={mesh.shape[head_axis]}"
            )
        heads_local //= mesh.shape[head_axis]
    if heads_local % seq_par != 0:
        raise ValueError(
            f"{heads_local} local heads not divisible by seq axis "
            f"{seq_axis}={seq_par}; use ring attention instead"
        )
    # The gathered per-device sequence equals the global S, so the flash
    # kernel's tiling constraint resolves here, once: anything that
    # doesn't fill its blocks runs the dense local path.
    if local_attention == "flash":
        from shockwave_tpu.ops.flash_attention import flash_tiles

        if not flash_tiles(q.shape[1]):
            local_attention = "dense"
    io_spec = P(batch_axis, seq_axis, head_axis, None)
    fn = shard_map(
        functools.partial(
            _ulysses_local,
            axis_name=seq_axis,
            local_attention=local_attention,
        ),
        mesh=mesh,
        in_specs=(io_spec, io_spec, io_spec),
        out_specs=io_spec,
        # pallas_call's out_shapes carry no vma annotation, so the flash
        # local kernel can't run under shard_map's vma checking.
        check_vma=(local_attention != "flash"),
    )
    return fn(q, k, v)
