"""Physical-cluster mode: the round loop over real workers via gRPC.

Subclasses the simulator's Scheduler for all bookkeeping (priorities,
allocation, completion merging, batch-size adaptation, Shockwave planner
hooks) and adds what only exists with real machines: worker registration,
per-round dispatch, the lease state machine (init / refresh / extension),
straggler kills, and shutdown. Reference: scheduler/scheduler.py
_schedule_with_rounds :2080-2129, _begin/_mid/_end_round :1804-2078,
lease callbacks :2942-3096, _kill_job :3098-3170.

Timing shape per round (reference: SCHEDULE_RECOMPUTE_FRACTION=0.5,
JOB_COMPLETION_BUFFER_TIME=60):
  t=0        dispatch this round's assignments (skipping gang members whose
             worker set is unchanged — their leases are extended instead)
  t=0.5R     compute NEXT round's assignment so lease-update RPCs arriving
             late in the round learn about extensions
  t=R..R+B   wait for every dispatched micro-task's Done; kill stragglers
"""

from __future__ import annotations

import logging
import os
import threading
import time
from collections import OrderedDict
from typing import Dict, Optional, Tuple

from shockwave_tpu import obs
from shockwave_tpu.analysis import sanitize
from shockwave_tpu.core.ids import JobId
from shockwave_tpu.core.scheduler import Scheduler
from shockwave_tpu.data.workload_info import steps_per_epoch
from shockwave_tpu.runtime import admission
from shockwave_tpu.runtime.lease import INFINITY
from shockwave_tpu.runtime.retry import PermanentRpcError

LOG = logging.getLogger("core.physical")


def _clock_gauges():
    """The per-worker clock-sync gauge pair — one definition so the
    heartbeat setter and the retirement remover can never drift onto
    different series."""
    return (
        obs.gauge(
            "worker_clock_offset_seconds",
            "per-worker NTP-style clock offset vs the scheduler "
            "(worker's min-RTT estimate, heartbeat-reported)",
        ),
        obs.gauge(
            "worker_clock_rtt_seconds",
            "round-trip time of the offset estimate's best sample",
        ),
    )


SCHEDULE_RECOMPUTE_FRACTION = 0.5
LEASE_UPDATE_FRACTION = 0.75
JOB_COMPLETION_BUFFER_SECONDS = 60.0
KILL_WAIT_SECONDS = 30.0


class PhysicalScheduler(Scheduler):
    def __init__(
        self,
        policy,
        port: int = 50060,
        completion_buffer_seconds: float = JOB_COMPLETION_BUFFER_SECONDS,
        heartbeat_timeout_s: Optional[float] = None,
        metrics_port: Optional[int] = None,
        ha_journal=None,
        ha_election=None,
        ha_checkpoint_rounds: Optional[int] = None,
        ha_restore_pending: bool = False,
        **kwargs,
    ):
        # The reference's fixed 1920s reset throttle assumes 360s rounds
        # (scheduler.py:100); scale it with the round length so short-round
        # deployments do not starve late arrivals of allocation updates.
        # Computed AFTER the base init so overhead-aware round auto-sizing
        # (round_overhead_fraction) is reflected in the throttle too.
        explicit_reset = "minimum_time_between_allocation_resets" in kwargs
        if not explicit_reset:
            kwargs["minimum_time_between_allocation_resets"] = 0.0
        super().__init__(policy, simulate=False, **kwargs)
        if not explicit_reset:
            self._min_reset_interval = (
                1920.0 / 360.0
            ) * self._time_per_iteration
        self._port = port
        self._completion_buffer = completion_buffer_seconds
        self._start_time = time.time()
        if obs.trace_enabled():
            # merge_traces.py alignment anchor: this process's trace
            # clock (wall-since-start, installed by the base __init__)
            # is zero at _start_time on the wall clock; the scheduler
            # IS the fleet's reference clock (offset 0).
            obs.get_tracer().set_meta(
                {
                    "role": "scheduler",
                    "clock": {
                        "wall_at_zero_s": self._start_time,
                        "offset_to_scheduler_s": 0.0,
                    },
                }
            )

        self._lock = sanitize.make_rlock(
            "core.physical.PhysicalScheduler._lock"
        )
        self._cv = sanitize.make_condition(self._lock)
        self._worker_connections: Dict[int, object] = {}
        self._worker_addrs: Dict[int, Tuple[str, int]] = {}
        self._round_id = 0
        # Legacy static-count contract (expect_jobs): still honored for
        # in-process drivers, but the streaming front door below is the
        # serving-system path — see _stream_done for the end-of-run
        # decision.
        self._num_expected_jobs: Optional[int] = None
        self._shutdown_requested = threading.Event()

        # Streaming admission front door: a bounded queue the SubmitJobs
        # RPC (and in-process submitters) feed and the round loop drains
        # at round boundaries. Timestamps ride the scheduler clock so
        # queue-latency metrics line up with every other series. With a
        # cell-decomposed planner the queue is sharded (one slice per
        # cell, coordinator-rebalanced); priority-aware drain and
        # per-tenant quotas ride env knobs (see admission.build_queue).
        self._admission = admission.build_queue(
            capacity=int(
                os.environ.get(
                    "SHOCKWAVE_ADMISSION_QUEUE_CAP",
                    admission.DEFAULT_CAPACITY,
                )
            ),
            retry_delay_s=float(
                os.environ.get(
                    "SHOCKWAVE_ADMISSION_RETRY_S",
                    max(1.0, self._time_per_iteration / 4.0),
                )
            ),
            clock=self.get_current_timestamp,
            shards=getattr(self._shockwave, "num_cells", 1) or 1,
        )

        # Per-job runtime state.
        self._dispatch_times: Dict[JobId, float] = {}
        self._round_end_time: float = 0.0
        # Jobs whose next-round worker set is identical: lease extensions.
        self._jobs_with_extended_lease: set = set()
        self._next_assignments: "OrderedDict[JobId, tuple]" = OrderedDict()
        # Gang lease agreement: job -> (max_steps, max_duration)
        # fixed by the first member to request an update
        # (reference: scheduler.py:3067-3096).
        self._max_steps_agreement: Dict[JobId, Tuple[int, float]] = {}
        # Last lease-protocol contact per job, for unresponsiveness
        # detection of extended-lease jobs (reference: scheduler.py:
        # 3196-3202,3220-3221 — an extended job that stops requesting
        # lease updates is declared unresponsive and killed).
        self._last_lease_contact: Dict[JobId, float] = {}
        # Micro-tasks dispatched this round and not yet reported done.
        self._outstanding: set = set()
        # Dispatch-time worker sets (assignments rotate before Done arrives).
        self._dispatched_worker_ids: Dict[JobId, tuple] = {}

        # Worker liveness: heartbeat timestamps under their OWN lock so
        # the (cheap, frequent) SendHeartbeat handler never queues
        # behind the round loop's long-held condition lock. Lock order
        # is strictly _cv -> _hb_lock (the reaper reads heartbeats
        # while planning; the handler takes only _hb_lock).
        self._hb_lock = sanitize.make_lock(
            "core.physical.PhysicalScheduler._hb_lock"
        )
        self._last_heartbeat: Dict[int, float] = {}
        # Workers already retired: a merely-stalled (not dead) worker
        # keeps heartbeating after its reap, and re-admitting its id to
        # the liveness map would leak an entry that can never expire
        # away (the worker is gone from every placement structure).
        self._retired_workers: set = set()
        # A worker silent past this many seconds is declared dead: its
        # outstanding micro-tasks are requeued with fault-completions,
        # capacity shrinks, and the planner replans. Registration seeds
        # the clock (registration IS the first lease) — a worker that
        # dies before its first heartbeat must still expire, or its
        # jobs stay pinned to a dead-but-registered host forever.
        # <= 0 disables (required for heartbeat-less worker agents).
        if heartbeat_timeout_s is None:
            heartbeat_timeout_s = max(15.0, 2.5 * self._time_per_iteration)
        self._heartbeat_timeout_s = float(heartbeat_timeout_s)

        # Fleet telemetry plane: periodic DumpMetrics pulls over every
        # worker agent merged under a worker label, served (with the
        # scheduler's own series) on a stdlib-HTTP Prometheus scrape
        # endpoint plus /healthz. Enabled by the metrics_port arg or
        # SHOCKWAVE_METRICS_PORT (0 = ephemeral; read the bound port
        # back from self._fleet.port). Off = None = zero overhead.
        self._fleet = None
        # worker_id -> (agent label, agent addr): fleet scrape targets
        # are per AGENT (one RPC client per address), labeled by the
        # agent's lowest worker id.
        self._fleet_agents: Dict[int, Tuple[str, Tuple[str, int]]] = {}
        if metrics_port is None:
            env_port = os.environ.get("SHOCKWAVE_METRICS_PORT")
            metrics_port = int(env_port) if env_port not in (None, "") else None
        if metrics_port is not None:
            from shockwave_tpu.obs.fleet import FleetTelemetry

            self._fleet = FleetTelemetry(
                scrape_interval_s=float(
                    os.environ.get("SHOCKWAVE_FLEET_SCRAPE_S", "5.0")
                )
            )
            self._fleet.start(http_port=int(metrics_port))

        # HA survivability plane (shockwave_tpu/ha/): when armed, every
        # state-changing control-plane event appends to the write-ahead
        # journal and the fenced epoch from the leader lease rides every
        # dispatch/kill RPC. Both None (the default) keeps the legacy
        # single-scheduler behavior at zero overhead (one attribute
        # check per hook).
        self._ha_journal = ha_journal
        self._ha_election = ha_election
        self._ha_epoch = (
            int(ha_election.epoch) if ha_election is not None else 0
        )
        self._ha_deposed = False
        self._ha_replaying = False
        # Set by the HA driver on a successor BEFORE the journal
        # restore runs: the gRPC server is live from construction, and
        # a worker re-attaching into the not-yet-restored (empty)
        # registry would be minted fresh ids that the restore then
        # clobbers. Registrations bounce (transient error, the agent
        # retries next beat) until restore_from_journal clears this.
        self._ha_restore_pending = bool(ha_restore_pending)
        self._ha_replay_admit_debt: Dict[str, int] = {}
        # Token of the admission-queue entry currently being drained
        # into add_job (round loop only, under _cv) so the admit journal
        # entry can pop the matching pending entry at replay.
        self._ha_drain_token: Optional[str] = None
        # scheduler_crash fault-event ids already consumed by a previous
        # incarnation (journaled before its SIGKILL): the successor must
        # not re-apply them to itself.
        self._ha_consumed_sched_faults: set = set()
        if ha_checkpoint_rounds is None:
            ha_checkpoint_rounds = int(
                os.environ.get("SHOCKWAVE_HA_CHECKPOINT_ROUNDS", "1")
            )
        self._ha_checkpoint_rounds = max(1, int(ha_checkpoint_rounds))
        if ha_election is not None:
            obs.gauge(
                "ha_leader_epoch", "this process's current fenced epoch"
            ).set(float(self._ha_epoch))
            ha_election.start_renewal(on_lost=self._ha_fenced)

        from shockwave_tpu.runtime.rpc import scheduler_server

        self._server = scheduler_server.serve(
            port,
            {
                "register_worker": self._register_worker_rpc,
                "done": self._done_rpc,
                "heartbeat": self._heartbeat_rpc,
                # Coalesced metrics push: a heartbeat carrying the
                # agent's rendered registry lands here and pre-empts
                # the fleet plane's next DumpMetrics poll for it.
                "worker_metrics": self._worker_metrics_rpc,
                # Binary successor: a compressed sketch-snapshot frame
                # the fleet MERGES into exact fleet-wide quantiles
                # instead of concatenating text.
                "worker_metrics_frame": self._worker_metrics_frame_rpc,
                "init_job": self._init_job_rpc,
                "update_lease": self._update_lease_rpc,
                "submit_jobs": self._submit_jobs_rpc,
                # Fencing epoch echoed on heartbeat acks so workers
                # track leadership changes passively.
                "sched_epoch": lambda: self._ha_epoch,
                # /metrics-style text dump: any client (or grpcurl-style
                # tooling speaking the hand-rolled wire contract) can
                # scrape the scheduler's live registry.
                "dump_metrics": obs.render_prometheus,
                # Market explainability: one job's decision narrative,
                # derived from the live decision log (see obs/explain).
                "explain_job": self._explain_job_rpc,
            },
        )

    # -- wall-clock timestamps (simulator uses virtual time) ------------
    def get_current_timestamp(self, in_seconds: bool = False) -> float:
        return time.time() - self._start_time

    # -- HA survivability hooks (shockwave_tpu/ha/) ---------------------
    def _ha_log(self, kind: str, payload: dict) -> None:
        """Append one control-plane delta to the write-ahead journal.
        No-op when HA is off, and during journal REPLAY — a replayed
        add_job/done re-entering the journal would duplicate the tail
        for the next failover (replay ends with a compacting
        checkpoint instead)."""
        if self._ha_journal is None or self._ha_replaying:
            return
        # The journal serializes appends under its OWN leaf lock (LSN
        # mint + O_APPEND write); callers from the round loop and RPC
        # handler threads need no shared lock here, and `_ha_journal`
        # itself is never rebound after construction.
        # shockwave-lint: disable=shared-state-race
        self._ha_journal.append(kind, payload, epoch=self._ha_epoch)

    def _ha_fenced(self) -> None:
        """Deposed: a newer epoch owns the lease. Stop dispatching
        immediately and shut down WITHOUT touching the workers — they
        belong to the successor now, and our own dispatch/kill RPCs are
        already bounced by the workers' epoch gates."""
        LOG.error(
            "deposed: leader lease lost to a newer epoch; fencing this "
            "scheduler (epoch %d)", self._ha_epoch,
        )
        obs.counter(
            "ha_deposed_total",
            "leadership terms this process lost to a newer epoch",
        ).inc()
        self._ha_deposed = True
        self.shutdown()

    def _ha_checkpoint(self) -> None:
        """Write one compacted journal checkpoint of the full
        control-plane state. Capture + LSN reservation + encode run
        atomically under the lock (reentrant — round-loop callers
        already hold it): the reservation makes every lock-protected
        WAL entry sort strictly before or after the checkpoint's
        contents, and the encode IS the deep snapshot — ha_state_dict
        returns references to live structures, so encoding them after
        releasing _cv would tear (or crash on) concurrent handler
        mutations. Only the JSON dump + disk write run unlocked."""
        if self._ha_journal is None:
            return
        from shockwave_tpu.obs.recorder import encode as _encode

        with self._cv:
            seq, lsn = self._ha_journal.begin_checkpoint()
            encoded = _encode(self.ha_state_dict())
        self._ha_journal.commit_checkpoint(
            seq, lsn, encoded, epoch=self._ha_epoch
        )

    def add_job(self, job, timestamp=None):
        """In-process admission entry. The gRPC server is live from
        construction, so a worker registration or a Done report can
        interleave with a driver thread's add_job even before the
        round loop starts — the base (simulator) implementation
        mutates allocation state and must run under the lock here."""
        from shockwave_tpu.ha import codec as ha_codec

        with self._cv:
            job_id = super().add_job(job, timestamp=timestamp)
            # Payload built only when armed: the zero-overhead contract
            # for legacy runs is one attribute check, not a vars() copy
            # per admission.
            if self._ha_journal is not None and not self._ha_replaying:
                self._ha_log(
                    "admit",
                    {
                        "job_id": job_id.integer,
                        "job": ha_codec.job_state(job),
                        "timestamp": self._per_job_start_timestamps[
                            job_id
                        ],
                        "token": self._ha_drain_token,
                    },
                )
            self._cv.notify_all()
            return job_id

    # -- RPC callbacks --------------------------------------------------
    def _register_worker_rpc(
        self,
        worker_type,
        num_accelerators,
        ip_addr,
        port,
        prev_worker_ids=None,
        outstanding_job_ids=None,
    ):
        """(reference: scheduler.py:2854-2940). With
        ``prev_worker_ids`` (HA re-attach after a scheduler death), the
        agent's previous identity is re-adopted when the restored
        registry still carries it: connections are rebuilt onto the old
        worker ids — no capacity is minted — and restored in-flight
        micro-tasks the agent no longer carries (lost in the crash
        window) are reconciled as fault completions."""
        from shockwave_tpu.runtime.rpc.scheduler_client import SchedulerRpcClient

        if self._ha_restore_pending:
            # Successor still replaying the journal: admitting this
            # agent against the empty pre-restore registry would mint
            # fresh ids the restore clobbers. Transient by design —
            # the agent's outage loop retries next beat.
            raise RuntimeError(
                "scheduler is restoring from the HA journal; "
                "re-register after failover completes"
            )
        with self._cv:
            # Idempotency gate: registration is retried with backoff, so
            # an agent whose RegisterWorker response was lost re-sends
            # it; handing out a second set of worker ids would double
            # the agent's capacity on paper. A known address whose
            # connections are GONE (journal-restored registry, workers
            # not yet re-attached) falls through to the re-attach path.
            existing = sorted(
                wid
                for wid, addr in self._worker_addrs.items()
                if addr == (ip_addr, port)
            )
            if existing and all(
                wid in self._worker_connections for wid in existing
            ):
                return existing, self._time_per_iteration, self._ha_epoch, False
            prev = [int(w) for w in (prev_worker_ids or [])] or existing
            known = prev and all(
                wid in self._worker_id_to_worker_type
                and wid not in self._retired_workers
                for wid in prev
            )
            if known:
                worker_ids = self._reattach_worker_locked(
                    prev, ip_addr, port, outstanding_job_ids or []
                )
                self._cv.notify_all()
                return (
                    worker_ids, self._time_per_iteration,
                    self._ha_epoch, True,
                )
            worker_ids = self.register_worker(
                worker_type, num_gpus=num_accelerators
            )
            client = SchedulerRpcClient(ip_addr, port)
            for worker_id in worker_ids:
                self._worker_connections[worker_id] = client
                self._worker_addrs[worker_id] = (ip_addr, port)
            self._add_fleet_target(worker_ids, client, ip_addr, port)
            # Registration starts the liveness lease; see
            # _heartbeat_rpc / _dead_workers. Lock order _cv -> _hb_lock.
            now = time.monotonic()
            with self._hb_lock:
                for worker_id in worker_ids:
                    self._last_heartbeat[worker_id] = now
            self._ha_log(
                "register",
                {
                    "worker_ids": list(worker_ids),
                    "worker_type": str(worker_type),
                    "num_accelerators": int(num_accelerators),
                    "ip_addr": str(ip_addr),
                    "port": int(port),
                },
            )
            self._cv.notify_all()
        return worker_ids, self._time_per_iteration, self._ha_epoch, False

    def _add_fleet_target(self, worker_ids, client, ip_addr, port) -> None:
        """Caller holds the lock (_cv). One scrape target per agent
        process, labeled by its lowest worker id (the label the merged
        fleet series carry as worker="<id>")."""
        if self._fleet is None:
            return
        label = str(min(worker_ids))
        for worker_id in worker_ids:
            self._fleet_agents[worker_id] = (label, (ip_addr, port))
        self._fleet.add_target(label, client.dump_worker_metrics)

    def _reattach_worker_locked(
        self, worker_ids, ip_addr, port, reported_job_ids
    ) -> list:
        """Caller holds the lock (_cv). Re-adopt a surviving agent
        after a failover: rebuild its connections onto its previous
        worker ids, seed its liveness lease, and reconcile the restored
        outstanding set against the micro-task state it actually still
        carries — anything the agent no longer has (its process died in
        the crash window, or its Done was lost with the old leader's
        ack) becomes a fault completion, so in-flight work is neither
        lost nor double-charged."""
        from shockwave_tpu.runtime.rpc.scheduler_client import SchedulerRpcClient

        client = SchedulerRpcClient(ip_addr, port)
        for worker_id in worker_ids:
            self._worker_connections[worker_id] = client
            self._worker_addrs[worker_id] = (ip_addr, port)
        self._add_fleet_target(worker_ids, client, ip_addr, port)
        now_mono = time.monotonic()
        with self._hb_lock:
            for worker_id in worker_ids:
                self._last_heartbeat[worker_id] = now_mono
        reported = {int(j) for j in reported_job_ids}
        reconciled = self._reconcile_reattach_locked(worker_ids, reported)
        obs.counter(
            "ha_worker_reattach_total",
            "agents re-adopted onto their previous worker ids after a "
            "failover",
        ).inc()
        if reconciled:
            LOG.warning(
                "re-attach of workers %s reconciled lost in-flight "
                "micro-tasks %s as fault completions",
                worker_ids, reconciled,
            )
        self._ha_log(
            "reattach",
            {
                "worker_ids": list(worker_ids),
                "ip_addr": str(ip_addr),
                "port": int(port),
                "reported_job_ids": sorted(reported),
                "reconciled": reconciled,
            },
        )
        return list(worker_ids)

    def _reconcile_reattach_locked(self, worker_ids, reported) -> list:
        """Caller holds the lock (_cv). The ONE reconcile pass shared
        by the live re-attach handler and its WAL replay (they must
        mutate identically or a successor's replayed state diverges
        from the state the dead leader actually had): every restored
        in-flight micro-task on ``worker_ids`` that the agent no
        longer carries (not in ``reported``) died in the crash window
        — fault-complete it so the job requeues without a
        failed-attempt charge. Returns the reconciled job keys."""
        reconciled = []
        for key, wid in list(self._outstanding):
            if wid not in worker_ids:
                continue
            if any(j in reported for j in key.as_tuple()):
                continue  # still running (or buffered) on the agent
            self._outstanding.discard((key, wid))
            self._jobs_with_extended_lease.discard(key)
            zeros = [0] * len(key.singletons())
            self._done_callback(
                key, wid, zeros, [0.0] * len(key.singletons()), fault=True
            )
            reconciled.append(str(key))
        return reconciled

    def _heartbeat_rpc(
        self, worker_id, est_offset_s: float = 0.0, est_rtt_s: float = 0.0
    ) -> None:
        """Liveness ping from a worker agent; deliberately does NOT take
        the round loop's condition lock (see _hb_lock). Heartbeats also
        carry the worker's best NTP-style clock-offset estimate
        (scheduler_clock - worker_clock; est_rtt_s > 0 marks it valid),
        exported as per-worker gauges for the clock_skew watchdog rule
        and merge_traces.py."""
        with self._hb_lock:
            worker_id = int(worker_id)
            if worker_id in self._retired_workers:
                return
            self._last_heartbeat[worker_id] = time.monotonic()
            if est_rtt_s > 0:
                # Inside _hb_lock so a concurrent retirement (which
                # marks retired THEN removes these series, also under
                # _hb_lock) cannot interleave with the set and leave a
                # frozen gauge behind for a dead worker.
                offset_gauge, rtt_gauge = _clock_gauges()
                offset_gauge.set(est_offset_s, worker=str(worker_id))
                rtt_gauge.set(est_rtt_s, worker=str(worker_id))

    def _worker_metrics_rpc(self, worker_id, text: str) -> None:
        """Coalesced metrics push riding a heartbeat: store the agent's
        rendered registry under the SAME fleet label the poll path
        uses (min worker id of the agent), so the next poll tick skips
        that target — one RPC where the wire carried beat + dump."""
        with self._cv:
            fleet = self._fleet
            entry = self._fleet_agents.get(int(worker_id))
        if fleet is None or entry is None:
            return
        fleet.accept_push(entry[0], text)

    def _worker_metrics_frame_rpc(self, worker_id, frame: bytes) -> None:
        """Binary sketch-frame push riding a heartbeat. Same label
        discipline as the text path; a frame from a worker whose agent
        has already been retired resolves to no fleet entry and is
        dropped here — a dead worker cannot re-plant its series."""
        with self._cv:
            fleet = self._fleet
            entry = self._fleet_agents.get(int(worker_id))
        if fleet is None or entry is None:
            return
        fleet.accept_frame(entry[0], frame)

    def _explain_job_rpc(self, job_id):
        """ExplainJob handler: the job's decision narrative, derived
        from the live decision log via the SAME builder the offline
        scripts/analysis/explain.py uses — so the live answer equals
        the offline replay-derived one field for field. Returns None
        (-> found=false on the wire) when the decision log is off."""
        rpc_start = time.perf_counter()
        try:
            recorder = obs.get_recorder()
            if not recorder.enabled or recorder.path is None:
                return None
            from shockwave_tpu.obs.explain import narrative_from_log

            # Flush so the log on disk covers every committed round up
            # to now; the builder tolerates a mid-write truncated tail.
            recorder.flush()
            return narrative_from_log(recorder.path, job_id=str(job_id))
        finally:
            self._observe_rpc("ExplainJob", rpc_start)

    def _submit_jobs_rpc(self, token, specs, close):
        """Streaming-admission handler: validate the batch, offer it to
        the bounded queue (idempotent on the token; RETRY_AFTER under
        backpressure), and wake an idle round loop. Deliberately does
        NOT take the round loop's condition lock for the queue work —
        admission must stay cheap under a submission storm; only the
        wakeup notify touches _cv."""
        rpc_start = time.perf_counter()
        try:
            return self.submit_batch(token, specs, close)
        finally:
            self._observe_rpc("SubmitJobs", rpc_start)

    def submit_batch(self, token, specs, close):
        """The one admission entry behind every front-door socket (the
        scheduler's own SubmitJobs handler and each HA admission-shard
        slice): validate, offer to the bounded queue, journal the
        accepted batch, wake the round loop."""
        from shockwave_tpu.ha import codec as ha_codec

        # A malformed spec raises ValueError here, BEFORE anything
        # is queued — the whole batch is rejected as INVALID so a
        # token never resolves to a partial admission. Validation
        # must be at least as strict as what add_job will demand at
        # drain time: a wire-valid batch that ACCEPTED and then
        # blew up the round loop at the round boundary would kill
        # the whole cluster for one bad submitter.
        jobs = [admission.job_from_spec_dict(s) for s in specs]
        for job in jobs:
            self._validate_job_runnable(job)
        status, retry_after_s, admitted = self._admission.submit(
            token, jobs, close=close
        )
        if status == admission.STATUS_ACCEPTED:
            # WAL: every ACCEPTED batch journals (a ledger-deduped
            # retransmit included — replay is idempotent on the token,
            # so the duplicate entry is a no-op there, and telling the
            # two apart here would need a wider queue return contract).
            # Payload built only when armed.
            if self._ha_journal is not None and not self._ha_replaying:
                self._ha_log(
                    "submit",
                    {
                        "token": str(token),
                        "jobs": [ha_codec.job_state(j) for j in jobs],
                        "close": bool(close),
                    },
                )
            with self._cv:
                self._cv.notify_all()
        return status, retry_after_s, admitted, self._admission.depth()

    def _validate_job_runnable(self, job) -> None:
        """Reject (ValueError -> INVALID on the wire) any job add_job
        would choke on at drain time: the job type must parse AND the
        throughput oracle must know the (model, batch size, gang) on
        this cluster's worker types — an oracle miss inside the round
        loop would take down scheduling for every running job."""
        from shockwave_tpu.data.profiles import synthesize_profile

        if self._oracle_throughputs is None:
            return
        worker_type = self._worker_types[0] if self._worker_types else "v100"
        try:
            synthesize_profile(job, self._oracle_throughputs, worker_type)
        except KeyError as e:
            raise ValueError(
                f"unrunnable job {job.job_type!r} x{job.scale_factor}: "
                f"{e.args[0] if e.args else e}"
            ) from None

    def _drain_admission_queue(self) -> int:
        """Caller holds the lock (_cv). Admit every queued submission
        into the scheduler (batched admission: one replan covers the
        whole drain). Returns the number of jobs admitted. A job that
        add_job rejects despite the front-door validation is dropped
        LOUDLY (logged, counted, recorded) — one bad job must never
        kill the round loop under every running job."""
        drained = self._admission.drain(now=self.get_current_timestamp())
        recorder = obs.get_recorder()
        admitted = 0
        for token, job, enqueued_s in drained:
            try:
                # Re-validate before add_job mutates anything: add_job
                # has no rollback, so a failure MID-insert would leave
                # a half-registered job; the validation reproduces the
                # oracle check cheaply and raises before any mutation.
                self._validate_job_runnable(job)
                # The admit journal entry carries the token so replay
                # can pop the matching restored pending entry (round
                # loop only, under _cv — no concurrent drains). The
                # EMPTY string is a real front-door token (dedup
                # disabled but the queue stores it) and must stay
                # distinct from None (in-process add_job, nothing
                # pending to pop at replay).
                self._ha_drain_token = token
                try:
                    job_id = self.add_job(job, timestamp=enqueued_s)
                finally:
                    self._ha_drain_token = None
            except Exception:
                LOG.error(
                    "admitted job %r (token %s) rejected at drain; "
                    "dropping it rather than crashing the round loop",
                    job.job_type, token, exc_info=True,
                )
                obs.counter(
                    "admission_drain_failures_total",
                    "queued jobs add_job rejected at the round "
                    "boundary (dropped, not admitted)",
                ).inc()
                if recorder.enabled:
                    recorder.record_admission(
                        {
                            "kind": "drain_failed",
                            "token": token,
                            "job_type": job.job_type,
                            "round": self._round_id,
                        }
                    )
                continue
            admitted += 1
            if recorder.enabled:
                recorder.record_admission(
                    {
                        "kind": "admitted",
                        "token": token,
                        "job_id": job_id.integer,
                        "round": self._round_id,
                        "time": self.get_current_timestamp(),
                    }
                )
        return admitted

    def expect_stream(self) -> None:
        """Declare that a streaming submitter WILL connect: the round
        loop idles on an empty job table until the stream closes,
        instead of exiting before the first SubmitJobs RPC lands (the
        startup race of any out-of-process submitter)."""
        self._admission.open()

    def close_submissions(self) -> None:
        """End-of-stream signal for in-process drivers (the RPC path
        sends close on SubmitJobs): after the queue drains and every
        admitted job completes, the round loop exits instead of idling
        for more arrivals."""
        self._admission.close()
        self._ha_log("close", {})
        with self._cv:
            self._cv.notify_all()

    def _stream_done(self) -> bool:
        """Caller holds the lock (_cv). Whether an empty job table means
        the run is over. Pending submissions always keep the loop
        alive; an open stream (any front-door submit seen, no close
        yet) idles; the legacy expected-count contract is honored for
        drivers that still use it; with neither signal, empty means
        done (the seed behavior)."""
        if self._admission.depth() > 0:
            return False
        if self._admission.closed:
            return True
        expected = self._num_expected_jobs
        if expected is not None:
            return self._num_jobs_in_trace >= expected
        return not self._admission.opened

    # -- worker death ---------------------------------------------------
    def _dead_workers(self) -> list:
        """Workers whose heartbeats expired (the lease-expiry check).
        Caller holds the lock (_cv); takes _hb_lock inside — lock order
        _cv -> _hb_lock."""
        if self._heartbeat_timeout_s <= 0:
            return []
        now = time.monotonic()
        with self._hb_lock:
            return [
                wid
                for wid, last in self._last_heartbeat.items()
                if now - last > self._heartbeat_timeout_s
                and wid in self._worker_id_to_worker_type
            ]

    def _reap_dead_workers(self) -> list:
        """Caller holds the lock (_cv). Detect heartbeat-expired workers
        and recover: requeue their outstanding micro-tasks as
        fault-completions (no failed-attempt charged to the job),
        unregister them so capacity shrinks, and flag the planner to
        replan. Returns the reaped worker ids."""
        dead = self._dead_workers()
        for worker_id in dead:
            self._retire_worker(worker_id, kind="heartbeat_expired")
        return dead

    def _retire_worker(
        self, worker_id: int, kind: str, fault_id=None
    ) -> list:
        """Caller holds the lock (_cv). The single recovery path for a
        worker that is gone — heartbeat expiry, injected crash, or spot
        reclamation: requeue its outstanding micro-tasks as
        fault-completions, unregister it, stamp the fault+recovery pair
        into the flight recorder, and force a replan onto the surviving
        fleet. Returns the requeued job keys."""
        recorder = obs.get_recorder()
        now = self.get_current_timestamp()
        requeued = []
        for key, wid in list(self._outstanding):
            if wid != worker_id:
                continue
            self._outstanding.discard((key, wid))
            self._jobs_with_extended_lease.discard(key)
            zeros = [0] * len(key.singletons())
            self._done_callback(
                key, wid, zeros, [0.0] * len(key.singletons()),
                fault=True,
            )
            requeued.append(str(key))
        LOG.warning(
            "worker %s retired (%s); requeued %s, capacity %d -> %d",
            worker_id, kind, requeued or "nothing",
            len(self._worker_ids), len(self._worker_ids) - 1,
        )
        self.remove_worker(worker_id)
        obs.counter(
            "scheduler_worker_deaths_total",
            "workers lost to crash or capacity reclamation",
        ).inc(kind=kind)
        obs.instant(
            "worker_death", cat="fault", tid="faults",
            args={"worker_id": worker_id, "kind": kind,
                  "requeued": requeued},
        )
        if recorder.enabled:
            record = {
                "kind": kind,
                "worker_id": worker_id,
                "round": self._round_id,
                "time": now,
                "requeued": requeued,
            }
            if fault_id is not None:
                record["fault_id"] = fault_id
            recorder.record_fault(record)
            recorder.record_recovery(
                {**record, "how": "requeued_and_replanned"}
            )
        if self._shockwave is not None:
            self._shockwave.set_recompute_flag()
        self._ha_log(
            "retire", {"worker_id": int(worker_id), "kind": str(kind)}
        )
        self._cv.notify_all()
        return requeued

    def _apply_physical_fault_events(self, injector) -> None:
        """Caller holds the lock (_cv). Injected worker churn against
        the LIVE cluster: a worker_crash / capacity_reclaim event
        force-retires real registered workers (best-effort Reset RPC so
        their training processes die too, mirroring a spot preemption
        notice — fired on a side thread: a blocking RPC under the
        round loop's condition lock would stall every lease renewal
        behind a black-holed host); worker_add has no physical analog
        (machines cannot be conjured) and is skipped loudly."""
        from shockwave_tpu.runtime import faults as faults_mod

        for event in injector.due_cluster_events(
            self.get_current_timestamp()
        ):
            obs.counter(
                "fault_injected_total",
                "fault events delivered by the injector",
            ).inc(kind=event.kind)
            if event.kind in faults_mod.SCHEDULER_KINDS:
                self._apply_scheduler_fault(injector, event)
                continue
            if event.kind == "worker_add":
                LOG.warning(
                    "fault event %d (worker_add) skipped: physical mode "
                    "cannot conjure machines", event.event_id,
                )
                injector.mark_applied(event, skipped="no_physical_analog")
                injector.mark_recovered(
                    event.event_id, how="skipped_no_physical_analog"
                )
                continue
            victims = faults_mod.select_victims(
                injector.plan, event, self._worker_id_to_worker_type
            )
            reset_clients = [
                self._worker_connections[worker_id]
                for worker_id in victims
                if worker_id in self._worker_connections
            ]
            requeued = []
            for worker_id in victims:
                requeued.extend(
                    self._retire_worker(
                        worker_id, kind=event.kind,
                        fault_id=event.event_id,
                    )
                )
            if reset_clients:
                threading.Thread(
                    target=self._reset_reclaimed_workers,
                    args=(reset_clients,),
                    daemon=True,
                ).start()
            injector.mark_applied(
                event, workers=victims, requeued=requeued
            )
            injector.mark_recovered(
                event.event_id, how="requeued_and_replanned",
                workers=victims,
            )

    def _apply_scheduler_fault(self, injector, event) -> None:
        """Caller holds the lock (_cv). The kill-the-brain drill:
        ``scheduler_crash`` SIGKILLs THIS process at its scheduled time
        — no cleanup, no flushes beyond what is already durable (the
        WAL appends are) — and the hot standby (or a cold restart)
        takes over through the journal. ``scheduler_restart`` has no
        in-process action in physical mode: the successor IS the
        restart. A successor whose journal shows the crash was already
        taken (``sched_fault`` marker) records the recovery instead of
        killing itself."""
        import signal as _signal

        recorder = obs.get_recorder()
        if (
            event.kind == "scheduler_restart"
            or event.event_id in self._ha_consumed_sched_faults
        ):
            how = (
                "successor_resumed"
                if event.kind == "scheduler_crash"
                else "standby_is_the_restart"
            )
            injector.mark_applied(event, skipped=how)
            injector.mark_recovered(event.event_id, how=how)
            if recorder.enabled and event.kind == "scheduler_crash":
                recorder.record_recovery(
                    {
                        "fault_id": event.event_id,
                        "kind": event.kind,
                        "round": self._round_id,
                        "time": self.get_current_timestamp(),
                        "how": how,
                        "epoch": self._ha_epoch,
                    }
                )
            return
        LOG.error(
            "fault event %d: scheduler_crash — SIGKILLing the leader "
            "(epoch %d) now", event.event_id, self._ha_epoch,
        )
        injector.mark_applied(event, epoch=self._ha_epoch)
        if recorder.enabled:
            recorder.record_fault(
                {
                    "fault_id": event.event_id,
                    "kind": event.kind,
                    "round": self._round_id,
                    "time": self.get_current_timestamp(),
                    "epoch": self._ha_epoch,
                }
            )
            recorder.flush()
        if self._ha_journal is not None:
            # Durable marker: the successor must not re-apply this
            # event to itself.
            self._ha_journal.append(
                "sched_fault", {"event_id": event.event_id},
                epoch=self._ha_epoch,
            )
        os.kill(os.getpid(), _signal.SIGKILL)

    def remove_worker(self, worker_id: int) -> None:
        """Base removal plus the physical-only maps (connections,
        addresses, heartbeats, the staged next-round plan)."""
        super().remove_worker(worker_id)
        self._worker_connections.pop(worker_id, None)
        self._worker_addrs.pop(worker_id, None)
        agent = self._fleet_agents.pop(worker_id, None)
        if agent is not None and self._fleet is not None:
            label = agent[0]
            if not any(
                lbl == label for lbl, _ in self._fleet_agents.values()
            ):
                # Last worker of the agent gone: stop scraping it.
                self._fleet.remove_target(label)
        with self._hb_lock:
            self._last_heartbeat.pop(worker_id, None)
            self._retired_workers.add(worker_id)
            # Its clock gauges go with it, removed under the SAME lock
            # the heartbeat setter holds: a retired worker must not
            # serve a frozen offset to /metrics and the clock_skew
            # rule forever, and a racing stale heartbeat must not
            # re-create the series after this removal.
            offset_gauge, rtt_gauge = _clock_gauges()
            offset_gauge.remove(worker=str(worker_id))
            rtt_gauge.remove(worker=str(worker_id))
            # Sweep every remaining worker-labeled series — counters,
            # histograms (sketch included), and exemplar details — so a
            # retired worker serves nothing frozen from any family.
            obs.remove_series(worker=str(worker_id))
        self._next_assignments = OrderedDict(
            (key, ids)
            for key, ids in self._next_assignments.items()
            if worker_id not in ids
        )

    def _observe_rpc(self, method: str, start: float) -> None:
        obs.histogram(
            "rpc_handler_seconds",
            "scheduler-side RPC handler latency (lock wait included)",
        ).observe(time.perf_counter() - start, method=method)

    def _done_rpc(
        self, worker_id, job_ids, num_steps, execution_times, logs,
        trace_contexts=None,
    ):
        """(reference: scheduler_server.py:62-95 -> _done_callback).
        ``trace_contexts`` (parallel to ``job_ids``) carries each
        micro-task's worker-side run-span context; the completion
        handling joins the job's causal chain as its child."""
        rpc_start = time.perf_counter()
        if obs.trace_enabled() and trace_contexts:
            from shockwave_tpu.obs import propagate

            for job_int, wire in zip(job_ids, trace_contexts):
                run_ctx = propagate.from_wire(wire)
                if run_ctx is None:
                    continue
                obs.instant(
                    "done_report", cat="rpc", tid="jobs",
                    args={"job_id": int(job_int),
                          "worker_id": int(worker_id),
                          "trace_id": run_ctx.trace_id,
                          "parent_span_id": run_ctx.span_id},
                )
        with self._cv:
            if len(job_ids) == 1:
                key = JobId(job_ids[0])
                steps_list = [num_steps[0]]
                times_list = [execution_times[0]]
            else:
                key = JobId(job_ids[0], job_ids[1])
                steps_list = list(num_steps)
                times_list = list(execution_times)
            # Idempotency gate: clients retry Done with backoff, so a
            # report whose response was lost can arrive twice; and a
            # worker reaped/killed while its report was in flight has
            # already had a completion synthesized. Every legitimate
            # first report has an outstanding entry (dispatch adds it);
            # anything else would double-credit steps or crash on a
            # retired worker's ids.
            if (key, worker_id) not in self._outstanding:
                obs.counter(
                    "scheduler_duplicate_done_total",
                    "Done reports dropped as retransmits or "
                    "already-reconciled micro-tasks",
                ).inc()
                self._observe_rpc("Done", rpc_start)
                return
            # WAL: the progress credit must survive a crash between
            # this report and the next checkpoint — a successor replays
            # it through the same _done_callback path. (Logged after
            # the idempotency gate, before the mutation: a crash in
            # between just means the worker's retransmit re-applies.
            # Payload built only when armed — legacy Done handling
            # pays one attribute check.)
            if self._ha_journal is not None and not self._ha_replaying:
                self._ha_log(
                    "done",
                    {
                        "job_ids": list(key.as_tuple()),
                        "worker_id": int(worker_id),
                        "steps": [int(s) for s in steps_list],
                        "times": [float(t) for t in times_list],
                    },
                )
            now = self.get_current_timestamp()
            for single, log_text in zip(key.singletons(), logs):
                if single in self._job_timelines:
                    self._job_timelines[single][0].append(log_text)
                if single in self._jobs:
                    self._per_job_latest_timestamps[single] = now
            self._outstanding.discard((key, worker_id))
            # The process exited, so any granted extension is moot: the job
            # must be re-dispatched if scheduled again.
            if not any(
                (key, wid) in self._outstanding
                for wid in self._dispatched_worker_ids.get(key, ())
            ):
                self._jobs_with_extended_lease.discard(key)
            self._done_callback(key, worker_id, steps_list, times_list)
            self._cv.notify_all()
        self._observe_rpc("Done", rpc_start)

    def _init_job_rpc(self, job_id):
        """First lease of a micro-task: run until the round ends
        (reference: scheduler.py:2942-3029)."""
        rpc_start = time.perf_counter()
        with self._cv:
            key = JobId(int(job_id))
            now = self.get_current_timestamp()
            self._dispatch_times.setdefault(key, now)
            self._last_lease_contact[key] = now
            remaining = max(self._round_end_time - now, 1.0)
            obs.instant(
                "init_job", cat="lease", tid="leases",
                args={"job_id": str(key)},
            )
            self._observe_rpc("InitJob", rpc_start)
            return INFINITY, remaining, 0.0

    def _update_lease_rpc(
        self, job_id, worker_id, steps, duration, max_steps, max_duration
    ):
        """(reference: scheduler.py:3031-3096)"""
        rpc_start = time.perf_counter()
        try:
            return self._update_lease_locked(
                job_id, worker_id, steps, duration, max_steps, max_duration
            )
        finally:
            self._observe_rpc("UpdateLease", rpc_start)

    def _update_lease_locked(
        self, job_id, worker_id, steps, duration, max_steps, max_duration
    ):
        with self._cv:
            key = JobId(int(job_id))
            self._last_lease_contact[key] = self.get_current_timestamp()
            if key in self._jobs_with_extended_lease:
                # The job keeps the same workers next round: extend through
                # the next round's end (reference: scheduler.py:1868-1891).
                extra = self._time_per_iteration
                obs.instant(
                    "lease_extended", cat="lease", tid="leases",
                    args={"job_id": str(key), "extra_s": extra},
                )
                return max_steps or INFINITY, max_duration, extra
            if steps == 0 or duration < LEASE_UPDATE_FRACTION * max_duration:
                return max_steps or INFINITY, max_duration, 0.0
            # Convert the remaining time budget into a step bound so all
            # gang members stop on the same step: first updater computes,
            # the rest adopt (reference: scheduler.py:3067-3096).
            if key not in self._max_steps_agreement:
                throughput = steps / max(duration, 1e-9)
                agreed_steps = max(
                    int(steps + throughput * max(max_duration - duration, 0.0)),
                    int(steps) + 1,
                )
                self._max_steps_agreement[key] = (agreed_steps, max_duration)
            agreed_steps, agreed_duration = self._max_steps_agreement[key]
            return agreed_steps, agreed_duration, 0.0

    # -- dispatch -------------------------------------------------------
    def _job_description(self, job, num_steps, rank, scale_factor, lead_addr):
        command = job.command
        if scale_factor > 1:
            # Gang rendezvous args, appended the way the reference appends
            # DDP args (reference: scheduler.py:1943-1950); JAX workloads
            # map them onto jax.distributed.initialize.
            command = (
                f"{command} --distributed_addr {lead_addr}"
                f" --num_workers {scale_factor} --worker_rank {rank}"
            )
        return {
            "job_id": job.job_id,
            "job_type": job.job_type,
            "command": command,
            "working_directory": job.working_directory,
            "needs_data_dir": job.needs_data_dir,
            "num_steps_arg": job.num_steps_arg,
            "num_steps": num_steps,
            "has_duration": job.duration is not None,
            "duration": job.duration or 0,
        }

    def _dispatch(self, key: JobId, worker_ids) -> None:
        """Send RunJob for every worker of a (possibly packed) assignment."""
        lead_ip, lead_port = self._worker_addrs[worker_ids[0]]
        # The gang coordinator port must differ across a job's attempts:
        # a relaunch that reuses the previous attempt's port can meet
        # the stale coordination service ("connected with a different
        # incarnation") and fail rendezvous forever after one bad round.
        lead_addr = (
            f"{lead_ip}:"
            f"{10000 + ((key.as_tuple()[0] * 131 + self._round_id) % 40000)}"
        )
        scale_factor = len(worker_ids)
        self._dispatch_times[key] = self.get_current_timestamp()
        self._dispatched_worker_ids[key] = tuple(worker_ids)
        for single in key.singletons():
            # Progress accounting in _done_callback only credits running
            # jobs (reference marks them at dispatch, scheduler.py:1935).
            self._running_jobs.add(single)
            self._per_job_latest_timestamps[single] = self.get_current_timestamp()
        # WAL: a successor must know these micro-tasks are in flight —
        # without the entry, a crash after dispatch and before the next
        # checkpoint would leave the restored outstanding set empty and
        # the workers' (buffered) Done reports would be dropped as
        # duplicates, losing the round's progress. (Payload built only
        # when armed.)
        if self._ha_journal is not None and not self._ha_replaying:
            self._ha_log(
                "dispatch",
                {
                    "job_ids": list(key.as_tuple()),
                    "worker_ids": [int(w) for w in worker_ids],
                    "round": self._round_id,
                },
            )
        # Causal chain: one dispatch span per (possibly packed) key as a
        # child of each member job's root; the RunJob descriptions carry
        # the dispatch context so the worker's run spans hang under it.
        dispatch_ctx = {}
        for single in key.singletons():
            root = self._job_trace_ctx.get(single)
            if root is not None:
                dispatch_ctx[single] = root.child()
        span_args = {"job_id": str(key), "workers": scale_factor,
                     "round": self._round_id}
        first_ctx = (
            next(iter(dispatch_ctx.values())) if dispatch_ctx else None
        )
        if first_ctx is not None:
            span_args.update(first_ctx.args())
        dispatch_start = time.perf_counter()
        with obs.span(
            "dispatch", cat="rpc", tid="dispatch", args=span_args,
        ):
            # A packed pair has one dispatch span but one context per
            # member: the span is stamped with the first member's, so
            # the other members' contexts (whose span ids the workers
            # will parent their run spans to) must be emitted as their
            # own causal nodes or those chains dangle in the merge.
            for single, ctx in dispatch_ctx.items():
                if ctx is first_ctx:
                    continue
                obs.instant(
                    "dispatch_member", cat="rpc", tid="dispatch",
                    args={"job_id": str(single), **ctx.args()},
                )
            for rank, worker_id in enumerate(worker_ids):
                descriptions = []
                for single in key.singletons():
                    job = self._jobs[single]
                    remaining = self._get_remaining_steps(single)
                    descriptions.append(
                        self._job_description(
                            job, max(remaining, 1), rank, scale_factor,
                            lead_addr
                        )
                    )
                    ctx = dispatch_ctx.get(single)
                    if ctx is not None:
                        descriptions[-1]["trace_context"] = ctx.to_wire()
                self._outstanding.add((key, worker_id))
                rpc_start = time.perf_counter()
                client = self._worker_connections.get(worker_id)
                try:
                    if client is None:
                        raise KeyError(
                            f"worker {worker_id} has no connection "
                            "(died between planning and dispatch?)"
                        )
                    # The client retries with backoff internally; an
                    # exception here means every attempt failed.
                    client.run_job(
                        descriptions, worker_id, self._round_id,
                        sched_epoch=self._ha_epoch,
                    )
                except PermanentRpcError:
                    # The worker's epoch gate bounced us: a newer
                    # leader exists and every dispatch this process
                    # sends is dead on arrival. Fence immediately —
                    # do NOT fault-complete the micro-task; it is the
                    # successor's to manage.
                    self._outstanding.discard((key, worker_id))
                    self._ha_fenced()
                    return
                except Exception:
                    # A dispatch that cannot reach its worker must not
                    # leave the micro-task outstanding (the round-end
                    # wait would burn the whole completion buffer) nor
                    # crash the round loop: synthesize a zero-progress
                    # fault completion and let heartbeat expiry decide
                    # whether the worker is actually dead.
                    LOG.warning(
                        "dispatch of job %s to worker %s failed after "
                        "retries", key, worker_id, exc_info=True,
                    )
                    obs.counter(
                        "scheduler_dispatch_failures_total",
                        "RunJob dispatches that exhausted every retry",
                    ).inc()
                    self._outstanding.discard((key, worker_id))
                    zeros = [0] * len(key.singletons())
                    self._done_callback(
                        key, worker_id, zeros,
                        [0.0] * len(key.singletons()), fault=True,
                    )
                    continue
                obs.histogram(
                    "rpc_client_seconds",
                    "scheduler-to-worker RPC round-trip latency",
                ).observe(time.perf_counter() - rpc_start, method="RunJob")
        obs.counter(
            "scheduler_dispatches_total", "micro-task dispatches (relaunches)"
        ).inc()
        obs.histogram(
            "dispatch_latency_seconds",
            "wall time to dispatch one micro-task to its full gang",
        ).observe(time.perf_counter() - dispatch_start)

    # -- the round loop -------------------------------------------------
    def wait_for_workers(self, count: int, timeout: float = 120.0) -> None:
        """Block until ``count`` workers registered. The timeout error
        lists exactly who DID register (id, type, agent address) so the
        missing worker is identifiable from the message alone — "only
        1/2 registered" with no names cost real debugging time."""
        deadline = time.time() + timeout
        with self._cv:
            while len(self._worker_ids) < count:
                remaining = deadline - time.time()
                if remaining <= 0:
                    registered = [
                        "%d (%s @ %s:%s)"
                        % (
                            wid,
                            self._worker_id_to_worker_type.get(wid, "?"),
                            *self._worker_addrs.get(wid, ("?", "?")),
                        )
                        for wid in self._worker_ids
                    ]
                    raise TimeoutError(
                        f"only {len(self._worker_ids)}/{count} workers "
                        f"registered with scheduler port {self._port} "
                        f"after {timeout:.1f}s; registered: "
                        f"[{', '.join(registered) or 'none'}] — the "
                        f"missing {count - len(self._worker_ids)} never "
                        "called RegisterWorker (check the worker agents' "
                        "logs / --sched_port wiring)"
                    )
                self._cv.wait(timeout=remaining)

    def expect_jobs(self, count: int) -> None:
        """Tell the round loop how many jobs the full trace will submit, so
        an empty job table mid-trace (an arrival gap) idles instead of
        ending the run."""
        with self._cv:
            self._num_expected_jobs = count

    def _start_ingest_thread(self):
        """Event-driven ingest: when ``SHOCKWAVE_INGEST_TICK_S`` is set
        (> 0), a daemon thread drains the admission front door on its
        own cadence instead of once per round boundary — mid-round
        arrivals enter the job table immediately and flow into the
        planner as incremental delta-replans (add_job raises the
        recompute flag; the job axis stays inside its power-of-two
        band, so a streamed arrival never recompiles), reconciling
        with speculation at the next boundary exactly like a REPAIR.
        Admission latency stops being quantized to the round length.
        Unset/0 (the default) keeps the boundary-drain path
        bit-identical to the legacy behavior. Returns the stop event,
        or None when disabled."""
        try:
            tick_s = float(
                os.environ.get("SHOCKWAVE_INGEST_TICK_S", "0") or 0
            )
        except ValueError:
            tick_s = 0.0
        if tick_s <= 0:
            return None
        stop = threading.Event()

        def loop():
            ticks = obs.counter(
                "ingest_ticks_total",
                "ingest-thread drain ticks that admitted jobs "
                "mid-round",
            )
            while not (
                stop.is_set() or self._shutdown_requested.is_set()
            ):
                stop.wait(tick_s)
                if stop.is_set() or self._shutdown_requested.is_set():
                    break
                # Same single-drainer discipline as the boundary path:
                # _drain_admission_queue requires _cv, so the round
                # loop and this thread can never interleave a drain.
                with self._cv:
                    if self._admission.depth() == 0:
                        continue
                    admitted = self._drain_admission_queue()
                    if admitted:
                        ticks.inc()
                        self._cv.notify_all()

        thread = threading.Thread(
            target=loop, name="shockwave-ingest", daemon=True
        )
        thread.start()
        return stop

    def run(self, max_rounds: Optional[int] = None) -> None:
        """Drive rounds until every added job completes
        (reference: _schedule_with_rounds scheduler.py:2080-2129)."""
        from shockwave_tpu.runtime import faults

        fault_injector = faults.active()
        ingest_stop = self._start_ingest_thread()
        while not self._shutdown_requested.is_set():
            with self._cv:
                if fault_injector is not None:
                    self._apply_physical_fault_events(fault_injector)
                self._reap_dead_workers()
                # Batched admission: drain the streaming front door at
                # the round boundary, so a burst of arrivals costs one
                # replan, not one per job.
                self._drain_admission_queue()
                if len(self._jobs) == 0:
                    if self._stream_done():
                        break
                    # Arrival gap: wait for the next submission.
                    self._cv.wait(timeout=1.0)
                    continue
                if max_rounds is not None and self._round_id >= max_rounds:
                    break
                round_start = self.get_current_timestamp()
                self._round_end_time = round_start + self._time_per_iteration
                if self._shockwave is not None and self._round_id >= 1:
                    self._shockwave_scheduler_update()
                # Plan-ahead pipelining: reconcile the previous round's
                # speculative solve at the boundary, BEFORE this round's
                # speculation is kicked below (a hit installs the plan
                # window for the schedule passes; a repair arms the
                # warm-started re-solve they will run).
                if (
                    self._speculate
                    and self._shockwave is not None
                    and hasattr(self._shockwave, "reconcile_at_boundary")
                ):
                    self._shockwave.reconcile_at_boundary()
                assignments = (
                    self._next_assignments or self._schedule_jobs_on_workers()
                )
                self._next_assignments = OrderedDict()
                self._max_steps_agreement = {}
                # Extensions granted at the last mid-round stay in force
                # until the next mid-round recompute, so refreshes arriving
                # early in this round still see them (the Done handler
                # clears a job's extension the moment its process exits).
                extended = set(self._jobs_with_extended_lease)
                # Drop jobs that completed between planning and now.
                assignments = OrderedDict(
                    (key, ids)
                    for key, ids in assignments.items()
                    if all(s in self._jobs for s in key.singletons())
                )
                # Backfill workers the stale mid-round plan leaves idle.
                # The reference plans each round mid-way through the
                # previous one; with hour-long jobs a completion between
                # planning and the boundary is rare, but on fast chips
                # jobs are round-length and the lag strands a slot every
                # round (observed: a 2-slot cluster running the 12-job
                # trace one job per round). Replan and admit unassigned
                # jobs onto workers the surviving plan doesn't occupy —
                # never touching mid-round lease-extension promises
                # (extended jobs survive the filter above and keep their
                # workers via the planner's keep-previous pass).
                assigned_singles = {
                    s for key in assignments for s in key.singletons()
                }
                occupied = {
                    wid for ids in assignments.values() for wid in ids
                }
                idle = len(self._worker_ids) - len(occupied)
                # Only pay the second scheduling pass when some
                # unassigned job can actually fit the idle workers.
                min_unassigned_sf = min(
                    (
                        job.scale_factor
                        for j, job in self._jobs.items()
                        if j not in assigned_singles
                    ),
                    default=None,
                )
                # INVARIANT (this second _schedule_jobs_on_workers call
                # has side effects: it re-runs _update_priorities,
                # advances _worker_type_shuffler, and on the shockwave
                # path overwrites _current_round_scheduled_jobs /
                # may trigger a planner replan — the replan is the
                # point, it is what admits jobs the stale plan missed):
                # _current_round_scheduled_jobs overwritten here is
                # ALWAYS refreshed by the mid-round planning pass below
                # before _shockwave_scheduler_update reads it at the
                # next round boundary. The only gap — every job
                # completing mid-round so the mid-round pass is skipped
                # — leaves entries that the update routes through the
                # benign mark_complete path.
                if min_unassigned_sf is not None and min_unassigned_sf <= idle:
                    for key, ids in self._schedule_jobs_on_workers().items():
                        if key in assignments:
                            continue
                        if any(
                            s not in self._jobs or s in assigned_singles
                            for s in key.singletons()
                        ):
                            continue
                        if occupied & set(ids):
                            continue
                        assignments[key] = ids
                        assigned_singles.update(key.singletons())
                        occupied.update(ids)
                preempted_this_round = []
                for key, prev_ids in self._current_worker_assignments.items():
                    if not any(s in self._jobs for s in key.singletons()):
                        continue
                    if key not in assignments or set(
                        assignments[key]
                    ) != set(prev_ids):
                        self._num_preemptions += 1
                        preempted_this_round.append(key)
                        obs.counter(
                            "scheduler_preemptions_total",
                            "still-active jobs that lost their workers "
                            "at a round boundary",
                        ).inc()
                        obs.instant(
                            "preemption", cat="sched", tid="rounds",
                            args={"job_id": str(key)},
                        )
                self._current_worker_assignments = assignments
                self._round_log.append(
                    {
                        "event": "round",
                        "round": self._round_id,
                        "time": self.get_current_timestamp(),
                        "jobs": {
                            str(key): len(ids)
                            for key, ids in assignments.items()
                        },
                    }
                )
                obs.counter(
                    "scheduler_rounds_total", "scheduling rounds started"
                ).inc()
                # Physical rounds trace as B/E pairs emitted live (an X
                # span backdated at round end would append out of ts
                # order on the rounds track).
                obs.get_tracer().begin(
                    f"round {self._round_id}", cat="sched", tid="rounds",
                    args={
                        "round": self._round_id,
                        "scheduled_jobs": len(assignments),
                        "active_jobs": len(self._jobs),
                    },
                )
                obs.gauge(
                    "scheduler_queue_depth", "active (incomplete) jobs"
                ).set(len(self._jobs))
                obs.gauge(
                    "scheduler_scheduled_jobs",
                    "jobs granted workers this round",
                ).set(len(assignments))
                self._round_observability(
                    assignments, preempted=preempted_this_round
                )
                for key, worker_ids in assignments.items():
                    if key in extended:
                        continue  # still running under an extended lease
                    self._dispatch(key, worker_ids)
                # Plan-ahead pipelining: with this round dispatched,
                # solve the NEXT round speculatively on a background
                # thread while the workers execute — the solve bill is
                # hidden behind the round instead of landing under the
                # condition lock at the boundary / mid-round pass.
                # Snapshot+clone happens here, under _cv, so the clone
                # sees a consistent planner; the solve itself shares
                # nothing mutable with the live planner.
                if self._shockwave_can_speculate():
                    outcome = self._predict_physical_round_outcome(
                        assignments
                    )
                    if outcome is not None:
                        self._shockwave.speculate_next_round(
                            outcome, background=True
                        )

            # Mid-round: plan the next round so in-flight lease updates can
            # be extended (reference: _mid_round scheduler.py:1839-1965).
            time.sleep(self._time_per_iteration * SCHEDULE_RECOMPUTE_FRACTION)
            with self._cv:
                if len(self._jobs) > 0:
                    self._next_assignments = self._schedule_jobs_on_workers()
                    self._jobs_with_extended_lease = set()
                    for key, worker_ids in self._next_assignments.items():
                        prev = self._current_worker_assignments.get(key)
                        # Extend only if the micro-task is actually still
                        # running on the same workers — a process that
                        # already exited must be re-dispatched.
                        still_running = any(
                            (key, wid) in self._outstanding
                            for wid in worker_ids
                        )
                        if (
                            prev is not None
                            and set(prev) == set(worker_ids)
                            and still_running
                        ):
                            self._jobs_with_extended_lease.add(key)
                            self._num_lease_extensions += 1
                            obs.counter(
                                "scheduler_lease_extensions_total",
                                "round transitions where a job kept its "
                                "exact worker set",
                            ).inc()
                        self._num_lease_extension_opportunities += 1

            # End of round: wait for completions, then kill stragglers
            # (reference: _end_round :1993-2078, kill :3098-3170).
            remaining = self._round_end_time - self.get_current_timestamp()
            if remaining > 0:
                time.sleep(remaining)
            deadline = time.time() + self._completion_buffer
            with self._cv:
                expected = {
                    item
                    for item in self._outstanding
                    if item[0] not in self._jobs_with_extended_lease
                }
                while expected & self._outstanding:
                    wait = deadline - time.time()
                    if wait <= 0:
                        break
                    self._cv.wait(timeout=min(wait, 1.0))
                    # A worker dying mid-wait must clear its outstanding
                    # micro-tasks (fault-completions) instead of burning
                    # the whole completion buffer waiting for a Done
                    # report that will never come.
                    self._reap_dead_workers()
                stragglers = {
                    key for key, _ in (expected & self._outstanding)
                }
                # Extended-lease jobs that stopped speaking the lease
                # protocol are unresponsive: a healthy extended job
                # refreshes every round (75% consumption), so >1.5 rounds
                # of silence means the process is wedged (reference:
                # scheduler.py:3196-3202,3220-3221).
                now = self.get_current_timestamp()
                silence = 1.5 * self._time_per_iteration
                for key in list(self._jobs_with_extended_lease):
                    still_running = any(
                        (key, wid) in self._outstanding
                        for wid in self._dispatched_worker_ids.get(key, ())
                    )
                    last = self._last_lease_contact.get(
                        key, self._dispatch_times.get(key, now)
                    )
                    if still_running and now - last > silence:
                        stragglers.add(key)
                        self._jobs_with_extended_lease.discard(key)
            for key in stragglers:
                self._kill_job(key)
            round_wall = self.get_current_timestamp() - round_start
            obs.histogram(
                "scheduler_round_duration_seconds",
                "round length (simulated time in sim mode)",
            ).observe(round_wall)
            obs.get_tracer().end(
                f"round {self._round_id}", cat="sched", tid="rounds"
            )
            # Advance the round cursor under the lock: RPC handlers and
            # the admission drain stamp records with the current round,
            # and an unlocked increment here lets a Done/Submit racing
            # the boundary attribute work to a half-advanced round.
            with self._cv:
                self._round_id += 1
                self._num_completed_rounds += 1
                self._ha_log(
                    "round",
                    {
                        "round_id": self._round_id,
                        "completed": self._num_completed_rounds,
                    },
                )
                should_checkpoint = (
                    self._ha_journal is not None
                    and self._round_id % self._ha_checkpoint_rounds == 0
                )
            # Periodic compaction: a full checkpoint every N rounds
            # bounds failover replay to checkpoint + one short WAL
            # tail. OUTSIDE the round-boundary lock block: the capture
            # re-takes _cv briefly, but the encode + disk write must
            # not stall RPC handlers for the whole serialization.
            if should_checkpoint:
                self._ha_checkpoint()

        if ingest_stop is not None:
            ingest_stop.set()
        self.shutdown()

    def _kill_job(self, key: JobId) -> None:
        """Kill an unresponsive micro-task and synthesize zero-progress
        completions so bookkeeping converges
        (reference: scheduler.py:3098-3170). The kill span joins the
        job's causal chain and its context rides the KillJob RPC so
        the worker's kill handling hangs under it."""
        obs.counter(
            "scheduler_kills_total", "straggler/unresponsive job kills"
        ).inc()
        kill_ctx = None
        with self._cv:
            # _remove_job pops root contexts under the condition lock;
            # this lookup must not interleave with it.
            for single in key.singletons():
                root = self._job_trace_ctx.get(single)
                if root is not None:
                    kill_ctx = root.child()
                    break
        with obs.span(
            "kill", cat="sched", tid="dispatch",
            args={"job_id": str(key),
                  **(kill_ctx.args() if kill_ctx else {})},
        ):
            self._kill_job_inner(
                key, kill_wire=kill_ctx.to_wire() if kill_ctx else ""
            )

    def _kill_job_inner(self, key: JobId, kill_wire: str = "") -> None:
        with self._cv:
            worker_ids = list(
                self._dispatched_worker_ids.get(key)
                or self._current_worker_assignments.get(key, ())
            )
            # Snapshot the connections under the lock too: the reaper
            # pops dead workers from the map concurrently, and the kill
            # RPCs below must run unlocked (a black-holed host would
            # stall every lease handler otherwise).
            clients = {
                worker_id: self._worker_connections.get(worker_id)
                for worker_id in worker_ids
            }
        for worker_id in worker_ids:
            for job_int in key.as_tuple():
                try:
                    client = clients.get(worker_id)
                    if client is None:
                        continue  # worker already retired
                    # Retried with backoff inside the client
                    # (runtime/retry.py); reaching here means every
                    # attempt failed.
                    client.kill_job(
                        job_int, trace_context=kill_wire,
                        sched_epoch=self._ha_epoch,
                    )
                except PermanentRpcError:
                    # Fenced: a newer leader owns this worker. The kill
                    # (and the job) are the successor's business now.
                    self._ha_fenced()
                    return
                except Exception:
                    # The synthesized zero-progress Done below still
                    # converges bookkeeping, but a kill RPC that cannot
                    # reach its worker even after retries is exactly how
                    # a dead host first shows up — it must be visible,
                    # not swallowed.
                    LOG.warning(
                        "kill RPC for job %s on worker %s failed after "
                        "retries", job_int, worker_id, exc_info=True,
                    )
                    obs.counter(
                        "scheduler_kill_rpc_failures_total",
                        "kill RPCs that raised instead of reaching "
                        "their worker",
                    ).inc()
        deadline = time.time() + KILL_WAIT_SECONDS
        with self._cv:
            while any(
                (key, wid) in self._outstanding for wid in worker_ids
            ):
                wait = deadline - time.time()
                if wait <= 0:
                    break
                self._cv.wait(timeout=wait)
            for worker_id in worker_ids:
                if (key, worker_id) in self._outstanding:
                    self._outstanding.discard((key, worker_id))
                    zeros = [0] * len(key.singletons())
                    self._done_callback(
                        key, worker_id, zeros, [0.0] * len(key.singletons())
                    )

    @staticmethod
    def _reset_reclaimed_workers(clients) -> None:
        """Best-effort Reset for injected reclamations, off the round
        loop's locks (the workers are already retired from every
        placement structure; this only hastens their processes' end)."""
        for client in clients:
            try:
                client.reset()
            except Exception:
                LOG.debug(
                    "reset of reclaimed worker failed (already gone)",
                    exc_info=True,
                )

    def _predict_physical_round_outcome(self, assignments):
        """Physical-mode round-outcome prediction for the speculative
        next-round solve. Unlike simulation (exact by construction),
        this is an ESTIMATE — each dispatched job is predicted to run
        measured-EMA-throughput x round-length steps — so the boundary
        reconcile's epoch tolerance absorbs benign drift (an epoch
        boundary racing the measured step count) and real churn takes
        the warm-started repair path. Jobs with no usable throughput
        estimate yet (first dispatch) are predicted as zero-progress;
        their first measurement diverging is exactly the repair case.
        Only the per-job (steps, throughput) estimate is physical-mode
        specific; the outcome itself is built by the shared
        :meth:`Scheduler._spec_outcome_from_steps`.
        """
        steps_pred: Dict[JobId, tuple] = {}
        for key, worker_ids in assignments.items():
            worker_type = self._worker_id_to_worker_type[worker_ids[0]]
            for single in key.singletons():
                job = self._jobs.get(single)
                if job is None:
                    continue
                tput = self._throughputs.get(single, {}).get(worker_type)
                if (
                    not isinstance(tput, (int, float))
                    or tput <= 0
                    or tput >= INFINITY
                ):
                    continue
                steps = min(
                    int(tput * self._time_per_iteration),
                    max(
                        0,
                        job.total_steps - self._total_steps_run[single],
                    ),
                )
                if steps > 0:
                    steps_pred[single] = (steps, float(tput))
        return self._spec_outcome_from_steps(steps_pred)

    def _micro_task_scale_factor(self, job_id) -> int:
        ids = self._dispatched_worker_ids.get(job_id)
        if ids is not None:
            return len(ids)
        return len(self._current_worker_assignments[job_id])

    # -- HA checkpoint / journal replay ---------------------------------
    def ha_state_dict(self) -> dict:
        """Base control-plane snapshot plus the physical runtime's
        own survival-critical state: the round cursor, worker registry
        addresses, in-flight micro-tasks, lease/incumbency maps, and
        the admission front door (token ledger + pending backlog +
        tenant quotas)."""
        state = super().ha_state_dict()
        state["physical"] = {
            "now": self.get_current_timestamp(),
            "round_id": self._round_id,
            # Scheduler-crash fault ids already taken by ANY past
            # incarnation: compaction would otherwise erase the WAL
            # markers and a later successor would re-apply a consumed
            # crash to itself (SIGKILL ping-pong between drills).
            "consumed_sched_faults": self._ha_consumed_sched_faults,
            "num_expected_jobs": self._num_expected_jobs,
            "dispatch_times": self._dispatch_times,
            "extended_leases": self._jobs_with_extended_lease,
            "next_assignments": self._next_assignments,
            "max_steps_agreement": self._max_steps_agreement,
            "last_lease_contact": self._last_lease_contact,
            "outstanding": self._outstanding,
            "dispatched_worker_ids": self._dispatched_worker_ids,
            "worker_addrs": self._worker_addrs,
            "retired_workers": self._retired_workers,
            "admission": self._admission.state_dict(),
        }
        return state

    def restore_ha_state(self, state: dict) -> None:
        """Install a decoded snapshot into this freshly constructed
        scheduler. Connections are NOT restored — workers re-attach to
        the successor carrying their previous identity — but the
        registry, addresses, and in-flight micro-task state are, so a
        re-attaching worker slots straight back in."""
        super().restore_ha_state(state)
        if self._shockwave is not None:
            # A real failover: the fleet may have churned during the
            # outage — the restored planner must replan onto whatever
            # actually re-attaches.
            self._shockwave.set_recompute_flag()
        p = state.get("physical") or {}
        # The control-plane clock must CONTINUE across the failover
        # (makespans span the outage; a reset clock would time-travel
        # every restored timestamp).
        now = float(p.get("now", self._current_timestamp))
        self._start_time = time.time() - now
        self._round_id = int(p.get("round_id", 0))
        self._num_expected_jobs = p.get("num_expected_jobs")
        self._dispatch_times = dict(p.get("dispatch_times") or {})
        self._next_assignments = OrderedDict(
            (key, tuple(ids))
            for key, ids in (p.get("next_assignments") or {}).items()
        )
        self._max_steps_agreement = dict(p.get("max_steps_agreement") or {})
        self._last_lease_contact = dict(p.get("last_lease_contact") or {})
        self._outstanding = {
            (key, int(wid)) for key, wid in (p.get("outstanding") or [])
        }
        self._dispatched_worker_ids = {
            key: tuple(int(w) for w in ids)
            for key, ids in (p.get("dispatched_worker_ids") or {}).items()
        }
        self._worker_addrs = {
            int(wid): (str(addr[0]), int(addr[1]))
            for wid, addr in (p.get("worker_addrs") or {}).items()
        }
        if "admission" in p:
            self._admission.restore_state(p["admission"])
        self._ha_consumed_sched_faults = set(
            int(e) for e in (p.get("consumed_sched_faults") or [])
        )
        with self._hb_lock:
            self._retired_workers = set(p.get("retired_workers") or [])
            # Every restored worker gets a fresh liveness lease: the
            # heartbeat-timeout grace period IS the re-attach window,
            # and a worker that never comes back is reaped through the
            # normal death path (requeue + capacity shrink).
            now_mono = time.monotonic()
            for wid in self._worker_id_to_worker_type:
                self._last_heartbeat[wid] = now_mono
        # In-flight micro-tasks keep running on the (re-attaching)
        # workers through the outage: treat them as extended leases so
        # the first post-failover round does not re-dispatch them, and
        # reset their lease-contact clocks so the unresponsiveness
        # check starts from the takeover, not from stamps made under
        # the dead leader's clock.
        self._jobs_with_extended_lease = set(
            p.get("extended_leases") or []
        )
        for key, _wid in self._outstanding:
            self._jobs_with_extended_lease.add(key)
            self._last_lease_contact[key] = now

    def restore_from_journal(self, snapshot) -> dict:
        """Resume from a :meth:`ControlPlaneJournal.replay` snapshot:
        install the checkpoint, re-apply the WAL tail in LSN order
        through the same code paths the live events took, then write a
        compacting checkpoint so the next failover replays from HERE
        (and so nothing re-journaled during replay can duplicate the
        tail). Returns {kind: count} of applied tail entries."""
        applied: Dict[str, int] = {}
        self._ha_replaying = True
        # Out-of-order WAL reconciliation: submit_batch journals its
        # 'submit' entry AFTER the queue accepted the batch (the queue
        # work deliberately runs outside the round loop's lock), so a
        # drain racing the append can journal the matching 'admit' at
        # a LOWER LSN. Replay tracks admits whose submit hasn't been
        # seen yet (discard_pending found nothing) and drops that many
        # already-admitted jobs when the late 'submit' arrives.
        self._ha_replay_admit_debt: Dict[str, int] = {}
        try:
            with self._cv:
                if snapshot.checkpoint is not None:
                    self.restore_ha_state(snapshot.checkpoint)
                for entry in snapshot.entries:
                    kind = entry["kind"]
                    self._ha_apply_entry(kind, entry["payload"])
                    applied[kind] = applied.get(kind, 0) + 1
        finally:
            self._ha_replaying = False
            self._ha_replay_admit_debt = {}
        recorder = obs.get_recorder()
        if recorder.enabled:
            recorder.record_recovery(
                {
                    "kind": "scheduler_failover",
                    "how": "journal_replayed",
                    "epoch": self._ha_epoch,
                    "round": self._round_id,
                    "checkpoint": snapshot.checkpoint is not None,
                    "tail_entries": len(snapshot.entries),
                }
            )
        obs.counter(
            "ha_failover_restores_total",
            "journal checkpoint+tail restores completed by a successor",
        ).inc()
        self._ha_checkpoint()
        # Registrations may flow now: the registry is the restored one.
        self._ha_restore_pending = False
        LOG.warning(
            "restored from journal: round %d, %d jobs live, %d workers "
            "registered, %d in-flight micro-tasks, tail %s",
            self._round_id, len(self._jobs),
            len(self._worker_id_to_worker_type), len(self._outstanding),
            applied or "empty",
        )
        return applied

    def _ha_apply_entry(self, kind: str, payload: dict) -> None:
        """Caller holds the lock (_cv), replay flag set. Re-apply one
        WAL delta through the live code paths."""
        from shockwave_tpu.ha import codec as ha_codec

        if kind == "register":
            # Mint the same ids the dead leader handed out (LSN order
            # makes the counter walk identical); connections stay empty
            # until the agent re-attaches.
            self._worker_id_counter = min(payload["worker_ids"])
            ids = self.register_worker(
                payload["worker_type"],
                num_gpus=int(payload["num_accelerators"]),
            )
            for wid in ids:
                self._worker_addrs[wid] = (
                    str(payload["ip_addr"]), int(payload["port"])
                )
            now_mono = time.monotonic()
            with self._hb_lock:
                for wid in ids:
                    self._last_heartbeat[wid] = now_mono
        elif kind == "reattach":
            # Only the reconciliation mutated accounting; connections
            # are rebuilt when the agent re-attaches to THIS process.
            self._reconcile_reattach_locked(
                list(payload["worker_ids"]),
                {int(j) for j in payload.get("reported_job_ids", [])},
            )
        elif kind == "retire":
            wid = int(payload["worker_id"])
            if wid in self._worker_id_to_worker_type:
                self._retire_worker(wid, kind=str(payload["kind"]))
        elif kind == "submit":
            jobs = [
                ha_codec.job_from_state(j) for j in payload["jobs"]
            ]
            token = str(payload["token"])
            # Jobs this token already admitted via LOWER-LSN 'admit'
            # entries (the out-of-order append race) must not re-enter
            # the backlog.
            debt = self._ha_replay_admit_debt.pop(token, 0)
            self._admission.restore_submission(
                token, jobs[debt:],
                close=bool(payload.get("close")),
            )
        elif kind == "close":
            self._admission.close()
        elif kind == "admit":
            token = payload.get("token")
            # None = in-process add_job (no queue entry to pop);
            # "" = a tokenless front-door batch, whose pending entries
            # ARE stored under "" and must still be consumed or the
            # successor's drain admits the job a second time.
            if token is not None:
                # The restored queue still holds this job as pending
                # (checkpoint or replayed submit); the drain consumed
                # it before the crash. Nothing to discard = the token's
                # 'submit' entry has a HIGHER LSN (the append race) —
                # note the debt so its replay skips this job.
                if self._admission.discard_pending(str(token), 1) == 0:
                    self._ha_replay_admit_debt[str(token)] = (
                        self._ha_replay_admit_debt.get(str(token), 0) + 1
                    )
            self._job_id_counter = int(payload["job_id"])
            self.add_job(
                ha_codec.job_from_state(payload["job"]),
                timestamp=payload.get("timestamp"),
            )
        elif kind == "dispatch":
            key = JobId(*payload["job_ids"])
            worker_ids = tuple(int(w) for w in payload["worker_ids"])
            self._dispatched_worker_ids[key] = worker_ids
            self._dispatch_times[key] = self.get_current_timestamp()
            for wid in worker_ids:
                self._outstanding.add((key, wid))
            for single in key.singletons():
                if single in self._jobs:
                    self._running_jobs.add(single)
            # Still in flight across the failover: see restore_ha_state.
            self._jobs_with_extended_lease.add(key)
            self._last_lease_contact[key] = self.get_current_timestamp()
        elif kind == "done":
            key = JobId(*payload["job_ids"])
            wid = int(payload["worker_id"])
            if (key, wid) not in self._outstanding:
                return  # duplicate entry (retransmit journaled twice)
            self._outstanding.discard((key, wid))
            if not any(
                (key, w) in self._outstanding
                for w in self._dispatched_worker_ids.get(key, ())
            ):
                self._jobs_with_extended_lease.discard(key)
            self._done_callback(
                key, wid,
                [int(s) for s in payload["steps"]],
                [float(t) for t in payload["times"]],
            )
        elif kind == "round":
            self._round_id = int(payload["round_id"])
            self._num_completed_rounds = int(
                payload.get("completed", self._num_completed_rounds)
            )
        elif kind == "sched_fault":
            self._ha_consumed_sched_faults.add(int(payload["event_id"]))
        else:
            LOG.warning("unknown WAL entry kind %r skipped", kind)

    def wait_for_reattach(self, timeout: float = 30.0) -> list:
        """After a journal restore, block until every restored worker
        re-attached (heartbeat-ack failure drives agents to the
        front-door map within a few beats). Workers that never come
        back are retired through the normal death path — their
        in-flight micro-tasks requeue as fault completions, exactly
        once. Returns the retired worker ids."""
        deadline = time.time() + timeout
        with self._cv:
            while True:
                missing = [
                    wid
                    for wid in self._worker_ids
                    if wid not in self._worker_connections
                ]
                if not missing or time.time() >= deadline:
                    break
                self._cv.wait(timeout=0.5)
            for wid in missing:
                LOG.warning(
                    "worker %d never re-attached after failover; "
                    "retiring it", wid,
                )
                self._retire_worker(wid, kind="failover_lost")
            return missing

    def shutdown(self) -> None:
        if self._shutdown_requested.is_set():
            return
        self._shutdown_requested.set()
        if self._fleet is not None:
            self._fleet.stop()
        if self._ha_deposed:
            # Fenced: the workers belong to the successor now — sending
            # them Shutdown would tear down the very fleet the new
            # leader is resuming. Stop our own server and go quietly;
            # the lease is already the successor's, so nothing to
            # release.
            LOG.warning(
                "deposed scheduler (epoch %d) shutting down without "
                "touching the fleet", self._ha_epoch,
            )
            if self._ha_election is not None:
                self._ha_election.stop(release=False)
            self._server.stop(grace=2)
            with self._cv:
                self._cv.notify_all()
            return
        # Snapshot under the lock: a straggling RegisterWorker or a
        # concurrent reap mutates the connection map while this
        # iterates (the shutdown RPCs themselves stay outside the lock
        # — a black-holed worker must not wedge the lease handlers).
        with self._cv:
            clients = list(self._worker_connections.values())
        seen = set()
        for client in clients:
            if id(client) in seen:
                continue
            seen.add(id(client))
            try:
                client.shutdown()
            except Exception:
                # Workers racing us to exit is normal at teardown; keep
                # it on the record at debug so a shutdown that hangs has
                # a trail, without alarming clean exits.
                LOG.debug(
                    "worker shutdown RPC failed (worker likely already "
                    "gone)", exc_info=True,
                )
        if self._ha_election is not None:
            # Clean exit: hand the standby leadership immediately
            # instead of making it wait out the lease TTL.
            self._ha_election.stop(release=True)
        self._server.stop(grace=2)
