"""The job record.

Carries what the scheduler needs to know about one training job: which model
family / batch size it is (encoded in ``job_type`` as ``"Model (batch size
N)"``), the launch command, how many steps remain, how many accelerators it
gangs over (``scale_factor``), and its dynamic-adaptation mode
(static / accordion / gns).

Capability parity with reference: scheduler/job.py:1-146.
"""

from __future__ import annotations

import dataclasses
from typing import Optional


@dataclasses.dataclass
class Job:
    job_type: str
    command: str = ""
    working_directory: str = ""
    num_steps_arg: str = "-n"
    total_steps: int = 0
    duration: Optional[float] = None
    mode: str = "static"  # static | accordion | gns
    scale_factor: int = 1
    priority_weight: float = 1.0
    SLO: Optional[float] = None
    needs_data_dir: bool = False
    job_id: Optional[int] = None
    # Admission-side multi-tenancy: the submitting tenant's identity,
    # carried on the SubmitJobs wire (admission_pb2.JobSpec.tenant) and
    # judged against per-tenant queue quotas at the front door. Empty =
    # the anonymous tenant (no quota applies). Not part of the trace
    # format — single-tenant traces stay byte-identical.
    tenant: str = ""
    # Causal root context of this job's life, carried on the SubmitJobs
    # wire (admission_pb2.JobSpec.trace_context; obs/propagate.py
    # encoding). Empty = untraced — the scheduler starts a fresh root
    # at admission if tracing is on. Not part of the trace format.
    trace_context: str = ""

    def __post_init__(self):
        if self.SLO is not None and self.SLO < 0:
            self.SLO = None

    # ``job_type`` is the single source of truth for (model, batch size),
    # matching the reference's string encoding (scheduler/job.py:119-129).
    @property
    def model(self) -> str:
        from shockwave_tpu.data.workload_info import parse_job_type

        return parse_job_type(self.job_type)[0]

    @property
    def batch_size(self) -> int:
        from shockwave_tpu.data.workload_info import parse_job_type

        return parse_job_type(self.job_type)[1]

    def job_type_key(self):
        return (self.job_type, self.scale_factor)

    def update_batch_size(self, new_bs: int) -> None:
        """Rewrite job_type and command for a new batch size.

        The batch-size argument is the last token of the command for most
        workloads; translation/imagenet commands carry one trailing
        positional/flag argument after it (reference: job.py:131-146).
        """
        if "translation" not in self.command and "imagenet" not in self.command:
            self.command = self.command[: self.command.rfind(" ")] + f" {new_bs}"
        else:
            last = self.command.rfind(" ")
            second_last = self.command[:last].rfind(" ")
            self.command = (
                self.command[:second_last] + f" {new_bs}" + self.command[last:]
            )
        self.job_type = self.job_type[: self.job_type.rfind(" ")] + f" {new_bs})"

    def to_trace_line(self) -> str:
        """Serialize to the 12-field tab-separated trace format (without the
        arrival-time column appended by the trace writer)."""
        slo = -1.0 if self.SLO is None else self.SLO
        return "\t".join(
            [
                self.job_type,
                self.command,
                self.working_directory,
                self.num_steps_arg,
                "%d" % int(self.needs_data_dir),
                "%d" % self.total_steps,
                "%d" % self.scale_factor,
                self.mode,
                "%g" % self.priority_weight,
                "%f" % slo,
                "%g" % float(self.duration if self.duration else 0),
            ]
        )
