from shockwave_tpu.core.ids import JobId
from shockwave_tpu.core.job import Job

__all__ = ["JobId", "Job"]
