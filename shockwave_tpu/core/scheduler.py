"""The round-based scheduler and its discrete-event simulator.

Time is divided into fixed rounds (``time_per_iteration`` seconds). Every
round the active policy picks which jobs occupy which workers; jobs are
preempted at round boundaries via checkpoint/restore (physical mode) or by
construction (simulation). This module reproduces the mechanism semantics of
the reference scheduler (reference: scheduler/scheduler.py) with a
simulation-first, lock-free structure; the physical runtime plugs into the
same callbacks (see shockwave_tpu.runtime).

Key mechanisms and their reference anchors:
  * round loop / event heap          scheduler.py:1509-1796
  * priorities & deficits            scheduler.py:2589-2800
  * strided worker assignment        scheduler.py:838-1129
  * micro-task completion merging    scheduler.py:3223-3482
  * batch-size adaptation (sim)      scheduler.py:1308-1363, 3504-3591
  * Shockwave planner hooks          scheduler.py:991-1014, 3598-3621
  * metrics                          scheduler.py:2131-2265, 3627-3655
"""

from __future__ import annotations

import copy
import heapq
import math
import os
import random
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

import numpy as np

from shockwave_tpu import obs
from shockwave_tpu.core.ids import JobId
from shockwave_tpu.core.job import Job
from shockwave_tpu.data.workload_info import (
    DATASET_SIZES,
    MAX_BATCH_SIZES,
    num_epochs as epochs_for_steps,
    steps_per_epoch,
    total_steps_for_epochs,
)
from shockwave_tpu.utils.logging import make_logger

INFINITY = int(1e9)
DEFAULT_THROUGHPUT = 1
EMA_ALPHA = 0.5
MAX_FAILED_ATTEMPTS = 5

# Batch-size scaling directions.
BS_BIG = 0
BS_SMALL = 1


def resolve_preemption_overhead(overheads, job_type: str) -> float:
    """Per-job relaunch overhead (seconds) from an overhead table.

    ``overheads`` is either a scalar (every family pays the same), or a
    dict keyed by family name — the part of ``job_type`` before the
    " (batch size N)" suffix — with an optional "default" entry. Absent
    families cost 0 (overhead-blind), matching the measured reports,
    which only cover families that actually relaunched.
    """
    if overheads is None:
        return 0.0
    if isinstance(overheads, (int, float)):
        return float(overheads)
    family = job_type.split(" (")[0]
    return float(overheads.get(family, overheads.get("default", 0.0)))


def autosize_round_duration(
    overheads,
    base_round_s: float,
    max_overhead_fraction: float = 0.25,
    max_round_s: Optional[float] = None,
) -> float:
    """Overhead-aware round length: long enough that the WORST measured
    per-family relaunch overhead costs at most ``max_overhead_fraction``
    of one round (the Shockwave paper amortizes with a fixed 360 s round,
    reference scheduler.py:100; with measured overheads the round can be
    sized instead of guessed). Never shrinks below ``base_round_s``;
    ``max_round_s`` caps the stretch so one pathological measurement
    cannot push rounds toward infinity.
    """
    if not 0.0 < max_overhead_fraction <= 1.0:
        raise ValueError(
            f"max_overhead_fraction must be in (0, 1], got "
            f"{max_overhead_fraction}"
        )
    if overheads is None:
        worst = 0.0
    elif isinstance(overheads, (int, float)):
        worst = float(overheads)
    elif isinstance(overheads, dict):
        worst = max(
            (float(v) for k, v in overheads.items() if k != "default"),
            default=0.0,
        )
        worst = max(worst, float(overheads.get("default", 0.0)))
    else:
        # Same contract as resolve_preemption_overhead: anything else
        # would pass sizing here and then crash at the first add_job.
        raise TypeError(
            "preemption overheads must be None, a scalar, or a "
            f"{{family: seconds}} dict, got {type(overheads).__name__}"
        )
    sized = max(float(base_round_s), worst / max_overhead_fraction)
    if max_round_s is not None:
        sized = min(sized, float(max_round_s))
    return max(sized, float(base_round_s))


class Scheduler:
    def __init__(
        self,
        policy,
        simulate: bool = True,
        throughputs: Optional[dict] = None,
        seed: int = 0,
        time_per_iteration: float = 360.0,
        profiles: Optional[dict] = None,
        shockwave_config: Optional[dict] = None,
        max_rounds: Optional[int] = None,
        minimum_time_between_allocation_resets: float = 1920.0,
        enable_global_queue: bool = False,
        per_worker_type_prices: Optional[Dict[str, float]] = None,
        log_level=None,
        profiling_percentage: float = 1.0,
        num_reference_models: Optional[int] = None,
        preemption_overheads=None,
        round_overhead_fraction: Optional[float] = None,
    ):
        self._policy = policy
        self._simulate = simulate
        self._oracle_throughputs = throughputs
        self._time_per_iteration = float(time_per_iteration)
        # Preemption awareness: per-family relaunch overheads (seconds;
        # scalar or {family: seconds}) feed the Shockwave planner's
        # switching-cost term, and — when round_overhead_fraction is set
        # — auto-size the round so the worst relaunch costs at most that
        # fraction of it.
        if preemption_overheads is None and shockwave_config is not None:
            preemption_overheads = shockwave_config.get(
                "preemption_overheads"
            )
        self._preemption_overheads = preemption_overheads
        if round_overhead_fraction is not None:
            sized = autosize_round_duration(
                preemption_overheads,
                self._time_per_iteration,
                max_overhead_fraction=round_overhead_fraction,
            )
            if sized != self._time_per_iteration:
                self._time_per_iteration = sized
                if shockwave_config is not None:
                    shockwave_config = dict(shockwave_config)
                    shockwave_config["time_per_iteration"] = sized
        self._profiles = profiles or {}
        self._max_rounds = max_rounds
        self._min_reset_interval = minimum_time_between_allocation_resets
        self._enable_global_queue = enable_global_queue
        # $/accelerator-hour per worker type; None disables cost
        # accounting. Each value is either a constant or a time-varying
        # [[time_s, price], ...] schedule resolved at charge time
        # (reference: scheduler.py:294-308, 3399-3411 with the spot-price
        # lookups of utils.py:300-420; see data/spot_prices.py).
        self._per_worker_type_prices = per_worker_type_prices

        self._current_timestamp: float = 0.0
        self._num_completed_rounds = 0

        # RNG fan-out mirrors the reference so seeded runs are comparable
        # (reference: scheduler.py:378-392).
        self._job_generator = random.Random(seed + 2)
        self._interarrival_time_generator = random.Random(seed + 3)
        self._worker_type_shuffler = random.Random(seed + 5)
        self._slo_generator = random.Random(seed + 6)

        # Job state.
        self._job_id_counter = 0
        self._jobs: "OrderedDict[JobId, Job]" = OrderedDict()
        # Tenant-spend gauge bookkeeping: the planner replan round last
        # published and the tenant labels last set (so a tenant whose
        # jobs all left is zeroed instead of frozen).
        self._tenant_spend_round: Optional[int] = None
        self._tenant_spend_seen: set = set()
        self._completed_jobs: set = set()
        self._running_jobs: set = set()
        self._steps_run_so_far: Dict[JobId, Dict[str, int]] = {}
        self._total_steps_run: Dict[JobId, int] = {}
        self._job_time_so_far: Dict[JobId, Dict[str, float]] = {}
        self._job_cost_so_far: Dict[JobId, float] = {}
        # Cumulative processing (run) seconds each job has received —
        # the realized counterpart the calibration tracker scores the
        # predictor's remaining-runtime forecasts against. Tracked
        # unconditionally: one dict add per micro-task completion, and
        # scheduling decisions never read it.
        self._job_total_run_time: Dict[JobId, float] = {}
        self._throughputs: Dict[JobId, dict] = {}
        self._original_bs: Dict[JobId, int] = {}
        self._bs_scale: Dict[JobId, Optional[int]] = {}
        self._job_id_to_job_type: Dict[JobId, Tuple[str, int]] = {}
        self._job_type_to_job_ids: Dict[Tuple[str, int], set] = {}
        self._num_failures_per_job: Dict[JobId, int] = {}
        self._per_job_start_timestamps: Dict[JobId, float] = {}
        self._per_job_latest_timestamps: Dict[JobId, Optional[float]] = {}
        # Pool-relative isolated-baseline scale for finish-time
        # fairness: under hetero_pools a job admitted to pool p has its
        # profile durations rescaled by base_tput/pool_tput, and its
        # rho denominator must use the SAME pool-speed baseline — a
        # k80-pool job judged against the v100 isolated duration reads
        # as unfairly late merely for running on the chips it was
        # assigned. 1.0 (absent) for single-pool runs.
        self._pool_ftf_scale: Dict[int, float] = {}
        self._job_completion_times: "OrderedDict[JobId, Optional[float]]" = OrderedDict()
        self._job_priority_weights: Dict[JobId, float] = {}
        self._num_jobs_in_trace = 0
        self._in_progress_updates: Dict[JobId, list] = {}
        # Micro-tasks with at least one fault-synthesized completion in
        # their in-flight merge (see _done_callback's fault flag).
        self._fault_tainted: set = set()
        self._job_timelines: Dict[JobId, list] = {}
        # Per-job causal root contexts (obs/propagate.py): jobs arriving
        # through the front door carry the submitter's root, everything
        # else gets a fresh one at admission; every span/instant of the
        # job's life stamps ids from this chain so merge_traces.py can
        # reconstruct one cross-process tree. Populated only while
        # tracing is on AND the chain is sampled — disabled runs never
        # touch it.
        self._job_trace_ctx: Dict[JobId, object] = {}
        # Structured event log (job admissions, per-round assignments,
        # completions) consumed by scripts/analysis/postprocess_log.py —
        # the machine-readable equivalent of the reference's text-log
        # postprocessing pipeline (reference:
        # scripts/utils/postprocess_simulator_log.py,
        # scripts/utils/generate_trace_from_scheduler_log.py). Always
        # recorded: one small dict per round/job, cheap next to the
        # per-iterator-line _job_timelines, and checkpointed with them.
        self._round_log: list = []
        # Absolute per-job deadlines, tracked only for SLO-aware policies
        # (reference: scheduler.py:583-587).
        self._slos: Optional[Dict[JobId, float]] = (
            {} if "SLO" in policy.name else None
        )

        # Worker state.
        self._worker_id_counter = 0
        self._worker_ids: List[int] = []
        self._worker_types: List[str] = []
        self._cluster_spec: Dict[str, int] = {}
        self._worker_id_to_worker_type: Dict[int, str] = {}
        # worker_type -> list of per-server worker-id lists.
        self._worker_type_to_worker_ids: Dict[str, List[List[int]]] = {}
        self._worker_start_times: Dict[int, float] = {}
        self._cumulative_worker_time_so_far: Dict[int, float] = {}
        self._worker_time_so_far: Dict[str, float] = {}
        self._available_worker_ids: set = set()

        # Allocation state.
        self._allocation: Dict[JobId, Dict[str, float]] = {}
        self._priorities: Dict[str, Dict[JobId, float]] = {}
        self._deficits: Dict[str, Dict[JobId, float]] = {}
        self._need_to_update_allocation = True
        self._last_reset_time: float = 0.0
        self._current_worker_assignments: "OrderedDict[JobId, tuple]" = OrderedDict()
        self._current_round_scheduled_jobs: List[JobId] = []
        self._num_lease_extensions = 0
        self._num_lease_extension_opportunities = 0
        # Preemptions: a still-active job that held workers last round
        # and this round is either unscheduled or moved to a different
        # worker set (each one pays a checkpoint/relaunch in physical
        # mode; the planner's switching-cost term exists to reduce this).
        self._num_preemptions = 0

        self._logger = make_logger(
            "scheduler", lambda: self._current_timestamp, level=log_level
        )

        # Telemetry (shockwave_tpu.obs): disabled by default, in which
        # case every call below is a no-op flag check. With tracing on,
        # trace timestamps follow this scheduler's clock — virtual time
        # in simulation, wall-since-start in physical mode — so the
        # exported timeline is laid out in the run's own time base.
        # Weakref: the tracer is process-global, so a bound method here
        # would pin every finished Scheduler (jobs, logs, timelines)
        # alive across a multi-run process.
        if obs.trace_enabled():
            import weakref

            self_ref = weakref.ref(self)

            def _trace_clock():
                sched = self_ref()
                return (
                    sched.get_current_timestamp()
                    if sched is not None
                    else 0.0
                )

            obs.set_trace_clock(_trace_clock)

        # Shockwave planner (attached when the policy is a Shockwave
        # variant; see shockwave_tpu.policies.shockwave).
        self._shockwave = None
        self._is_shockwave = policy.name.startswith("Shockwave")
        # Plan-ahead pipelining (shockwave_config["speculate"]): while
        # round r executes, solve round r+1 speculatively from a
        # snapshot + the predicted round outcome, then reconcile at the
        # boundary (see shockwave_tpu/policies/speculation.py). The
        # SCHEDULER owns the execution model, so it supplies the
        # predicted outcome; the planner snapshots/solves/reconciles.
        self._speculate = bool(
            (shockwave_config or {}).get("speculate", False)
        )
        if self._is_shockwave:
            if shockwave_config is None:
                raise ValueError("Shockwave policies require shockwave_config")
            self._shockwave = policy.make_planner(shockwave_config)

        self._job_packing = "Packing" in policy.name

        # Online throughput estimation (reference: scheduler.py:282-292,
        # 394-403): with packing policies, when colocation profiling is
        # partial or the reference-model set is a subset of the job table,
        # the allocator sees ESTIMATED pair throughputs (matrix completion
        # + cosine matching against reference types) while simulated
        # execution keeps using the oracle truth.
        self._estimate_throughputs = self._job_packing and (
            profiling_percentage < 1.0 or num_reference_models is not None
        )
        if self._estimate_throughputs:
            from shockwave_tpu.core.throughput_estimator import (
                ThroughputEstimator,
            )
            from shockwave_tpu.data.job_table import build_job_table

            if throughputs is None:
                raise ValueError(
                    "throughput estimation requires an oracle to profile "
                    "against"
                )
            job_types = [(t.model, 1) for t in build_job_table()]
            if num_reference_models is None:
                num_reference_models = len(job_types)
            self._throughput_estimator = ThroughputEstimator(
                throughputs,
                sorted(throughputs.keys()),
                job_types,
                num_reference_models,
                profiling_percentage,
                seed=seed + 4,
            )
            self._reference_throughputs = (
                self._throughput_estimator.get_reference_throughputs()
            )
            self._reference_job_map: Dict[JobId, Tuple[str, int]] = {}

    # ------------------------------------------------------------------
    # Worker registration (simulation path; RPC path wraps this).
    # ------------------------------------------------------------------
    def register_worker(self, worker_type: str, num_gpus: int = 1) -> List[int]:
        """Register one server with ``num_gpus`` workers of ``worker_type``
        (reference: scheduler.py:2854-2940)."""
        if worker_type not in self._worker_type_to_worker_ids:
            if self._shockwave_is_pool_set():
                # The pool set snapshots the cluster at first admission
                # (static-cluster assumption, as in the reference); chips
                # of a type registered after that are never planned.
                self._logger.warning(
                    "worker type %r registered after the Shockwave pool "
                    "set was fixed; its chips will not be planned",
                    worker_type,
                )
            # Atomic publication: the streaming-admission validator
            # (core/physical.py _validate_job_runnable) reads this list
            # from the SubmitJobs RPC thread without the round loop's
            # condition lock (admission must stay cheap under a
            # submission storm). Rebinding a fresh sorted list is an
            # atomic pointer swap under the GIL; an in-place
            # append+sort would expose a half-sorted list mid-read.
            # The read-modify-write here is safe because every writer
            # holds _cv — only the lockless READER side is unguarded,
            # and it sees the old or the new list, never a torn one.
            # shockwave-lint: disable=shared-state-race
            self._worker_types = sorted(
                [*self._worker_types, worker_type]
            )
            self._cluster_spec[worker_type] = 0
            self._worker_type_to_worker_ids[worker_type] = []
            self._worker_time_so_far[worker_type] = 0.0
            self._priorities[worker_type] = {}
            self._deficits[worker_type] = {}
            for job_id in self._jobs:
                self._steps_run_so_far[job_id][worker_type] = 0
                self._set_initial_throughput(job_id, worker_type)
                self._job_time_so_far[job_id][worker_type] = (
                    self._time_per_iteration / 2.0
                )
        server_ids = []
        for _ in range(num_gpus):
            worker_id = self._worker_id_counter
            self._worker_id_counter += 1
            server_ids.append(worker_id)
            self._worker_ids.append(worker_id)
            self._worker_id_to_worker_type[worker_id] = worker_type
            self._cluster_spec[worker_type] += 1
            self._worker_start_times[worker_id] = self._current_timestamp
            self._cumulative_worker_time_so_far[worker_id] = 0.0
            self._available_worker_ids.add(worker_id)
        self._worker_type_to_worker_ids[worker_type].append(server_ids)
        self._need_to_update_allocation = True
        return server_ids

    def remove_worker(self, worker_id: int) -> None:
        """Unregister a dead or reclaimed worker from every placement
        structure. Jobs holding the worker lose their current
        assignment (the next scheduling pass re-places them, with the
        planner's switching-cost term pricing the forced migration);
        per-worker accounting dicts keep their entries so utilization
        and cost math over the worker's lifetime stays intact."""
        worker_type = self._worker_id_to_worker_type.pop(worker_id, None)
        if worker_type is None:
            return
        self._worker_ids.remove(worker_id)
        self._cluster_spec[worker_type] -= 1
        servers = self._worker_type_to_worker_ids[worker_type]
        for server in servers:
            if worker_id in server:
                server.remove(worker_id)
        self._worker_type_to_worker_ids[worker_type] = [
            s for s in servers if s
        ]
        self._available_worker_ids.discard(worker_id)
        for key in [
            k
            for k, ids in self._current_worker_assignments.items()
            if worker_id in ids
        ]:
            del self._current_worker_assignments[key]
        self._need_to_update_allocation = True
        self._sync_planner_capacity()

    def _sync_planner_capacity(self) -> None:
        """Propagate a capacity change (worker death, reclamation, churn
        re-add) into the Shockwave planner so the next replan solves for
        the fleet that actually exists. Called on removal and by the
        fault applier after churn re-adds — NOT on ordinary initial
        registration, which must stay bit-identical to the configured
        ``num_gpus`` semantics."""
        if self._shockwave is None:
            return
        if self._shockwave_is_pool_set():
            for wt in list(self._shockwave.children):
                count = self._cluster_spec.get(wt, 0)
                if count > 0 and count != self._shockwave.pools.get(wt):
                    self._shockwave.set_pool_capacity(wt, count)
            return
        try:
            pool_type = self._shockwave_pool_type()
        except ValueError:
            return
        count = self._cluster_spec.get(pool_type, 0)
        if count > 0:
            self._shockwave.set_capacity(count)

    # ------------------------------------------------------------------
    # Fault application (simulation path; physical mode detects real
    # worker death via heartbeat expiry in core/physical.py).
    # ------------------------------------------------------------------
    def _apply_cluster_fault_events(
        self, injector, running_jobs, queued_jobs=None
    ) -> None:
        """Apply every due churn/reclaim event from the armed fault plan
        at this round boundary. Crashed or reclaimed workers take their
        running micro-tasks down with them: each affected task is
        force-completed with zero steps (``fault=True``, so the job is
        not charged a failed attempt), the job stays in the table for
        re-placement, capacity shrinks, and the planner is flagged to
        replan. ``scheduler_crash`` / ``scheduler_restart`` events kill
        the brain itself: in simulation both round-trip the FULL
        control-plane state through the HA journal codec (capture ->
        JSON -> restore, the exact on-disk transformation a failover
        replays) and the run must continue bit-identically. Every
        applied event is paired with a recovery record in the flight
        recorder."""
        from shockwave_tpu.runtime import faults as faults_mod

        recorder = obs.get_recorder()
        for event in injector.due_cluster_events(self._current_timestamp):
            now = self._current_timestamp
            obs.counter(
                "fault_injected_total",
                "fault events delivered by the injector",
            ).inc(kind=event.kind)
            if event.kind in faults_mod.SCHEDULER_KINDS:
                detail = self._sim_scheduler_restart_roundtrip(
                    running_jobs, queued_jobs
                )
                how = (
                    "journal_state_restored"
                    if detail.get("roundtrip_exact")
                    else "journal_state_restored_INEXACT"
                )
            elif event.kind == "worker_add":
                capacity = sum(self._cluster_spec.values())
                count = event.count
                if injector.plan.max_capacity is not None:
                    count = min(
                        count, max(injector.plan.max_capacity - capacity, 0)
                    )
                worker_type = event.worker_type or self._worker_types[0]
                added = []
                for _ in range(count):
                    added.extend(
                        self.register_worker(worker_type, num_gpus=1)
                    )
                self._sync_planner_capacity()
                if added:
                    obs.counter(
                        "scheduler_capacity_adds_total",
                        "workers restored by churn/spot re-add events",
                    ).inc(len(added))
                detail = {"added_workers": added}
                how = "capacity_restored"
            else:  # worker_crash / capacity_reclaim
                victims = faults_mod.select_victims(
                    injector.plan, event, self._worker_id_to_worker_type
                )
                requeued = self._crash_workers(victims, running_jobs, now)
                if victims:
                    obs.counter(
                        "scheduler_worker_deaths_total",
                        "workers lost to crash or capacity reclamation",
                    ).inc(len(victims), kind=event.kind)
                detail = {
                    "workers": victims,
                    "requeued": [str(k) for k in requeued],
                }
                how = "requeued_and_replanned"
            obs.instant(
                "fault", cat="fault", tid="faults",
                args={"fault_id": event.event_id, "kind": event.kind,
                      **{k: str(v) for k, v in detail.items()}},
            )
            record = {
                "fault_id": event.event_id,
                "kind": event.kind,
                "round": self._num_completed_rounds,
                "time": now,
                **detail,
            }
            if recorder.enabled:
                recorder.record_fault(record)
                recorder.record_recovery({**record, "how": how})
            injector.mark_applied(event, **detail)
            injector.mark_recovered(event.event_id, how=how, **detail)

    def _crash_workers(self, victims, running_jobs, now) -> list:
        """Kill ``victims`` mid-simulation: force-complete every running
        micro-task holding one of them with zero progress (the round's
        work since the last checkpoint is lost — the realistic cost of
        a crash), then unregister the workers. Returns the requeued job
        keys."""
        victim_set = set(victims)
        requeued = []
        if not victim_set:
            return requeued
        survivors = []
        while running_jobs:
            entry = heapq.heappop(running_jobs)
            _, job_id, worker_ids, _, round_start = entry
            if victim_set & set(worker_ids):
                elapsed = max(now - round_start, 0.0)
                n = len(job_id.singletons())
                for wid in worker_ids:
                    self._done_callback(
                        job_id, wid, [0] * n, [elapsed] * n, fault=True
                    )
                requeued.append(job_id)
                self._num_preemptions += 1
                obs.counter(
                    "scheduler_preemptions_total",
                    "still-active jobs that lost their workers "
                    "at a round boundary",
                ).inc()
            else:
                survivors.append(entry)
        for entry in survivors:
            heapq.heappush(running_jobs, entry)
        for worker_id in victims:
            self.remove_worker(worker_id)
        return requeued

    # ------------------------------------------------------------------
    # Job lifecycle.
    # ------------------------------------------------------------------
    def add_job(self, job: Job, timestamp: Optional[float] = None) -> JobId:
        """(reference: scheduler.py:537-619)"""
        job_id = JobId(self._job_id_counter)
        self._job_id_counter += 1
        job.job_id = job_id.integer
        self._jobs[job_id] = job
        self._steps_run_so_far[job_id] = {}
        self._job_time_so_far[job_id] = {}
        self._job_cost_so_far[job_id] = 0.0
        self._job_total_run_time[job_id] = 0.0
        self._job_timelines[job_id] = [[] for _ in range(job.scale_factor)]
        self._throughputs[job_id] = {}
        self._original_bs[job_id] = job.batch_size
        self._num_jobs_in_trace += 1
        job_type_key = job.job_type_key()
        self._job_id_to_job_type[job_id] = job_type_key
        self._job_type_to_job_ids.setdefault(job_type_key, set()).add(job_id)
        if self._estimate_throughputs and job.scale_factor == 1:
            # Profile the unseen job against the reference types and match
            # it (reference: scheduler.py:573-575).
            self._reference_job_map[job_id] = (
                self._throughput_estimator.match_job_to_reference_job(
                    job_type_key
                )
            )
        self._num_failures_per_job[job_id] = 0
        self._total_steps_run[job_id] = 0
        if self._slos is not None and job.SLO is not None and job.duration:
            # Deadline = SLO factor x isolated duration, from submission.
            self._slos[job_id] = (
                job.SLO * job.duration + self.get_current_timestamp()
            )
        for worker_type in self._worker_types:
            self._steps_run_so_far[job_id][worker_type] = 0
            self._set_initial_throughput(job_id, worker_type)
            if self._job_packing:
                self._populate_job_combination_metadata(job_id, worker_type)
            self._job_time_so_far[job_id][worker_type] = (
                self._time_per_iteration / 2.0
            )
        self._per_job_latest_timestamps[job_id] = None
        self._add_to_priorities(job_id)
        self._need_to_update_allocation = True
        self._bs_scale[job_id] = None
        if self._shockwave is not None:
            if (
                job_id.integer not in self._profiles
                and self._oracle_throughputs is not None
            ):
                # Streaming admission: jobs arriving through the front
                # door carry no pre-computed profile (the static-trace
                # drivers synthesized the whole table up front) — derive
                # one from the throughput oracle at admission, the same
                # math synthesize_profiles applies to a static trace.
                from shockwave_tpu.data.profiles import synthesize_profile

                worker_type = (
                    self._worker_types[0] if self._worker_types else "v100"
                )
                self._profiles[job_id.integer] = synthesize_profile(
                    job, self._oracle_throughputs, worker_type
                )
            self._maybe_upgrade_shockwave_to_pools()
            pool_kwargs = {}
            if self._shockwave_is_pool_set():
                pool, scale = self._pick_shockwave_pool(
                    job, self._profiles[job_id.integer]
                )
                pool_kwargs = dict(pool=pool, duration_scale=scale)
                self._pool_ftf_scale[job_id.integer] = scale
            self._shockwave.add_job(
                job_id,
                self._profiles[job_id.integer],
                self._time_per_iteration,
                job.scale_factor,
                submit_time=self.get_current_timestamp(),
                overhead_s=resolve_preemption_overhead(
                    self._preemption_overheads, job.job_type
                ),
                **pool_kwargs,
            )
        if timestamp is None:
            timestamp = self.get_current_timestamp()
        self._per_job_start_timestamps[job_id] = timestamp
        self._round_log.append(
            {
                "event": "job",
                "job_id": job_id.integer,
                "arrival": timestamp,
                "job_type": job.job_type,
                "command": job.command,
                "working_directory": job.working_directory,
                "num_steps_arg": job.num_steps_arg,
                "needs_data_dir": job.needs_data_dir,
                "total_steps": job.total_steps,
                "scale_factor": job.scale_factor,
                "mode": job.mode,
                "priority_weight": job.priority_weight,
                "SLO": job.SLO,
                "duration": job.duration,
            }
        )
        obs.counter(
            "scheduler_jobs_admitted_total", "jobs admitted from the trace"
        ).inc()
        obs.gauge(
            "scheduler_queue_depth", "active (incomplete) jobs"
        ).set(len(self._jobs))
        trace_args = {}
        if obs.trace_enabled():
            from shockwave_tpu.obs import propagate

            # Adopt the submitter's root (front-door jobs carry it on
            # the wire) or mint a fresh one; an unsampled chain traces
            # locally but is never stored/propagated.
            root = propagate.from_wire(getattr(job, "trace_context", ""))
            if root is None:
                root = propagate.new_root()
            if root is not None and root.sampled:
                self._job_trace_ctx[job_id] = root
                trace_args = {
                    "trace_id": root.trace_id,
                    "parent_span_id": root.span_id,
                }
                now = self.get_current_timestamp()
                if now > timestamp:
                    # The admission-queue wait, as its own span under
                    # the job's root (arrival stamp -> admission).
                    wait_ctx = root.child()
                    obs.complete(
                        "queue_wait", ts_s=timestamp, dur_s=now - timestamp,
                        cat="job", tid="jobs",
                        args={"job_id": job_id.integer, **wait_ctx.args()},
                    )
        # ts is the (monotone) scheduler clock, not the arrival stamp: a
        # backlogged admission would otherwise time-travel the track.
        obs.instant(
            "job_admitted", cat="job", tid="jobs",
            ts_s=self.get_current_timestamp(),
            args={"job_id": job_id.integer, "job_type": job.job_type,
                  "scale_factor": job.scale_factor, "arrival_s": timestamp,
                  **trace_args},
        )
        self._logger.info("[Job dispatched]\tJob ID: %s", job_id)
        return job_id

    def _remove_job(self, job_id: JobId) -> None:
        """(reference: scheduler.py:627-705)"""
        if isinstance(job_id, int):
            job_id = JobId(job_id)
        self._completed_jobs.add(job_id)
        duration = (
            self._per_job_latest_timestamps[job_id]
            - self._per_job_start_timestamps[job_id]
        )
        self._job_priority_weights[job_id] = self._jobs[job_id].priority_weight
        del self._jobs[job_id]
        if self._num_failures_per_job[job_id] >= MAX_FAILED_ATTEMPTS:
            self._job_completion_times[job_id] = None
        else:
            self._job_completion_times[job_id] = duration
        self._round_log.append(
            {
                "event": "complete",
                "job_id": job_id.integer,
                "time": self.get_current_timestamp(),
                "duration": self._job_completion_times[job_id],
            }
        )
        if obs.enabled():
            self._record_completion_telemetry(
                job_id, self._job_completion_times[job_id]
            )
        calibration = obs.get_calibration()
        if calibration.enabled:
            if self._job_completion_times[job_id] is not None:
                calibration.record_outcome(
                    job_id.integer,
                    self._job_total_run_time.get(job_id, 0.0),
                )
            else:
                # A job dropped after repeated failures never realized
                # its remaining runtime; its forecasts are unjudgeable.
                calibration.discard(job_id.integer)
        job_type_key = self._job_id_to_job_type[job_id]
        self._job_type_to_job_ids[job_type_key].discard(job_id)
        del self._steps_run_so_far[job_id]
        del self._job_time_so_far[job_id]
        del self._throughputs[job_id]
        del self._job_id_to_job_type[job_id]
        del self._num_failures_per_job[job_id]
        self._in_progress_updates.pop(job_id, None)
        # Deadlines are kept after completion for get_num_SLO_violations
        # (the active-jobs policy path filters on ``job_id in self._jobs``).
        if self._job_packing:
            stale_pairs = [
                other
                for other in self._throughputs
                if other.is_pair and job_id.overlaps_with(other)
            ]
            for other in stale_pairs:
                del self._throughputs[other]
                self._job_time_so_far.pop(other, None)
                self._in_progress_updates.pop(other, None)
            if not self._job_type_to_job_ids[job_type_key]:
                del self._job_type_to_job_ids[job_type_key]
        self._remove_from_priorities(job_id)
        self._need_to_update_allocation = True
        self._job_trace_ctx.pop(job_id, None)
        self._logger.info("Remaining active jobs: %d", len(self._jobs))

    def _record_completion_telemetry(self, job_id: JobId, duration) -> None:
        """Per-job completion series: JCT and finish-time fairness (rho =
        JCT / (isolated duration x contention), the live-run counterpart
        of get_finish_time_fairness, using the population seen so far)."""
        now = self.get_current_timestamp()
        obs.counter(
            "scheduler_jobs_completed_total", "jobs run to completion"
        ).inc()
        obs.gauge(
            "scheduler_queue_depth", "active (incomplete) jobs"
        ).set(len(self._jobs))
        args = {"job_id": job_id.integer}
        root = self._job_trace_ctx.get(job_id)
        if root is not None:
            args["trace_id"] = root.trace_id
            args["parent_span_id"] = root.span_id
        if duration is not None:
            obs.histogram(
                "scheduler_job_jct_seconds", "per-job completion time"
            ).observe(duration)
            args["jct_s"] = round(duration, 3)
            ftf = self._finish_time_rho(job_id, duration)
            if ftf is not None:
                obs.histogram(
                    "scheduler_job_ftf",
                    "finish-time fairness rho at completion",
                ).observe(ftf)
                args["ftf"] = round(ftf, 3)
        else:
            obs.counter(
                "scheduler_jobs_failed_total",
                "jobs dropped after MAX_FAILED_ATTEMPTS",
            ).inc()
        obs.instant(
            "job_complete", cat="job", tid="jobs", ts_s=now, args=args
        )

    # ------------------------------------------------------------------
    # Throughputs.
    # ------------------------------------------------------------------
    def _set_initial_throughput(self, job_id: JobId, worker_type: str) -> None:
        assert not job_id.is_pair
        if self._oracle_throughputs is not None:
            key = self._jobs[job_id].job_type_key()
            self._throughputs[job_id][worker_type] = self._oracle_throughputs[
                worker_type
            ][key]["null"]
        else:
            self._throughputs[job_id][worker_type] = DEFAULT_THROUGHPUT

    def _populate_job_combination_metadata(
        self, job_id: JobId, worker_type: str
    ) -> None:
        """Register colocated throughputs for all same-scale pairs involving
        ``job_id`` (reference: scheduler.py:2509-2575)."""
        job = self._jobs[job_id]
        job_type_key = job.job_type_key()
        for other_job_id in self._jobs:
            if other_job_id == job_id:
                continue
            other = self._jobs[other_job_id]
            if job.scale_factor != other.scale_factor:
                continue
            merged = JobId(job_id[0], other_job_id[0])
            if merged not in self._throughputs:
                self._throughputs[merged] = {}
                self._job_time_so_far[merged] = {}
            self._job_time_so_far[merged][worker_type] = 0.0
            oracle = (
                self._oracle_throughputs[worker_type]
                if self._oracle_throughputs is not None
                else None
            )
            other_key = other.job_type_key()
            if (
                self._estimate_throughputs
                and job_id in self._reference_job_map
                and other_job_id in self._reference_job_map
            ):
                # Estimated pair throughput: the matched reference types'
                # normalized colocation fractions scaled by the jobs' own
                # isolated throughputs (reference: scheduler.py:2531-2555).
                refs = [
                    self._reference_job_map[job_id],
                    self._reference_job_map[other_job_id],
                ]
                isolated = [
                    oracle[job_type_key]["null"],
                    oracle[other_key]["null"],
                ]
                ref_oracle = self._reference_throughputs[worker_type]
                if job_id < other_job_id:
                    fractions = ref_oracle[refs[0]][refs[1]]
                else:
                    fractions = ref_oracle[refs[1]][refs[0]]
                    isolated = isolated[::-1]
                self._throughputs[merged][worker_type] = [
                    f * t for f, t in zip(fractions, isolated)
                ]
            elif oracle is None:
                self._throughputs[merged][worker_type] = [0.0, 0.0]
            else:
                keys = (
                    (job_type_key, other_key)
                    if job_id < other_job_id
                    else (other_key, job_type_key)
                )
                pair_entry = oracle.get(keys[0], {}).get(keys[1])
                self._throughputs[merged][worker_type] = (
                    list(pair_entry) if pair_entry is not None else [0.0, 0.0]
                )

    def _update_throughput(
        self, job_id, worker_type, all_num_steps, all_execution_times
    ) -> None:
        """(reference: scheduler.py:429-498)"""
        if job_id not in self._throughputs:
            return
        if self._shockwave is not None:
            current_round = self._num_completed_rounds
            for i, single in enumerate(job_id.singletons()):
                tput = (
                    0.0
                    if all_execution_times[i] <= 0
                    else all_num_steps[i] / all_execution_times[i]
                )
                if single in self._jobs:
                    self._shockwave.record_round_throughput(
                        single, current_round, tput, self._jobs[single].batch_size
                    )
        if self._simulate and self._estimate_throughputs and job_id.is_pair:
            # Once a pair actually runs, the simulator has "measured" it:
            # replace the estimate with the oracle truth
            # (reference: scheduler.py:450-462).
            if all(s in self._jobs for s in job_id.singletons()):
                oracle = self._oracle_throughputs[worker_type]
                keys = [
                    self._jobs[s].job_type_key() for s in job_id.singletons()
                ]
                self._throughputs[job_id][worker_type] = list(
                    oracle[keys[0]][keys[1]]
                )
            return
        if not self._simulate:
            # EMA update from measured steps (physical mode).
            singles = job_id.singletons()
            old = self._throughputs[job_id][worker_type]
            old_list = list(old) if job_id.is_pair else [old]
            new_list = []
            for i in range(len(singles)):
                measured = (
                    0.0
                    if all_execution_times[i] <= 0
                    else all_num_steps[i] / all_execution_times[i]
                )
                if old_list[i] != INFINITY:
                    measured = EMA_ALPHA * measured + (1 - EMA_ALPHA) * old_list[i]
                new_list.append(measured)
            if np.min(all_execution_times) <= 0 and job_id.is_pair:
                new_list = [0.0, 0.0]
            self._throughputs[job_id][worker_type] = (
                new_list if job_id.is_pair else new_list[0]
            )

    def _get_remaining_steps(self, job_id: JobId) -> int:
        return self._jobs[job_id].total_steps - self._total_steps_run[job_id]

    # ------------------------------------------------------------------
    # Priorities / allocation.
    # ------------------------------------------------------------------
    def _add_to_priorities(self, job_id: JobId) -> None:
        for worker_type in self._worker_types:
            self._priorities[worker_type][job_id] = 0.0
            self._deficits[worker_type][job_id] = 0.0
            for other in self._throughputs:
                if other.is_pair and job_id.overlaps_with(other):
                    self._priorities[worker_type][other] = 0.0
                    self._deficits[worker_type][other] = 0.0

    def _remove_from_priorities(self, job_id: JobId) -> None:
        # Drop the job itself plus any packed pair containing it
        # (reference: scheduler.py:2667-2682).
        for worker_type in self._worker_types:
            stale = [
                other
                for other in self._priorities[worker_type]
                if job_id.overlaps_with(other)
            ]
            for other in stale:
                self._priorities[worker_type].pop(other, None)
                self._deficits[worker_type].pop(other, None)

    def _get_allocation_state(self) -> dict:
        throughputs = {}
        scale_factors = {}
        priority_weights = {}
        times_since_start = {}
        num_steps_remaining = {}
        for job_id, per_type in self._throughputs.items():
            singles = job_id.singletons()
            if not all(s in self._jobs for s in singles):
                continue
            throughputs[job_id] = dict(per_type)
            for s in singles:
                scale_factors[s] = self._jobs[s].scale_factor
                priority_weights[s] = self._jobs[s].priority_weight
                times_since_start[s] = self.get_current_timestamp() - (
                    self._per_job_start_timestamps.get(s, 0.0)
                )
                num_steps_remaining[s] = self._get_remaining_steps(s)
        return {
            "throughputs": throughputs,
            "scale_factors": scale_factors,
            "priority_weights": priority_weights,
            "times_since_start": times_since_start,
            "num_steps_remaining": num_steps_remaining,
            "cluster_spec": dict(self._cluster_spec),
        }

    def _compute_allocation(self) -> Dict[JobId, Dict[str, float]]:
        """Dispatch to the policy with the signature its family expects
        (reference: scheduler.py:2386-2466)."""
        state = self._get_allocation_state()
        name = self._policy.name
        throughputs = state["throughputs"]
        scale_factors = state["scale_factors"]
        cluster_spec = state["cluster_spec"]
        if not throughputs or not cluster_spec:
            return {}
        if name == "AlloX_Perf":
            allocation = self._policy.get_allocation(
                throughputs,
                scale_factors,
                state["times_since_start"],
                state["num_steps_remaining"],
                cluster_spec,
            )
        elif name.startswith("FinishTimeFairness"):
            allocation = self._policy.get_allocation(
                throughputs,
                scale_factors,
                state["priority_weights"],
                state["times_since_start"],
                state["num_steps_remaining"],
                cluster_spec,
            )
        elif name == "Isolated":
            allocation = self._policy.get_allocation(
                throughputs, scale_factors, cluster_spec
            )
        elif name.startswith("MaxMinFairness"):
            allocation = self._policy.get_allocation(
                throughputs, scale_factors, state["priority_weights"], cluster_spec
            )
        elif name.startswith("MinTotalDuration"):
            allocation = self._policy.get_allocation(
                throughputs, scale_factors, state["num_steps_remaining"], cluster_spec
            )
        elif "SLO" in name:
            # Policies consume time-remaining-to-deadline
            # (reference: scheduler.py:2373-2377).
            now = self.get_current_timestamp()
            slos_remaining = {
                job_id: max(deadline - now, 1e-3)
                for job_id, deadline in (self._slos or {}).items()
                if job_id in self._jobs
            }
            allocation = self._policy.get_allocation(
                throughputs,
                scale_factors,
                cluster_spec,
                SLOs=slos_remaining,
                num_steps_remaining=state["num_steps_remaining"],
            )
        else:
            allocation = self._policy.get_allocation(
                throughputs, scale_factors, cluster_spec
            )
        return allocation or {}

    def _reset_time_run_so_far(self) -> None:
        """(reference: scheduler.py:2589-2637)"""
        current_time = self.get_current_timestamp()
        elapsed = current_time - self._last_reset_time
        for worker_type in self._worker_types:
            self._worker_time_so_far[worker_type] = 0.0
            for job_id in self._job_time_so_far:
                time_received = self._job_time_so_far[job_id].get(
                    worker_type, self._time_per_iteration / 2.0
                ) - (self._time_per_iteration / 2.0)
                if job_id in self._allocation:
                    should_have = self._allocation[job_id][worker_type] * elapsed
                else:
                    should_have = 0.0
                self._deficits[worker_type].setdefault(job_id, 0.0)
                self._deficits[worker_type][job_id] += should_have - time_received
                self._job_time_so_far[job_id][worker_type] = (
                    self._time_per_iteration / 2.0
                )
                self._worker_time_so_far[worker_type] += (
                    self._time_per_iteration / 2.0
                )
        self._last_reset_time = current_time

    def _update_priorities(self) -> None:
        """(reference: scheduler.py:2684-2800, simulation branch)"""
        current_time = self.get_current_timestamp()
        interval_ok = (
            current_time - self._last_reset_time >= self._min_reset_interval
            or self._last_reset_time == 0
        )
        if self._need_to_update_allocation and interval_ok:
            self._reset_time_run_so_far()
            self._allocation = self._compute_allocation()
            self._need_to_update_allocation = False

        fractions: Dict[str, Dict[JobId, float]] = {}
        for worker_type in self._worker_types:
            fractions[worker_type] = {}
            worker_time = self._worker_time_so_far[worker_type]
            for job_id in self._job_time_so_far:
                if worker_time == 0.0 or worker_type not in self._job_time_so_far[job_id]:
                    fractions[worker_type][job_id] = 0.0
                else:
                    fractions[worker_type][job_id] = (
                        self._job_time_so_far[job_id][worker_type] / worker_time
                    )
            for job_id in self._priorities[worker_type]:
                if job_id not in self._allocation:
                    self._priorities[worker_type][job_id] = 0.0
                    continue
                alloc = self._allocation[job_id][worker_type]
                new_priority = alloc * 1e9
                tput = self._throughputs[job_id][worker_type]
                tput_zero = (
                    (tput[0] == 0 or tput[1] == 0) if job_id.is_pair else tput == 0
                )
                if alloc == 0.0:
                    new_priority = 0.0
                elif tput_zero:
                    new_priority = 0.0
                elif fractions[worker_type][job_id] > 0.0:
                    new_priority = alloc / fractions[worker_type][job_id]
                self._priorities[worker_type][job_id] = new_priority

    # ------------------------------------------------------------------
    # Per-round scheduling.
    # ------------------------------------------------------------------
    def _schedule_jobs_on_workers_helper(
        self, worker_types: List[str]
    ) -> Dict[str, List[Tuple[JobId, int]]]:
        """Greedy selection in sorted priority order
        (reference: scheduler.py:892-989)."""
        already_scheduled: set = set()
        scheduled_jobs: Dict[str, List[Tuple[JobId, int]]] = {
            wt: [] for wt in worker_types
        }
        num_workers_left = {wt: self._cluster_spec[wt] for wt in worker_types}

        entries = []
        for worker_type in worker_types:
            per_type = []
            for job_id in self._priorities[worker_type]:
                allocation = 0.0
                if self._allocation and job_id in self._allocation:
                    allocation = self._allocation[job_id][worker_type]
                per_type.append(
                    (
                        job_id,
                        worker_type,
                        self._priorities[worker_type][job_id],
                        self._deficits[worker_type][job_id],
                        allocation,
                    )
                )
            if not self._enable_global_queue:
                per_type.sort(key=lambda x: (x[2], x[3], x[4]), reverse=True)
            entries += per_type
        if self._enable_global_queue:
            entries.sort(key=lambda x: (x[2], x[3], x[4]), reverse=True)

        for job_id, worker_type, priority, _, _ in entries:
            if num_workers_left[worker_type] == 0:
                continue
            singles = job_id.singletons()
            if any(s in already_scheduled for s in singles):
                continue
            tput = self._throughputs[job_id][worker_type]
            if job_id.is_pair:
                if tput[0] <= 0 or tput[1] <= 0:
                    continue
                sf0 = self._jobs[singles[0]].scale_factor
                sf1 = self._jobs[singles[1]].scale_factor
                if sf0 != sf1:
                    continue
                scale_factor = sf0
            else:
                if tput <= 0:
                    continue
                scale_factor = self._jobs[job_id].scale_factor
            if self._policy.name.startswith("FIFO") and priority <= 0.0:
                continue
            if scale_factor > num_workers_left[worker_type]:
                continue
            num_workers_left[worker_type] -= scale_factor
            for s in singles:
                already_scheduled.add(s)
            scheduled_jobs[worker_type].append((job_id, scale_factor))
        return scheduled_jobs

    def _shockwave_is_pool_set(self) -> bool:
        from shockwave_tpu.policies.shockwave import PoolSetPlanner

        return isinstance(self._shockwave, PoolSetPlanner)

    def _maybe_upgrade_shockwave_to_pools(self) -> None:
        """With ``"hetero_pools": true`` in the shockwave config, a
        heterogeneous cluster swaps the single-pool planner for a
        PoolSetPlanner (one EG plan per worker type) BEFORE any job is
        admitted. BEYOND REFERENCE: the reference plans a homogeneous
        pool only and idles every other worker type (reference
        scheduler.py:991-1014). On the same mixed cluster (120-job
        trace, 8xv100+4xp100+4xk80) the upgrade wins across the board —
        makespan −27%, avg JCT −25%, utilization 0.48 -> 0.93, worst
        FTF 3.90 -> 3.08 with rho judged against per-pool isolated
        baselines (_finish_time_rho). Artifact:
        results/hetero/shockwave_pools.json. Opt-in so golden
        single-pool metrics stay stable by default."""
        from shockwave_tpu.policies.shockwave import (
            PoolSetPlanner,
            ShockwavePlanner,
        )

        if not isinstance(self._shockwave, ShockwavePlanner):
            # A CellPlanner (or pool set already in place) is not
            # upgraded; say so instead of silently ignoring the flag —
            # cells x hetero pools is an unimplemented composition.
            if getattr(self._shockwave, "config", {}).get(
                "hetero_pools", False
            ):
                self._logger.warning(
                    "hetero_pools requested but the planner is %s; "
                    "per-worker-type pools are not composed with it — "
                    "keeping the existing planner",
                    type(self._shockwave).__name__,
                )
            return
        if not self._shockwave.config.get("hetero_pools", False):
            return
        if self._oracle_throughputs is None:
            # Pool assignment needs per-type throughputs; without an
            # oracle the mode would silently degenerate to one pool.
            self._logger.warning(
                "hetero_pools requested but no throughput oracle is "
                "configured; keeping single-pool planning"
            )
            return
        if self._shockwave.num_jobs > 0:
            return
        if len(self._worker_type_to_worker_ids) <= 1:
            return
        # NOTE: the pool set snapshots the cluster here, at first
        # admission — worker types (or capacity) registered later are
        # not planned, matching the reference's static num_gpus
        # assumption; register_worker warns when that happens.
        pools = {
            wt: self._cluster_spec[wt]
            for wt in self._worker_type_to_worker_ids
        }
        self._shockwave = PoolSetPlanner(
            self._shockwave.config, self._shockwave.backend, pools
        )

    def _pick_shockwave_pool(self, job, profile) -> Tuple[str, float]:
        """(pool, duration_scale) for a newly admitted job: among the
        pools WIDE ENOUGH for the job's gang, the one with the earliest
        FAIR-SHARE completion estimate — duration (rescaled to the
        pool's speed) x (live incomplete-job population + 1) / capacity.
        The population is recomputed from planner state, so an
        uncontended cluster routes everything to the fastest pool and
        drained pools come straight back instead of carrying historical
        totals. duration_scale rebases the job's profile durations to
        the chosen pool's measured speed; the type they were
        synthesized against comes from the shockwave config's
        "profile_base_type" when set (fallback: v100 if present, else
        the first registered type)."""
        base_type = self._shockwave.config.get("profile_base_type") or (
            "v100" if "v100" in self._worker_type_to_worker_ids
            else next(iter(self._worker_type_to_worker_ids))
        )
        key = job.job_type_key()

        def tput(wt):
            try:
                return float(self._oracle_throughputs[wt][key]["null"])
            except (KeyError, TypeError):
                return 0.0

        base_tput = max(tput(base_type), 1e-9)
        duration = float(sum(profile.get("duration_every_epoch", ())))
        best_wt, best_finish = None, float("inf")
        widest_wt = max(
            self._shockwave.pools, key=lambda wt: self._shockwave.pools[wt]
        )
        for wt, capacity in self._shockwave.pools.items():
            if capacity < job.scale_factor:
                continue  # a gang the pool can never place
            t = tput(wt)
            if t <= 0:
                continue
            scale_wt = base_tput / t
            # Fair-share completion estimate: the scheduler gives each
            # of the pool's incomplete jobs ~capacity/N chips, so this
            # job's expected completion is duration x (N+1) / capacity
            # (all in pool-speed seconds). Uncontended -> fastest pool;
            # deep fair-share dilution -> slow pools absorb overflow.
            population = self._shockwave.pool_incomplete_jobs(wt)
            finish = duration * scale_wt * (population + 1) / max(capacity, 1)
            if finish < best_finish:
                best_wt, best_finish = wt, finish
        if best_wt is None:
            # No pool fits (or has throughput): the widest pool at least
            # mirrors the homogeneous-cluster semantics for an
            # unschedulable gang instead of wedging a random pool. Keep
            # the durations unscaled — a huge base/0-throughput ratio
            # would poison the pool's FTF priorities for every job.
            return widest_wt, 1.0
        scale = base_tput / max(tput(best_wt), 1e-9)
        return best_wt, scale

    def _shockwave_pool_type(self) -> str:
        """The homogeneous pool the Shockwave planner plans onto
        (reference: v100-only by design, scheduler.py:991-1014; here
        generalized to v100 when present, else the cluster's sole
        worker type)."""
        if "v100" in self._worker_type_to_worker_ids:
            return "v100"
        types = list(self._worker_type_to_worker_ids)
        if len(types) == 1:
            return types[0]
        # Silently planning onto an absent pool would end the
        # simulation with zero work (empty schedule == done).
        raise ValueError(
            "Shockwave plans a homogeneous pool: need a 'v100' "
            f"pool or a single worker type, got {types}"
        )

    def _shockwave_schedule_helper(self) -> Dict[str, List[Tuple[JobId, int]]]:
        """Pull this round's job list from the Shockwave planner
        (reference: scheduler.py:991-1014). With a PoolSetPlanner every
        worker-type pool contributes its own planned round."""
        if self._shockwave_is_pool_set():
            by_pool = self._shockwave.current_round_schedule_by_pool()
            self._current_round_scheduled_jobs = [
                j for schedule in by_pool.values() for j in schedule
            ]
            return {
                wt: [
                    (j, self._jobs[j].scale_factor)
                    for j in schedule
                    if j in self._jobs
                ]
                for wt, schedule in by_pool.items()
            }
        worker_type = self._shockwave_pool_type()
        scheduled: Dict[str, List[Tuple[JobId, int]]] = {worker_type: []}
        self._current_round_scheduled_jobs = self._shockwave.current_round_schedule()
        for job_id in self._current_round_scheduled_jobs:
            if job_id in self._jobs:
                scheduled[worker_type].append(
                    (job_id, self._jobs[job_id].scale_factor)
                )
        return scheduled

    def _assign_workers_to_job(
        self, job_id, scale_factor, worker_state, worker_assignments
    ) -> None:
        """Strided server-local placement (reference: scheduler.py:838-889)."""
        worker_ids = worker_state["worker_ids"]
        assigned = worker_state["assigned_worker_ids"]
        ptr = worker_state["server_id_ptr"]
        ids_for_job = list(worker_assignments.get(job_id, ()))
        while len(ids_for_job) < scale_factor and ptr < len(worker_ids):
            if not worker_ids[ptr]:
                ptr += 1
                continue
            candidate = worker_ids[ptr][0]
            if candidate not in assigned:
                ids_for_job.append(candidate)
                assigned.add(candidate)
            worker_ids[ptr].pop(0)
        if len(ids_for_job) != scale_factor:
            raise RuntimeError(f"Could not assign workers to job {job_id}")
        worker_assignments[job_id] = tuple(ids_for_job)
        worker_state["server_id_ptr"] = ptr
        for single in job_id.singletons():
            if self._simulate:
                self._per_job_latest_timestamps[single] = self.get_current_timestamp()
                self._running_jobs.add(single)

    def _schedule_jobs_on_workers(self) -> "OrderedDict[JobId, tuple]":
        """(reference: scheduler.py:1017-1129)"""
        if not self._is_shockwave:
            self._update_priorities()

        # The reference's fixed goodness order for its GPU vocabulary;
        # any other worker types (e.g. measured "tpu_v5e" oracles) come
        # after, alphabetically — not silently unschedulable.
        known = ["v100", "p100", "k80"]
        worker_types = [
            wt for wt in known if wt in self._worker_type_to_worker_ids
        ] + sorted(
            wt for wt in self._worker_type_to_worker_ids if wt not in known
        )
        if "Perf" not in self._policy.name and "Packing" not in self._policy.name:
            self._worker_type_shuffler.shuffle(worker_types)

        if self._is_shockwave:
            scheduled_jobs = self._shockwave_schedule_helper()
            worker_types = [wt for wt in worker_types if wt in scheduled_jobs]
        else:
            scheduled_jobs = self._schedule_jobs_on_workers_helper(worker_types)

        new_assignments: "OrderedDict[JobId, tuple]" = OrderedDict()
        worker_state = {}
        for worker_type in worker_types:
            scheduled_jobs[worker_type].sort(key=lambda x: x[1], reverse=True)
            worker_state[worker_type] = {
                "worker_ids": copy.deepcopy(
                    self._worker_type_to_worker_ids[worker_type]
                ),
                "assigned_worker_ids": set(),
                "server_id_ptr": 0,
            }

        prev_worker_types = {
            job_id: self._worker_id_to_worker_type[ids[0]]
            for job_id, ids in self._current_worker_assignments.items()
        }

        for worker_type in worker_types:
            state = worker_state[worker_type]
            assigned = state["assigned_worker_ids"]
            scale_factors = sorted(
                {sf for _, sf in scheduled_jobs[worker_type]}, reverse=True
            )
            for current_sf in scale_factors:
                # First pass: keep jobs on their previous workers if intact.
                for job_id, sf in scheduled_jobs[worker_type]:
                    if sf != current_sf:
                        continue
                    if prev_worker_types.get(job_id) != worker_type:
                        continue
                    prev_ids = self._current_worker_assignments[job_id]
                    if any(wid in assigned for wid in prev_ids):
                        continue
                    new_assignments[job_id] = prev_ids
                    assigned.update(prev_ids)
                # Second pass: everyone else, strided.
                for job_id, sf in scheduled_jobs[worker_type]:
                    if sf != current_sf:
                        continue
                    if not self._is_shockwave and job_id not in self._allocation:
                        continue
                    self._assign_workers_to_job(
                        job_id, sf, state, new_assignments
                    )

        counts: Dict[int, int] = {}
        for ids in new_assignments.values():
            for wid in ids:
                counts[wid] = counts.get(wid, 0) + 1
                if counts[wid] > 1:
                    raise RuntimeError(f"Worker {wid} assigned twice")
        return new_assignments

    # ------------------------------------------------------------------
    # Micro-task accounting.
    # ------------------------------------------------------------------
    def _get_num_steps(self, job_id, worker_type, single_job_id=None) -> int:
        """(reference: scheduler.py:1131-1165)"""
        if self._simulate and job_id.is_pair:
            assert single_job_id is not None
            oracle = self._oracle_throughputs[worker_type]
            index = job_id.as_tuple().index(single_job_id[0])
            sf = self._jobs[single_job_id].scale_factor
            keys = [(self._jobs[s].job_type, sf) for s in job_id.singletons()]
            tput = oracle[keys[0]][keys[1]][index]
            num_steps = int(tput * self._time_per_iteration)
        else:
            tput = self._throughputs[job_id][worker_type]
            if job_id.is_pair:
                index = job_id.as_tuple().index(single_job_id[0])
                tput = tput[index]
            num_steps = int(tput * self._time_per_iteration)
        target = single_job_id if single_job_id is not None else job_id
        return min(num_steps, self._get_remaining_steps(target))

    def _get_job_steps_and_finish_times(self, job_id, worker_type):
        """(reference: scheduler.py:1166-1212)"""
        max_finish_time = self.get_current_timestamp()
        all_num_steps = []
        true_pair_tputs = None
        if self._simulate and self._estimate_throughputs and job_id.is_pair:
            # Execution runs at the ORACLE rate even when the allocator
            # only saw estimates (reference: scheduler.py:1173-1184).
            oracle = self._oracle_throughputs[worker_type]
            keys = [self._jobs[s].job_type_key() for s in job_id.singletons()]
            true_pair_tputs = oracle[keys[0]][keys[1]]
        for i, single in enumerate(job_id.singletons()):
            num_steps = self._get_num_steps(job_id, worker_type, single)
            all_num_steps.append(num_steps)
            if true_pair_tputs is not None:
                tput = true_pair_tputs[i]
            else:
                tput = self._throughputs[job_id][worker_type]
                if job_id.is_pair:
                    tput = tput[i]
            if tput <= 0:
                raise RuntimeError(
                    f"Throughput for job {single} on {worker_type} is <= 0"
                )
            finish_time = self.get_current_timestamp() + num_steps / tput
            max_finish_time = max(max_finish_time, finish_time)
            self._running_jobs.add(single)
        return all_num_steps, max_finish_time

    def _micro_task_scale_factor(self, job_id) -> int:
        """Gang size of the micro-task being merged. Physical mode overrides
        this with the dispatch-time record, since assignments may have
        rotated by the time a Done report arrives."""
        return len(self._current_worker_assignments[job_id])

    def _done_callback(
        self, job_id, worker_id, all_num_steps, all_execution_times,
        fault: bool = False,
    ) -> None:
        """Merge per-worker completions for a micro-task; update steps, time
        and batch-size adaptation; remove finished jobs
        (reference: scheduler.py:3223-3482, simulation-relevant paths).

        ``fault=True`` marks a completion synthesized because the WORKER
        died under the job (crash, reclamation, heartbeat expiry): the
        zero-progress report then does not count toward the job's
        MAX_FAILED_ATTEMPTS — penalizing a job for its host's death
        would let sustained churn evict healthy jobs."""
        to_remove: List[JobId] = []
        worker_type = self._worker_id_to_worker_type[worker_id]
        self._available_worker_ids.add(worker_id)
        is_active = {s: s in self._jobs for s in job_id.singletons()}
        if not any(is_active.values()):
            return

        scale_factor = self._micro_task_scale_factor(job_id)
        updates = self._in_progress_updates.setdefault(job_id, [])
        updates.append((worker_id, all_num_steps, all_execution_times))
        if fault:
            # The taint must survive partial gang merges: when rank A's
            # completion is synthesized for a dead worker but rank B
            # reports normally LATER, B's call completes the merge with
            # fault=False and would charge the job a failed attempt for
            # its host's death.
            self._fault_tainted.add(job_id)
        if len(updates) < scale_factor:
            return
        fault = fault or job_id in self._fault_tainted
        self._fault_tainted.discard(job_id)
        updates.sort(key=lambda x: x[0])
        micro_task_succeeded = True
        merged_steps = [0] * len(job_id.singletons())
        merged_times = [0.0] * len(job_id.singletons())
        for _, steps_i, times_i in updates:
            for j, single in enumerate(job_id.singletons()):
                if (
                    not self._simulate
                    and is_active[single]
                    and (steps_i[j] <= 0 or times_i[j] <= 0)
                ):
                    # Physical mode: any worker reporting no progress means
                    # the micro-task failed (reference: scheduler.py:3326-3328).
                    micro_task_succeeded = False
                merged_steps[j] += steps_i[j]
                merged_times[j] = max(merged_times[j], times_i[j])
        if self._simulate:
            # In simulation a gang's steps are split across workers and the
            # final sliver of a job can be smaller than its gang size, which
            # leaves some workers with 0 steps; judge success on the merged
            # totals instead of per-worker shares.
            for j, single in enumerate(job_id.singletons()):
                if is_active[single] and (
                    merged_steps[j] <= 0 or merged_times[j] <= 0
                ):
                    micro_task_succeeded = False
        self._in_progress_updates[job_id] = []

        if not micro_task_succeeded:
            self._logger.info("[Micro-task failed]\tJob ID: %s", job_id)
            if not fault and not job_id.is_pair and is_active[job_id]:
                self._num_failures_per_job[job_id] += 1
                if self._num_failures_per_job[job_id] >= MAX_FAILED_ATTEMPTS:
                    to_remove.append(job_id)
            self._need_to_update_allocation = True
        else:
            for single, num_steps, execution_time in zip(
                job_id.singletons(), merged_steps, merged_times
            ):
                if not is_active[single]:
                    continue
                if self._per_worker_type_prices is not None:
                    from shockwave_tpu.data.spot_prices import latest_price

                    self._job_cost_so_far[single] += (
                        latest_price(
                            self._per_worker_type_prices,
                            worker_type,
                            self.get_current_timestamp(),
                        )
                        * execution_time
                        / 3600.0
                        * scale_factor
                    )
                if single in self._running_jobs:
                    self._running_jobs.remove(single)
                    self._steps_run_so_far[single][worker_type] += num_steps
                    self._total_steps_run[single] += num_steps
                    self._job_total_run_time[single] = (
                        self._job_total_run_time.get(single, 0.0)
                        + execution_time
                    )
                    if self._get_remaining_steps(single) <= 0:
                        to_remove.append(single)
            max_execution_time = max(merged_times)
            if job_id in self._job_time_so_far:
                self._job_time_so_far[job_id][worker_type] += max_execution_time
                self._worker_time_so_far[worker_type] += max_execution_time
            for wid, _, _ in updates:
                self._cumulative_worker_time_so_far[wid] += max_execution_time

        self._update_throughput(job_id, worker_type, merged_steps, merged_times)

        for single in job_id.singletons():
            self._scale_bs_and_iters(single)
            self._bs_scale[single] = None

        for single in to_remove:
            self._remove_job(single)
            if self._shockwave is not None:
                self._shockwave.remove_job(single)

    # ------------------------------------------------------------------
    # Batch-size adaptation (simulation).
    # ------------------------------------------------------------------
    def _simulate_gns(self, job_id: JobId) -> None:
        """(reference: scheduler.py:1308-1334)"""
        from shockwave_tpu.data import bs_patterns

        job = self._jobs[job_id]
        model = job.model
        batch_size = job.batch_size
        current_epoch = epochs_for_steps(
            model, batch_size, self._total_steps_run[job_id]
        )
        pattern = bs_patterns.gns_pattern(
            job.job_type,
            self._original_bs[job_id],
            max(760, current_epoch + 2),
            job.scale_factor,
        )
        if (
            pattern[current_epoch + 1] > batch_size
            or pattern[current_epoch] > batch_size
        ):
            if MAX_BATCH_SIZES.get(model) != batch_size:
                self._bs_scale[job_id] = BS_BIG

    def _simulate_accordion(self, job_id: JobId) -> None:
        """(reference: scheduler.py:1336-1363)"""
        from shockwave_tpu.data import bs_patterns

        job = self._jobs[job_id]
        model = job.model
        if model == "Transformer":
            return
        batch_size = job.batch_size
        original = self._original_bs[job_id]
        current_epoch = epochs_for_steps(
            model, batch_size, self._total_steps_run[job_id]
        )
        in_critical = bs_patterns.accordion_in_critical_regime(
            model, original, current_epoch
        )
        if batch_size == original and not in_critical:
            if MAX_BATCH_SIZES.get(model) != batch_size:
                self._bs_scale[job_id] = BS_BIG
        elif batch_size != original and in_critical:
            from shockwave_tpu.data.workload_info import MIN_BATCH_SIZES

            if MIN_BATCH_SIZES.get(model) != batch_size:
                self._bs_scale[job_id] = BS_SMALL

    def _scale_bs_and_iters(self, job_id: JobId) -> None:
        """Apply a pending batch-size change: rewrite the job's command and
        type, refresh throughputs, and rescale total/completed steps so epoch
        progress is preserved (reference: scheduler.py:3504-3591)."""
        if job_id is None or self._bs_scale.get(job_id) is None:
            return
        assert not job_id.is_pair
        job = self._jobs[job_id]
        old_bs = job.batch_size
        model = job.model
        original = self._original_bs[job_id]
        if MAX_BATCH_SIZES.get(model) == original:
            self._bs_scale[job_id] = None
            return
        if job.mode == "gns":
            assert self._bs_scale[job_id] == BS_BIG
            new_bs = 2 * old_bs
        elif job.mode == "accordion":
            new_bs = (
                MAX_BATCH_SIZES[model]
                if self._bs_scale[job_id] == BS_BIG
                else original
            )
        else:
            new_bs = old_bs
        job.update_batch_size(new_bs)
        for worker_type in self._worker_types:
            key = job.job_type_key()
            if key not in self._oracle_throughputs[worker_type]:
                self._logger.error(
                    "Reverting job %s bs: %s -> %s", job_id, new_bs, old_bs
                )
                self._bs_scale[job_id] = None
                job.update_batch_size(old_bs)
                return
            self._throughputs[job_id][worker_type] = self._oracle_throughputs[
                worker_type
            ][key]["null"]

        total_steps = job.total_steps
        total_steps_run = self._total_steps_run[job_id]
        old_total_epochs = epochs_for_steps(model, old_bs, total_steps)
        new_total_steps = math.ceil(total_steps * old_bs / new_bs)
        if epochs_for_steps(model, new_bs, new_total_steps) != old_total_epochs:
            new_total_steps = total_steps_for_epochs(model, new_bs, old_total_epochs)
        job.total_steps = new_total_steps

        completed_epochs = epochs_for_steps(model, old_bs, total_steps_run)
        new_steps_run = total_steps_for_epochs(model, new_bs, completed_epochs)
        # Rescale each worker type's step history proportionally so per-type
        # accounting stays consistent (the reference rewrites only "v100",
        # scheduler.py:3588-3589, which breaks on non-v100 clusters).
        old_total = self._total_steps_run[job_id]
        for worker_type in self._worker_types:
            old_per_type = self._steps_run_so_far[job_id].get(worker_type, 0)
            if old_total > 0:
                self._steps_run_so_far[job_id][worker_type] = round(
                    old_per_type * new_steps_run / old_total
                )
            else:
                self._steps_run_so_far[job_id][worker_type] = 0
        self._total_steps_run[job_id] = new_steps_run

        self._bs_scale[job_id] = None
        if self._shockwave is not None:
            # Only this job changed shape: a federated planner stales
            # just the cell/pool owning it, not the whole fleet.
            self._shockwave.set_recompute_flag(jobs=[job_id])

    def _round_observability(
        self, assignments, preempted=None
    ) -> None:
        """Per-round taps for the observability planes beyond plain
        metrics: flight-recorder round context, predictor-calibration
        forecasts, and the health watchdog. One enabled-flags check when
        everything is off (the default), so un-instrumented runs pay a
        single branch per round."""
        recorder = obs.get_recorder()
        calibration = obs.get_calibration()
        watchdog = obs.get_watchdog()
        metrics_on = obs.metrics_enabled()
        if not (
            recorder.enabled
            or calibration.enabled
            or watchdog.enabled
            or metrics_on
        ):
            return
        if metrics_on:
            self._publish_tenant_spend()
            # Scale housekeeping: sample tracked families into the
            # ring-buffer history and run the cardinality governor's
            # activity decay — one O(series) pass per round.
            obs.scale_tick(self.get_current_timestamp())
        if not (recorder.enabled or calibration.enabled or watchdog.enabled):
            return
        now = self.get_current_timestamp()
        if recorder.enabled:
            recorder.record_round_context(
                self._num_completed_rounds,
                now,
                assignments=assignments,
                job_steps={
                    j.integer: self._total_steps_run.get(j, 0)
                    for j in self._jobs
                },
                preempted=preempted,
            )
        if calibration.enabled and self._shockwave is not None:
            for j in self._jobs:
                md = self._shockwave.get_metadata(j)
                if md is None or md.completed_epochs >= md.total_epochs:
                    continue
                run_so_far = self._job_total_run_time.get(j, 0.0)
                # Score the now-to-finish forecast (planner horizon math
                # excludes the in-progress epoch; see
                # JobMetadata.remaining_runtime_to_completion), with the
                # credible interval shifted by the same offset. The
                # posterior is evaluated once and threaded through.
                base = md.remaining_runtime()
                predicted = md.remaining_runtime_to_completion(
                    run_so_far, base=base
                )
                lo, hi = md.remaining_runtime_interval(mean=base)
                offset = predicted - base
                calibration.record_forecast(
                    j.integer,
                    run_so_far,
                    predicted,
                    lo + offset,
                    hi + offset,
                    ts_s=now,
                    ape_floor_s=md.mean_epoch_duration(),
                )
        if watchdog.enabled:
            watchdog.check_round(
                self._num_completed_rounds,
                now,
                job_steps={
                    j.integer: self._total_steps_run.get(j, 0)
                    for j in self._jobs
                },
                scheduled=[
                    s.integer
                    for key in assignments
                    for s in key.singletons()
                ],
            )

    def _publish_tenant_spend(self) -> None:
        """Per-tenant spend gauges from the planner's last committed
        replan: ``market_tenant_spend{tenant}`` sums each tenant's
        chip-rounds in the plan (the market's per-job ``spend``
        column). Tenants ride the admission wire
        (admission_pb2.JobSpec.tenant); jobs without one land under
        ``default``. A tenant whose jobs all finished is zeroed, not
        left frozen at its last value. One dict lookup per round when
        the snapshot is unchanged (or the planner isn't the market)."""
        market = getattr(self._shockwave, "last_market", None)
        if market is None or market["round"] == self._tenant_spend_round:
            return
        self._tenant_spend_round = market["round"]
        tenant_by_key = {
            str(j): (job.tenant or "default")
            for j, job in self._jobs.items()
        }
        by_tenant: dict = {}
        for key, spend in zip(market["keys"], market["spend"]):
            tenant = tenant_by_key.get(key)
            if tenant is None:
                continue  # departed since the replan
            by_tenant[tenant] = by_tenant.get(tenant, 0.0) + spend
        # Rollup + top-k: the labeled gauge keeps only the k biggest
        # spenders (a 10k-tenant campaign must not mint 10k series);
        # the fleet totals stay exact in two unlabeled rollups, and the
        # top spenders also ride the exemplars block with real names.
        k = max(1, int(os.environ.get("SHOCKWAVE_OBS_EXEMPLARS", 10)))
        top = dict(
            sorted(by_tenant.items(), key=lambda kv: -kv[1])[:k]
        )
        gauge = obs.gauge(
            "market_tenant_spend",
            "chip-rounds of the last committed plan per tenant "
            "(top spenders only; see market_tenant_spend_total)",
        )
        for tenant in self._tenant_spend_seen - set(top):
            gauge.remove(tenant=tenant)
        for tenant, spend in top.items():
            gauge.set(float(spend), tenant=tenant)
            obs.offer_exemplar(
                "tenant_top_spend",
                tenant,
                float(spend),
                help="tenants with the largest chip-round spend in the "
                "last committed plan",
                spend=round(float(spend), 6),
            )
        obs.gauge(
            "market_tenant_spend_total",
            "chip-rounds of the last committed plan summed over ALL "
            "tenants (exact, unlabeled rollup)",
        ).set(float(sum(by_tenant.values())))
        obs.gauge(
            "market_tenants",
            "tenants with spend in the last committed plan",
        ).set(len(by_tenant))
        self._tenant_spend_seen = set(top)

    # ------------------------------------------------------------------
    # Plan-ahead pipelining (shockwave_tpu/policies/speculation.py).
    # ------------------------------------------------------------------
    def _shockwave_can_speculate(self) -> bool:
        return (
            self._speculate
            and self._shockwave is not None
            and hasattr(self._shockwave, "speculate_next_round")
            and not self._shockwave_is_pool_set()
            and bool(self._current_round_scheduled_jobs)
        )

    def _predict_round_outcome(self, dispatch_preview):
        """The planner delta the scheduler predicts between now (round
        r's micro-tasks just dispatched) and the next round boundary:
        the throughput records the completion merge will append, each
        scheduled job's epoch progress after the boundary's
        ``set_progress`` pass, and the jobs that will finish and leave
        the planner. In simulation the prediction is EXACT — the
        dispatched step counts and finish times below are precisely
        what ``_done_callback`` will merge — so a no-churn speculative
        plan is bit-identical to the serial boundary solve.

        ``dispatch_preview`` maps each dispatched single job to its
        (num_steps, execution_seconds). Returns None when the boundary
        is already known to churn: a dispatched job with a pending
        batch-size switch will have its steps rescaled and the planner
        re-flagged at the merge, so speculating could only buy a
        repair against state this prediction cannot express."""
        steps_map: dict = {}
        for job_id in self._current_round_scheduled_jobs:
            if self._jobs.get(job_id) is None:
                continue
            if (
                self._bs_scale.get(job_id) is not None
                and job_id in dispatch_preview
            ):
                return None
            steps_add, exec_s = dispatch_preview.get(job_id, (0, 0.0))
            steps_map[job_id] = (
                steps_add,
                steps_add / exec_s if exec_s > 0 else 0.0,
            )
        return self._spec_outcome_from_steps(steps_map)

    def _spec_outcome_from_steps(self, steps_map):
        """Shared tail of the sim/physical round-outcome prediction:
        from each scheduled single job's predicted (steps_run,
        throughput) for this round, build the
        :class:`~shockwave_tpu.policies.speculation.SpecOutcome` — the
        throughput records the completion merge will append (stamped
        with the CURRENT completed-round counter, which both modes
        increment at iteration end), each surviving job's epoch
        progress after the boundary's ``set_progress`` pass, and the
        predicted completions. One builder for both modes so the
        outcome shape can never desynchronize sim from physical."""
        from shockwave_tpu.policies.speculation import SpecOutcome

        pool = self._shockwave_pool_type()
        next_round = self._num_completed_rounds
        progress: dict = {}
        throughputs: list = []
        completions: list = []
        for job_id in self._current_round_scheduled_jobs:
            job = self._jobs.get(job_id)
            if job is None:
                continue
            steps_add, tput = steps_map.get(job_id, (0, 0.0))
            if steps_add > 0:
                throughputs.append(
                    (job_id, next_round, tput, job.batch_size)
                )
            if (
                steps_add > 0
                and self._total_steps_run[job_id] + steps_add
                >= job.total_steps
            ):
                completions.append(job_id)
            else:
                steps_after = (
                    self._steps_run_so_far.get(job_id, {}).get(pool, 0)
                    + steps_add
                )
                progress[job_id] = steps_after // steps_per_epoch(
                    job.model, job.batch_size
                )
        return SpecOutcome(
            target_round=self._shockwave.round_index + 1,
            progress=progress,
            throughputs=throughputs,
            completions=completions,
            capacity=self._shockwave.num_gpus,
        )

    def _shockwave_scheduler_update(self) -> None:
        """Push epoch progress into the planner and advance its round
        (reference: scheduler.py:3598-3621)."""
        is_pool_set = self._shockwave_is_pool_set()
        # Lazy: on a multi-type cluster before the first admission the
        # single-pool lookup would raise, but there is nothing to update.
        default_pool = (
            self._shockwave_pool_type()
            if not is_pool_set and self._current_round_scheduled_jobs
            else None
        )
        for job_id in self._current_round_scheduled_jobs:
            if job_id in self._completed_jobs:
                self._shockwave.mark_complete(job_id)
                continue
            pool_type = (
                self._shockwave.pool_of(job_id) if is_pool_set
                else default_pool
            )
            steps_run = self._steps_run_so_far.get(job_id, {}).get(
                pool_type, 0
            )
            if job_id in self._jobs:
                bs = self._jobs[job_id].batch_size
                model = self._jobs[job_id].model
                current_epoch = steps_run // steps_per_epoch(model, bs)
                self._shockwave.set_progress(job_id, current_epoch)
        self._shockwave.increment_round()

    # ------------------------------------------------------------------
    # Simulation.
    # ------------------------------------------------------------------
    def get_current_timestamp(self, in_seconds: bool = False) -> float:
        return self._current_timestamp

    def simulate(
        self,
        cluster_spec: Dict[str, int],
        arrival_times: Optional[List[float]] = None,
        jobs: Optional[List[Job]] = None,
        num_gpus_per_server: Optional[Dict[str, int]] = None,
        jobs_to_complete: Optional[set] = None,
        max_rounds: Optional[int] = None,
        checkpoint_threshold: Optional[int] = None,
        checkpoint_file: Optional[str] = None,
        submitter=None,
        admission_capacity: Optional[int] = None,
        admission_retry_s: Optional[float] = None,
        admission_pricer=None,
    ) -> float:
        """Trace-driven simulation; returns the makespan
        (reference: scheduler.py:1365-1796, from_trace path).

        Checkpointing (reference: scheduler.py:1759-1775): with
        ``checkpoint_threshold`` set, the full scheduler + loop state is
        pickled to ``checkpoint_file`` once that many jobs have been
        admitted; a later ``simulate`` call on a fresh Scheduler with an
        existing ``checkpoint_file`` resumes from that point instead of
        replaying the prefix (used to fast-forward long continuous-trace
        sweeps). Shockwave runs checkpoint their planner state too (plan
        cache, predictor metadata, finish-time history — see
        ShockwavePlanner.state_dict), so fast-forward works with the
        flagship policy; a resumed run's metrics match an unbroken one
        (tests/test_simulator.py::test_checkpoint_resume_shockwave).

        Streaming admission: with ``submitter`` (a
        :class:`shockwave_tpu.runtime.admission.StreamingSubmitter`),
        jobs arrive through the same bounded, token-deduplicated,
        backpressured admission queue the physical SubmitJobs RPC
        feeds, in virtual time — the loop idles through arrival gaps
        and ends when the submitter closed the stream, the queue
        drained, and every admitted job completed. ``arrival_times`` /
        ``jobs`` are then ignored (the submitter carries the trace).
        """
        import os as _os

        from shockwave_tpu.runtime import admission as admission_mod
        from shockwave_tpu.runtime import faults

        # Armed fault injection (chaos runs): churn/reclaim events from
        # the plan are applied at round boundaries below; None — the
        # default — costs one check per round.
        fault_injector = faults.active()

        if submitter is not None:
            if checkpoint_threshold is not None or checkpoint_file is not None:
                # Both directions: saving (the queue/ledger is not part
                # of the checkpoint contract) AND resuming (a restored
                # queued_jobs list would be silently orphaned — the
                # streaming admit path never pops it).
                raise ValueError(
                    "checkpointing is not supported with a streaming "
                    "submitter (the admission queue is not part of the "
                    "checkpoint contract)"
                )
            remaining_jobs = submitter.total_jobs
            queued_jobs: list = []
            # Virtual-time admission queue: the simulator owns the
            # clock, so enqueue/latency stamps ride _current_timestamp.
            # A cell-decomposed planner shards the queue (one slice per
            # cell, coordinator-rebalanced backlog).
            self._admission = admission_mod.build_queue(
                capacity=admission_capacity
                or admission_mod.DEFAULT_CAPACITY,
                retry_delay_s=(
                    admission_retry_s
                    if admission_retry_s is not None
                    else max(1.0, self._time_per_iteration / 4.0)
                ),
                clock=lambda: self._current_timestamp,
                shards=getattr(self._shockwave, "num_cells", 1) or 1,
                # Marginal-price admission (whatif 2-scenario solve):
                # optional, and safe here by construction — in sim the
                # submitter pumps on the round-loop thread, so the
                # pricer's planner-state snapshot never races a replan.
                pricer=admission_pricer,
            )
        else:
            assert arrival_times is not None and jobs is not None
            remaining_jobs = len(jobs)
            queued_jobs = list(zip(arrival_times, jobs))
        running_jobs: list = []
        consecutive_idle_rounds = 0
        checkpoint_saved = False

        for worker_type in sorted(cluster_spec):
            num_gpus = (
                num_gpus_per_server[worker_type]
                if num_gpus_per_server is not None
                else 1
            )
            for _ in range(cluster_spec[worker_type] // num_gpus):
                self.register_worker(worker_type, num_gpus=num_gpus)

        if checkpoint_file is not None and _os.path.exists(checkpoint_file):
            extra = self.load_checkpoint(checkpoint_file)
            queued_jobs = extra["queued_jobs"]
            running_jobs = extra["running_jobs"]
            remaining_jobs = extra["remaining_jobs"]
            consecutive_idle_rounds = extra["consecutive_idle_rounds"]
            checkpoint_saved = True
            self._logger.info(
                "Resumed from checkpoint %s at t=%.1f (%d jobs queued)",
                checkpoint_file, self._current_timestamp, len(queued_jobs),
            )
        elif submitter is not None:
            first = submitter.next_due_time()
            self._current_timestamp = first if first is not None else 0.0
        else:
            self._current_timestamp = arrival_times[0]

        while True:
            # Checkpoint at the loop TOP — the exact control point resume
            # re-enters — so saved state and resumed state are equivalent
            # by construction. (Saving mid-iteration, as the reference
            # does after admissions (reference scheduler.py:1759-1775),
            # diverges on resume: the loop-top clock advance jumps to the
            # next arrival past the round the continuing run schedules at
            # the saved timestamp.)
            if (
                checkpoint_threshold is not None
                and checkpoint_file is not None
                and not checkpoint_saved
                and self._job_id_counter >= checkpoint_threshold
            ):
                self.save_checkpoint(
                    checkpoint_file,
                    extra=dict(
                        queued_jobs=queued_jobs,
                        running_jobs=running_jobs,
                        remaining_jobs=remaining_jobs,
                        consecutive_idle_rounds=consecutive_idle_rounds,
                    ),
                )
                checkpoint_saved = True
                self._logger.info(
                    "Saved checkpoint to %s after job %d",
                    checkpoint_file, self._job_id_counter - 1,
                )
            if jobs_to_complete is not None and jobs_to_complete.issubset(
                self._completed_jobs
            ):
                break
            if remaining_jobs == 0:
                break
            if max_rounds is not None and self._num_completed_rounds >= max_rounds:
                break
            next_job_arrival_time = (
                submitter.next_due_time()
                if submitter is not None
                else (queued_jobs[0][0] if queued_jobs else None)
            )
            if next_job_arrival_time is None and not running_jobs:
                self._last_reset_time = 0

            # Advance the clock to the end of the round (latest micro-task
            # finish) or to the next arrival when idle.
            max_timestamp = 0.0
            if running_jobs and -running_jobs[0][0] > max_timestamp:
                max_timestamp = -running_jobs[0][0]
            if max_timestamp > 0:
                self._current_timestamp = max_timestamp
            elif next_job_arrival_time is not None:
                self._current_timestamp = max(
                    self._current_timestamp, next_job_arrival_time
                )

            # Injected churn lands BEFORE the completion drain: a worker
            # crashed mid-round must take its micro-task's progress down
            # with it, not let the task complete normally first.
            if fault_injector is not None:
                self._apply_cluster_fault_events(
                    fault_injector, running_jobs, queued_jobs=queued_jobs
                )

            # Complete every running micro-task (they all end by round end).
            while running_jobs:
                (
                    finish_time,
                    job_id,
                    worker_ids,
                    all_num_steps,
                    round_start,
                ) = running_jobs[0]
                finish_time = -finish_time
                if finish_time > self._current_timestamp:
                    break
                all_execution_times = []
                for single in job_id.singletons():
                    # Execution time is measured from when this micro-task was
                    # dispatched, not from a global round marker, so idle gaps
                    # between rounds are never billed as work.
                    all_execution_times.append(finish_time - round_start)
                    self._per_job_latest_timestamps[single] = finish_time
                self._in_progress_updates[job_id] = []
                scale_factor = len(worker_ids)
                total_steps = [0] * len(job_id.singletons())
                for i, worker_id in enumerate(worker_ids):
                    if i == len(worker_ids) - 1:
                        worker_steps = [
                            all_num_steps[j] - total_steps[j]
                            for j in range(len(all_num_steps))
                        ]
                    else:
                        worker_steps = [x // scale_factor for x in all_num_steps]
                    for j in range(len(worker_steps)):
                        total_steps[j] += worker_steps[j]
                    self._done_callback(
                        job_id, worker_id, worker_steps, all_execution_times
                    )
                for single in job_id.singletons():
                    if single not in self._jobs:
                        remaining_jobs -= 1
                heapq.heappop(running_jobs)

            # Batch-size adaptation flags for the next completion.
            for job_id in self._jobs:
                if self._jobs[job_id].mode == "accordion":
                    self._simulate_accordion(job_id)
                elif self._jobs[job_id].mode == "gns":
                    self._simulate_gns(job_id)

            if self._shockwave is not None and self._num_completed_rounds >= 1:
                self._shockwave_scheduler_update()

            # Admit arrivals due by now. The streaming path pumps the
            # submitter (batched submits with idempotent tokens,
            # backpressure honored, injected SubmitJobs faults retried)
            # and drains the admission queue; the static path pops the
            # pre-known trace directly.
            if submitter is not None:
                for token, job, enqueued in submitter.pump(
                    self._admission, self._current_timestamp
                ):
                    job_id = self.add_job(
                        job,
                        timestamp=getattr(job, "arrival_time", enqueued),
                    )
                    recorder = obs.get_recorder()
                    if recorder.enabled:
                        recorder.record_admission(
                            {
                                "kind": "admitted",
                                "token": token,
                                "job_id": job_id.integer,
                                "round": self._num_completed_rounds,
                                "time": self._current_timestamp,
                            }
                        )
            else:
                while (
                    queued_jobs
                    and queued_jobs[0][0] <= self._current_timestamp
                ):
                    arrival_time, job = queued_jobs.pop(0)
                    self.add_job(job, timestamp=arrival_time)

            if len(self._jobs) == 0:
                if submitter is not None:
                    if (
                        submitter.exhausted()
                        and self._admission.depth() == 0
                    ):
                        break
                    continue
                if not queued_jobs:
                    break
                continue

            scheduled_jobs = self._schedule_jobs_on_workers()
            if self._is_shockwave and len(scheduled_jobs) == 0:
                break
            stream_pending = submitter is not None and not (
                submitter.exhausted() and self._admission.depth() == 0
            )
            if (
                not scheduled_jobs
                and not running_jobs
                and not queued_jobs
                and not stream_pending
            ):
                # One idle iteration is recoverable: the reset-time trick at
                # the top of the loop forces an allocation recompute next
                # time around. Two in a row is a real deadlock.
                consecutive_idle_rounds += 1
                if consecutive_idle_rounds > 1:
                    raise RuntimeError(
                        "Scheduling deadlock: %d active jobs but nothing "
                        "schedulable" % len(self._jobs)
                    )
            else:
                consecutive_idle_rounds = 0
            preempted_this_round = []
            for job_id in self._current_worker_assignments:
                if any(s in self._jobs for s in job_id.singletons()):
                    self._num_lease_extension_opportunities += 1
                    kept = job_id in scheduled_jobs and set(
                        self._current_worker_assignments[job_id]
                    ) == set(scheduled_jobs[job_id])
                    if not kept:
                        self._num_preemptions += 1
                        preempted_this_round.append(job_id)
                        obs.counter(
                            "scheduler_preemptions_total",
                            "still-active jobs that lost their workers "
                            "at a round boundary",
                        ).inc()
                        obs.instant(
                            "preemption", cat="sched", tid="rounds",
                            args={"job_id": str(job_id)},
                        )
            for job_id in scheduled_jobs:
                if job_id in self._current_worker_assignments and set(
                    self._current_worker_assignments[job_id]
                ) == set(scheduled_jobs[job_id]):
                    self._num_lease_extensions += 1
                    obs.counter(
                        "scheduler_lease_extensions_total",
                        "round transitions where a job kept its exact "
                        "worker set",
                    ).inc()
            self._current_worker_assignments = scheduled_jobs
            self._round_log.append(
                {
                    "event": "round",
                    "round": self._num_completed_rounds,
                    "time": self._current_timestamp,
                    "jobs": {
                        str(job_id): len(worker_ids)
                        for job_id, worker_ids in scheduled_jobs.items()
                    },
                }
            )
            obs.counter(
                "scheduler_rounds_total", "scheduling rounds started"
            ).inc()
            obs.histogram(
                "scheduler_round_duration_seconds",
                "round length (simulated time in sim mode)",
            ).observe(self._time_per_iteration)
            obs.gauge(
                "scheduler_queue_depth", "active (incomplete) jobs"
            ).set(len(self._jobs))
            obs.gauge(
                "scheduler_scheduled_jobs", "jobs granted workers this round"
            ).set(len(scheduled_jobs))
            obs.complete(
                f"round {self._num_completed_rounds}",
                ts_s=self._current_timestamp,
                dur_s=self._time_per_iteration,
                cat="sched",
                tid="rounds",
                args={
                    "round": self._num_completed_rounds,
                    "scheduled_jobs": len(scheduled_jobs),
                    "active_jobs": len(self._jobs),
                },
            )
            self._round_observability(
                scheduled_jobs, preempted=preempted_this_round
            )

            dispatch_preview: dict = {}
            for job_id, worker_ids in scheduled_jobs.items():
                worker_type = self._worker_id_to_worker_type[worker_ids[0]]
                for wid in worker_ids:
                    self._available_worker_ids.discard(wid)
                all_num_steps, max_finish_time = self._get_job_steps_and_finish_times(
                    job_id, worker_type
                )
                for i, single in enumerate(job_id.singletons()):
                    # Exactly what the drain loop will merge for this
                    # micro-task: total steps per singleton, execution
                    # time = micro-task finish - round start.
                    dispatch_preview[single] = (
                        all_num_steps[i],
                        max_finish_time - self._current_timestamp,
                    )
                run_args = {
                    "round": self._num_completed_rounds,
                    "workers": len(worker_ids),
                    "worker_type": worker_type,
                }
                run_root = self._job_trace_ctx.get(
                    job_id.singletons()[0]
                )
                if run_root is not None:
                    # Sim runs are single-process, but the same causal
                    # chain args make spantree/merge_traces analyses
                    # work on sim traces unchanged.
                    run_args.update(run_root.child().args())
                obs.complete(
                    f"run job {job_id}",
                    ts_s=self._current_timestamp,
                    dur_s=max_finish_time - self._current_timestamp,
                    cat="job",
                    pid="cluster",
                    tid=f"job {job_id}",
                    args=run_args,
                )
                heapq.heappush(
                    running_jobs,
                    (
                        -max_finish_time,
                        job_id,
                        worker_ids,
                        all_num_steps,
                        self._current_timestamp,
                    ),
                )

            self._num_completed_rounds += 1

            # Plan-ahead pipelining: with this round's execution fully
            # determined, speculatively solve the NEXT round from a
            # snapshot + the predicted outcome. Inline here — solver
            # wall time never advances virtual time, so the overlap is
            # free by construction and the reconcile machinery runs
            # identically to physical mode.
            if self._shockwave_can_speculate():
                outcome = self._predict_round_outcome(dispatch_preview)
                if outcome is not None:
                    self._shockwave.speculate_next_round(outcome)

        self._logger.info(
            "Total duration: %.3f seconds (%.2f hours)",
            self._current_timestamp,
            self._current_timestamp / 3600.0,
        )
        return self._current_timestamp

    # ------------------------------------------------------------------
    # Simulator checkpointing (fast-forward for long continuous sweeps;
    # reference: scheduler.py:1214-1294, trigger :1759-1775).
    # ------------------------------------------------------------------
    _CHECKPOINT_FIELDS = [
        "_current_timestamp",
        "_num_completed_rounds",
        "_job_id_counter",
        "_jobs",
        "_completed_jobs",
        "_steps_run_so_far",
        "_total_steps_run",
        "_job_time_so_far",
        "_job_cost_so_far",
        "_job_total_run_time",
        "_throughputs",
        "_original_bs",
        "_bs_scale",
        "_job_id_to_job_type",
        "_job_type_to_job_ids",
        "_num_failures_per_job",
        "_per_job_start_timestamps",
        "_per_job_latest_timestamps",
        "_pool_ftf_scale",
        "_job_completion_times",
        "_job_priority_weights",
        "_num_jobs_in_trace",
        "_allocation",
        "_priorities",
        "_deficits",
        "_last_reset_time",
        "_worker_time_so_far",
        "_cumulative_worker_time_so_far",
        "_num_lease_extensions",
        "_num_lease_extension_opportunities",
        "_num_preemptions",
        "_completed_jobs",
        "_slos",
        "_in_progress_updates",
        "_fault_tainted",
        "_job_timelines",
        "_round_log",
        "_current_worker_assignments",
        "_available_worker_ids",
        # Loop-coupled state the checkpointed running_jobs heap depends
        # on: _done_callback credits steps only for singles present in
        # _running_jobs, so restored in-flight micro-tasks would complete
        # uncredited (and re-dispatch) without it; the allocation dirty
        # flag likewise steers the first post-resume round.
        "_running_jobs",
        "_need_to_update_allocation",
        # Shockwave round bridge: _shockwave_scheduler_update reads this
        # on the first post-resume round, so it must travel with the
        # planner state.
        "_current_round_scheduled_jobs",
    ]

    def save_checkpoint(self, path: str, extra: Optional[dict] = None) -> None:
        """Pickle scheduler state plus ``extra`` (the simulate-loop locals
        — queued/running jobs — mirroring reference scheduler.py:1214-1245
        which checkpoints those alongside the 24 scheduler fields).

        Unlike the reference — whose checkpoint silently OMITS its
        Shockwave planner state (reference scheduler.py:1214-1294), so a
        resumed Shockwave run would replan from amnesia — the planner
        (round cursor, plan cache, predictor metadata, finish-time
        history) is serialized alongside, as plain dicts/arrays via
        ShockwavePlanner.state_dict()."""
        import pickle

        state = {f: getattr(self, f) for f in self._CHECKPOINT_FIELDS}
        shockwave_state = (
            self._shockwave.state_dict() if self._shockwave is not None else None
        )
        with open(path, "wb") as f:
            pickle.dump(
                {
                    "fields": state,
                    "extra": extra or {},
                    "shockwave": shockwave_state,
                },
                f,
            )

    def load_checkpoint(self, path: str) -> dict:
        """Restore scheduler fields (and planner state, if the checkpoint
        carries any); returns the ``extra`` dict."""
        import pickle

        from shockwave_tpu.policies.shockwave import planner_from_state

        with open(path, "rb") as f:
            state = pickle.load(f)
        for field, value in state["fields"].items():
            setattr(self, field, value)
        shockwave_state = state.get("shockwave")
        if shockwave_state is not None:
            assert self._shockwave is not None, (
                "checkpoint carries Shockwave planner state but the "
                "resuming scheduler's policy is not Shockwave"
            )
            self._shockwave = planner_from_state(shockwave_state)
        else:
            # The converse must fail loudly too: resuming a Shockwave run
            # from a planner-less checkpoint (pre-round-4 format, or one
            # saved by a different policy) would silently drive an
            # amnesiac planner.
            assert self._shockwave is None, (
                "Shockwave scheduler resuming from a checkpoint without "
                "planner state"
            )
        return state["extra"]

    # ------------------------------------------------------------------
    # HA control-plane state (shockwave_tpu/ha/): the JSON-codec
    # counterpart of save_checkpoint — everything a hot-standby or
    # restarted scheduler needs to resume mid-round, expressed entirely
    # in structures the flight-recorder codec round-trips exactly.
    # ------------------------------------------------------------------
    # Directly encodable fields (scalars, dicts, lists, tuples, numpy
    # arrays, JobId keys). Sets travel separately so restore can coerce
    # them back (the codec decodes a set as a list).
    _HA_STATE_FIELDS = [
        # clock / cursors
        "_current_timestamp", "_num_completed_rounds", "_job_id_counter",
        "_num_jobs_in_trace", "_need_to_update_allocation",
        "_last_reset_time", "_num_lease_extensions",
        "_num_lease_extension_opportunities", "_num_preemptions",
        # per-job accounting
        "_steps_run_so_far", "_total_steps_run", "_job_time_so_far",
        "_job_cost_so_far", "_job_total_run_time", "_throughputs",
        "_original_bs", "_bs_scale", "_job_id_to_job_type",
        "_job_type_to_job_ids",
        "_num_failures_per_job", "_per_job_start_timestamps",
        "_per_job_latest_timestamps", "_pool_ftf_scale",
        "_job_completion_times", "_job_priority_weights", "_slos",
        "_in_progress_updates", "_job_timelines", "_round_log",
        "_current_worker_assignments", "_current_round_scheduled_jobs",
        # allocation state
        "_allocation", "_priorities", "_deficits",
        # worker registry (a successor restores the registry so
        # re-attaching workers slot back into their old ids)
        "_worker_id_counter", "_worker_ids", "_worker_types",
        "_cluster_spec", "_worker_id_to_worker_type",
        "_worker_type_to_worker_ids", "_worker_start_times",
        "_cumulative_worker_time_so_far", "_worker_time_so_far",
    ]
    _HA_SET_FIELDS = (
        "_completed_jobs", "_fault_tainted", "_available_worker_ids",
        "_running_jobs",
    )
    # Scheduling decisions sample these; a resumed run diverges without
    # their exact positions (random.Random.getstate round-trips as a
    # tuple of ints).
    _HA_RNG_FIELDS = (
        "_job_generator", "_interarrival_time_generator",
        "_worker_type_shuffler", "_slo_generator",
    )

    def ha_state_dict(self) -> dict:
        """Full control-plane snapshot as recorder-codec-encodable
        structures — the payload of one HA journal checkpoint. The
        physical scheduler extends this with its runtime-only state
        (outstanding micro-tasks, lease/incumbency maps, the
        admission-token ledger, the round cursor)."""
        from shockwave_tpu.ha import codec as ha_codec

        state = {
            "schema": "shockwave-ha-state-v1",
            "fields": {
                f: getattr(self, f) for f in self._HA_STATE_FIELDS
            },
            "sets": {f: getattr(self, f) for f in self._HA_SET_FIELDS},
            "jobs": OrderedDict(
                (job_id, ha_codec.job_state(job))
                for job_id, job in self._jobs.items()
            ),
            "profiles": self._profiles,
            "rng": {
                name: getattr(self, name).getstate()
                for name in self._HA_RNG_FIELDS
            },
        }
        planner_state = ha_codec.planner_state_or_none(self)
        if planner_state is not None:
            state["planner"] = planner_state
        return state

    def restore_ha_state(self, state: dict) -> None:
        """Install a decoded :meth:`ha_state_dict` snapshot. The
        scheduler must be freshly constructed with the same policy and
        configuration (policy/config are deployment facts, not journal
        state)."""
        from shockwave_tpu.ha import codec as ha_codec

        fields = state["fields"]
        for f in self._HA_STATE_FIELDS:
            if f in fields:
                setattr(self, f, fields[f])
        for f in self._HA_SET_FIELDS:
            if f in state["sets"]:
                setattr(self, f, set(state["sets"][f]))
        # Set-valued dict: decode() yields lists for the inner sets.
        self._job_type_to_job_ids = {
            key: set(ids)
            for key, ids in fields.get(
                "_job_type_to_job_ids", self._job_type_to_job_ids
            ).items()
        }
        self._jobs = OrderedDict(
            (job_id, ha_codec.job_from_state(job_fields))
            for job_id, job_fields in state["jobs"].items()
        )
        self._profiles = dict(state.get("profiles") or {})
        for name, rng_state in (state.get("rng") or {}).items():
            if name in self._HA_RNG_FIELDS:
                getattr(self, name).setstate(rng_state)
        planner_state = state.get("planner")
        if planner_state is not None:
            from shockwave_tpu.policies.shockwave import planner_from_state

            # The snapshot's own recompute_flag is restored verbatim:
            # the simulator's crash/restart roundtrip must leave the
            # run bit-identical. The PHYSICAL restore (a real failover,
            # where the fleet may have changed under the outage) forces
            # a replan on top — see PhysicalScheduler.restore_ha_state.
            self._shockwave = planner_from_state(planner_state)

    def _sim_scheduler_restart_roundtrip(
        self, running_jobs, queued_jobs=None
    ) -> dict:
        """Simulation's ``scheduler_crash``/``scheduler_restart``: push
        the ENTIRE control plane (scheduler + planner + the simulate
        loop's running/queued job state) through the HA journal codec —
        capture, JSON-serialize, decode, restore in place — exactly the
        transformation a real failover replays from disk. Returns the
        bit-exactness verdict for the fault record; the run continuing
        bit-identically is the standing proof the checkpoint captures
        every behavior-relevant field."""
        import heapq as _heapq

        from shockwave_tpu.ha import codec as ha_codec

        state = self.ha_state_dict()
        fp_before = ha_codec.state_fingerprint(state)
        state["sim_loop"] = {
            "running_jobs": [tuple(entry) for entry in running_jobs],
            "queued_jobs": (
                [
                    (arrival, ha_codec.job_state(job))
                    for arrival, job in queued_jobs
                ]
                if queued_jobs is not None
                else None
            ),
        }
        restored = ha_codec.json_roundtrip(state)
        self.restore_ha_state(restored)
        loop_state = restored.get("sim_loop") or {}
        running_jobs[:] = [
            tuple(entry) for entry in loop_state.get("running_jobs") or []
        ]
        _heapq.heapify(running_jobs)
        if (
            queued_jobs is not None
            and loop_state.get("queued_jobs") is not None
        ):
            queued_jobs[:] = [
                (arrival, ha_codec.job_from_state(job_fields))
                for arrival, job_fields in loop_state["queued_jobs"]
            ]
        fp_after = ha_codec.state_fingerprint(self.ha_state_dict())
        return {
            "state_sha": fp_before[:16],
            "roundtrip_exact": fp_before == fp_after,
        }

    def save_round_log(self, path: str) -> None:
        """Write the structured event log (job / round / complete events)
        as JSON lines, for scripts/analysis/postprocess_log.py. Written
        atomically (temp file + rename) so a killed run can't leave a
        truncated log behind."""
        import json

        from shockwave_tpu.utils.fileio import atomic_write_text

        atomic_write_text(
            path,
            "".join(json.dumps(record) + "\n" for record in self._round_log),
        )

    def save_job_timelines(self, directory: str) -> None:
        """One per-job file of structured iterator log excerpts, each
        written atomically (reference: scheduler.py:2267-2284)."""
        import os

        from shockwave_tpu.utils.fileio import atomic_write_text

        os.makedirs(directory, exist_ok=True)
        for job_id, timelines in self._job_timelines.items():
            atomic_write_text(
                os.path.join(directory, f"job_{job_id.integer}.log"),
                "".join(
                    f"[rank {rank}] {line}\n"
                    for rank, lines in enumerate(timelines)
                    for line in lines
                ),
            )

    # ------------------------------------------------------------------
    # Metrics.
    # ------------------------------------------------------------------
    def get_average_jct(self, job_ids=None, verbose: bool = False):
        """(reference: scheduler.py:2131-2189)"""
        if len(self._job_completion_times) == 0:
            return None
        if job_ids is None:
            job_ids = sorted(self._job_completion_times.keys())
        else:
            job_ids = sorted(job_ids)
        times = [
            self._job_completion_times[j]
            for j in job_ids
            if self._job_completion_times.get(j) is not None
        ]
        if not times:
            return None
        avg = float(np.mean(times))
        if verbose:
            print(
                "Average job completion time: %.3f seconds (%.2f hours)"
                % (avg, avg / 3600.0)
            )
        return avg

    def get_cluster_utilization(self):
        """(reference: scheduler.py:2202-2220)"""
        utilizations = []
        for worker_id, worker_time in self._cumulative_worker_time_so_far.items():
            total = self._current_timestamp - self._worker_start_times[worker_id]
            if total <= 0:
                continue
            utilization = worker_time / total
            if utilization > 1.0 and not self._job_packing:
                return None
            utilizations.append(utilization)
        if not utilizations:
            return None
        return float(np.mean(utilizations))

    def _finish_time_rho(self, job_id: JobId, jct: float):
        """rho = JCT / (isolated duration x contention factor) — THE
        finish-time-fairness definition, shared by the summary getter
        and the live completion telemetry so the two can never drift.
        None when the job has no profile (no isolated baseline)."""
        profile = self._profiles.get(job_id.integer)
        if profile is None:
            return None
        # Per-pool isolated baseline: the profile durations are
        # synthesized against the base (fastest-profiled) type; a job
        # admitted to a slower pool runs — isolated or contended — at
        # that pool's speed, so its baseline is rescaled by the same
        # factor its planner profile was (VERDICT r05 #5).
        isolated = sum(profile["duration_every_epoch"]) * (
            self._pool_ftf_scale.get(job_id.integer, 1.0)
        )
        if isolated <= 0:
            return None
        contention = max(
            1.0, self._num_jobs_in_trace / max(1, len(self._worker_ids))
        )
        return jct / (isolated * contention)

    def get_finish_time_fairness(self, job_ids=None):
        """rho per completed job; also the fraction of jobs with
        rho > 1.1 (reference: scheduler.py:3627-3655). ``job_ids``
        restricts to a measurement window (continuous sweeps exclude the
        warmup/tail jobs from every metric, not just JCT)."""
        if len(self._job_completion_times) == 0:
            return [], 0.0
        ftf_list = []
        for job_id in sorted(self._job_completion_times.keys()):
            if job_ids is not None and job_id not in job_ids:
                continue
            jct = self._job_completion_times[job_id]
            if jct is None:
                continue
            rho = self._finish_time_rho(job_id, jct)
            if rho is None:
                continue
            ftf_list.append(round(rho, 3))
        if not ftf_list:
            return [], 0.0
        unfair_fraction = 100.0 * sum(f > 1.1 for f in ftf_list) / len(ftf_list)
        return ftf_list, unfair_fraction

    def get_completed_steps(self, job_ids=None):
        if job_ids is None:
            job_ids = sorted(self._total_steps_run.keys())
        return {j: self._total_steps_run[j] for j in job_ids if j in self._total_steps_run}

    def get_num_preemptions(self):
        """Count of round transitions where a still-active job lost its
        workers (unscheduled or moved) — each one is a checkpoint/relaunch
        in physical mode."""
        return self._num_preemptions

    def get_num_lease_extensions(self):
        """(reference: scheduler.py:2248-2265)"""
        if self._num_lease_extension_opportunities > 0:
            return (
                100.0
                * self._num_lease_extensions
                / self._num_lease_extension_opportunities
            )
        return 0.0

    def get_total_cost(self):
        return float(sum(self._job_cost_so_far.values()))

    def get_num_SLO_violations(self, verbose: bool = False):
        """Jobs that finished past their absolute deadline, or never
        finished at all (reference: scheduler.py:2230-2246 — note the
        reference compares the completion *duration* against the absolute
        deadline, a latent bug once arrivals are nonzero; here the job's
        absolute finish timestamp is compared)."""
        violations = 0
        for job_id, deadline in (self._slos or {}).items():
            if job_id in self._jobs:
                continue  # still running: not yet decided
            finished_at = self._per_job_latest_timestamps.get(job_id)
            completed = self._job_completion_times.get(job_id) is not None
            violated = (not completed) or finished_at > deadline
            if verbose:
                self._logger.info(
                    "%s: finished_at=%s, deadline=%f, violated=%s",
                    job_id, finished_at, deadline, violated,
                )
            violations += int(violated)
        return violations
